"""End-to-end drive: LeNet on MNIST through the public API.

Builds the BASELINE config #1 network, trains 2 epochs on the bundled
(synthetic-fallback) MNIST, asserts accuracy, round-trips a checkpoint, and
exercises the stateful RNN inference path on a small LSTM.
"""

import os
import sys
import tempfile

import numpy as np


def main():
    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    print("devices:", jax.devices())

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              SubsamplingLayer, DenseLayer,
                                              OutputLayer)
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.data.fetchers import MnistDataSetIterator

    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(Adam(1e-3))
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=5, stride=1,
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
            .layer(ConvolutionLayer(n_out=50, kernel_size=5, stride=1,
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()

    # the synthetic-fallback MNIST is deliberately non-trivial (~98% Bayes
    # ceiling: overlapping smooth class templates + 1% label noise), so a
    # few epochs land mid-90s rather than a meaningless 100
    train = MnistDataSetIterator(128, train=True, num_examples=6400,
                                 flatten=False)
    test = MnistDataSetIterator(256, train=False, num_examples=1024,
                                flatten=False)
    net.fit(train, epochs=6)
    ev = net.evaluate(test)
    acc = ev.accuracy()
    print(f"accuracy after 6 epochs: {acc:.4f}")
    assert acc > 0.85, f"accuracy {acc} too low"

    # checkpoint round-trip
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "lenet.zip")
        net.save(p)
        net2 = MultiLayerNetwork.load(p)
        x = np.random.RandomState(0).rand(4, 28, 28, 1).astype(np.float32)
        o1, o2 = np.asarray(net.output(x)), np.asarray(net2.output(x))
        assert np.allclose(o1, o2, atol=1e-6), "save/load output mismatch"
    print("checkpoint round-trip: OK")

    # error-path probes
    try:
        (NeuralNetConfiguration.builder().list()
         .layer(DenseLayer(n_out=4, activation="not_an_act"))
         .layer(OutputLayer(n_out=2, loss="mcxent"))
         .set_input_type(InputType.feed_forward(3)).build())
        MultiLayerNetwork(_ := None)
    except Exception as e:
        print(f"bad activation raised: {type(e).__name__}: {e}")

    try:
        conf_bad = (NeuralNetConfiguration.builder().list()
                    .layer(DenseLayer(n_out=4))
                    .layer(OutputLayer(n_out=2, loss="mcxent"))
                    .build())
        MultiLayerNetwork(conf_bad).init()
        raise AssertionError("expected error for missing n_in/input type")
    except AssertionError:
        raise
    except Exception as e:
        print(f"missing input type raised: {type(e).__name__}")

    # stateful rnn inference
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    rconf = (NeuralNetConfiguration.builder()
             .seed(1).updater(Adam(1e-3)).list()
             .layer(LSTM(n_out=8, activation="tanh"))
             .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
             .set_input_type(InputType.recurrent(5)).build())
    rnet = MultiLayerNetwork(rconf).init()
    xt = np.random.RandomState(1).rand(2, 1, 5).astype(np.float32)
    o1 = np.asarray(rnet.rnn_time_step(xt))
    o2 = np.asarray(rnet.rnn_time_step(xt))
    assert not np.allclose(o1, o2), "rnn_time_step not stateful"
    rnet.rnn_clear_previous_state()
    o3 = np.asarray(rnet.rnn_time_step(xt))
    assert np.allclose(o1, o3, atol=1e-6), "state clear broken"
    print("rnn_time_step statefulness: OK")
    print("VERIFY PASS")


if __name__ == "__main__":
    main()
