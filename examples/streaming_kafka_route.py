"""End-to-end drive: broker route → streaming iterator → training.

A producer publishes NDArray records onto a topic (in-memory broker here;
swap ``default_client()`` for a real Kafka deployment), the pub/sub route
pumps them into the bounded-buffer streaming iterator, and plain
``MultiLayerNetwork.fit`` consumes them — the dl4j-streaming ingest shape,
TPU-native.
"""

import os
import threading
import time

import numpy as np


def main():
    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.data.kafka import (InMemoryBroker,
                                               NDArrayPublisher,
                                               NDArrayPubSubRoute)
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam

    broker = InMemoryBroker()
    route = NDArrayPubSubRoute(broker, "train-topic", batch_size=32).start()

    def producer():
        pub = NDArrayPublisher(broker, "train-topic")
        rs = np.random.RandomState(0)
        for _ in range(512):
            x = rs.randn(8).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[int(x.sum() > 0)]
            pub.publish(x, y)
        # let the pump drain the topic, then end the stream so fit() stops
        while broker.pending("train-topic"):
            time.sleep(0.01)
        route.stop()

    t = threading.Thread(target=producer)
    t.start()

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-2)).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(route.iterator)          # consumes until the stream ends
    t.join()
    print(f"trained from the stream: {net.iteration} iterations, "
          f"final score {net.get_score():.4f}")
    assert net.iteration > 0 and np.isfinite(net.get_score())
    print("STREAMING ROUTE PASS")


if __name__ == "__main__":
    main()
