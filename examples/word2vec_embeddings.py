"""Word2Vec skip-gram embeddings + nearest words + t-SNE visualization in
the web UI (the reference's Word2Vec.Builder → words-nearest → UI workflow).

Run: PYTHONPATH=/root/repo python examples/word2vec_embeddings.py
"""


def main():
    import numpy as np
    from deeplearning4j_tpu.nlp import Word2Vec
    from deeplearning4j_tpu.plot.tsne import BarnesHutTsne
    from deeplearning4j_tpu.ui import UIServer

    animals = "cat dog kitten puppy pet fur paw tail".split()
    finance = "stock market trade price share profit bank fund".split()
    rs = np.random.RandomState(0)
    sentences = []
    for _ in range(400):
        topic = animals if rs.rand() < 0.5 else finance
        sentences.append(" ".join(rs.choice(topic, size=8)))

    w2v = Word2Vec(min_word_frequency=5, layer_size=32, window_size=4,
                   negative=5, epochs=3, seed=1, sentences=sentences,
                   subsampling=0).fit()
    print("nearest to 'cat':  ", w2v.words_nearest("cat", 4))
    print("nearest to 'stock':", w2v.words_nearest("stock", 4))

    words = [w for w in animals + finance if w2v.has_word(w)]
    vecs = np.stack([w2v.word_vector(w) for w in words])
    emb = BarnesHutTsne(max_iter=120, perplexity=5).fit_transform(vecs)

    ui = UIServer.get_instance(port=0)
    ui.upload_tsne("word2vec", emb, labels=words)
    print(f"t-SNE view: http://127.0.0.1:{ui.port}/tsne  (ctrl-c to exit)")
    import os
    if os.environ.get("DL4J_TPU_EXAMPLE_NONBLOCKING") != "1":
        try:
            import threading
            threading.Event().wait()        # keep the UI server reachable
        except KeyboardInterrupt:
            pass


if __name__ == "__main__":
    main()
