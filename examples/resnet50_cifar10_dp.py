"""Data-parallel ResNet50 on CIFAR-10 through ParallelWrapper — the
reference's flagship multi-device workflow (ParallelWrapper.Builder over a
zoo ComputationGraph), TPU-native: one pjit-sharded train step, XLA emits
the gradient all-reduce over ICI.

Run (8 virtual devices on CPU):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
  PYTHONPATH=/root/repo python examples/resnet50_cifar10_dp.py
"""

import numpy as np


def main():
    import jax
    from deeplearning4j_tpu.zoo.resnet import ResNet50
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper, default_mesh
    from deeplearning4j_tpu.data.fetchers import load_cifar10
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener

    mesh = default_mesh()
    n = mesh.devices.size
    print(f"mesh: {n} x {jax.devices()[0].device_kind}")

    from deeplearning4j_tpu.nn.updaters import Adam
    # the zoo default updater (Nesterov 0.1, reference parity) needs
    # warmup+decay for from-scratch runs; override it for this short demo
    cg = ResNet50(num_classes=10, input_shape=(32, 32, 3), seed=7,
                  updater=Adam(1e-3)).init()
    storage = InMemoryStatsStorage()
    cg.set_listeners(StatsListener(storage, session_id="resnet50"))

    x, y = load_cifar10(train=True, num_examples=64 * n)
    pw = ParallelWrapper(cg, mesh=mesh, averaging_frequency=1)
    pw.fit(ListDataSetIterator(DataSet(x, y), 16 * n), epochs=10)
    print(f"loss after 10 epochs: {cg.get_score():.4f}")

    ev = cg.evaluate([DataSet(x[:128], y[:128])])
    print(f"train-subset accuracy: {ev.accuracy():.3f}")
    print(f"collected {len(storage.get_all_updates('resnet50'))} stats reports")


if __name__ == "__main__":
    main()
