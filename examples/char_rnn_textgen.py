"""Char-RNN text generation: train TextGenerationLSTM, sample with
rnn_time_step (the reference zoo TextGenerationLSTM workflow; LSTM layers
route through the fused Pallas kernel on TPU).

Run: PYTHONPATH=/root/repo python examples/char_rnn_textgen.py
"""

import numpy as np

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. ") * 120


def main():
    import jax.numpy as jnp
    from deeplearning4j_tpu.zoo.simple import TextGenerationLSTM

    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    V = len(chars)
    ids = np.array([idx[c] for c in TEXT], np.int32)

    T, B = 64, 32
    n = (len(ids) - 1) // T
    xs = np.eye(V, dtype=np.float32)[ids[:n * T].reshape(n, T)]
    ys = np.eye(V, dtype=np.float32)[ids[1:n * T + 1].reshape(n, T)]

    net = TextGenerationLSTM(total_unique_characters=V).init()
    steps = 0
    for epoch in range(12):
        order = np.random.RandomState(epoch).permutation(n)
        for s in range(0, n - B + 1, B):
            sel = order[s:s + B]
            net.fit(jnp.asarray(xs[sel]), jnp.asarray(ys[sel]))
            steps += 1
    print(f"trained {steps} steps, final loss {net.get_score():.4f}")

    # stream a sample through the stored-state path (rnnTimeStep parity)
    net.rnn_clear_previous_state()
    ch = idx["t"]
    out = ["t"]
    rng = np.random.RandomState(0)
    for _ in range(120):
        x = np.zeros((1, V), np.float32)
        x[0, ch] = 1.0
        p = np.asarray(net.rnn_time_step(x))[0, -1].astype(np.float64)
        p /= p.sum()
        ch = int(rng.choice(V, p=p))
        out.append(chars[ch])
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
