"""Test config: force CPU with 8 virtual devices so sharding/multi-chip tests
run anywhere, fast and deterministically (parity with the reference's
`local[N]` Spark test masters — SURVEY.md §4 'distributed tests without a
real cluster').

The environment pins JAX_PLATFORMS to the axon TPU tunnel; tests must NOT
claim the real TPU chip (it is a single shared grant used by the benchmark
driver, and a wedged tunnel would hang the whole suite). We both force the
platform env var and drop the axon PJRT factory if it was registered by the
image's sitecustomize before jax initializes any backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    import jax._src.xla_bridge as _xb

    # sitecustomize imported jax at interpreter start with JAX_PLATFORMS=axon
    # already baked into the config default — override it explicitly.
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import pytest

# In-memory XLA executable memo, shared across the whole suite run. The
# suite compiles the same tiny programs hundreds of times — every
# DecodeEngine/InferenceEngine builds fresh closures, so jax's jaxpr-level
# jit cache never hits, but the lowered HLO is identical. Memoizing
# compile_or_get_cached on jax's own content-addressed cache key returns
# the already-loaded executable for a repeat compile. This deliberately
# does NOT use the persistent disk cache: on this jaxlib's CPU backend,
# deserializing a cached executable whose twin is already loaded in the
# same process corrupts the heap (the same symbol-registry defect that
# makes cache-loaded CPU executables unserializable — see
# exec/aot.py::export_compiled), and one pytest process re-compiling a
# program it already holds is exactly that case. Compile accounting is
# unaffected: every counter in the tree counts python-level TRACES, which
# still happen per fresh closure. The memo key includes the current
# jax_compilation_cache_dir so tests that point the config at their own
# DL4JTPU_JAX_CACHE dirs (AOT cold-start arms) keep their compile
# isolation; pytest_runtest_teardown pins the dir back off afterwards so
# a leaked dir can never feed disk-cached executables to a later test.
_COMPILE_MEMO = {}


def _install_compile_memo():
    import threading

    from jax._src import compilation_cache as _cc
    from jax._src import compiler as _compiler

    orig = _compiler.compile_or_get_cached
    lock = threading.Lock()

    def memoized(backend, computation, devices, compile_options,
                 host_callbacks, *a, **kw):
        if getattr(backend, "platform", None) != "cpu" or host_callbacks:
            return orig(backend, computation, devices, compile_options,
                        host_callbacks, *a, **kw)
        try:
            key = (_cc.get_cache_key(computation, devices, compile_options,
                                     backend),
                   jax.config.jax_compilation_cache_dir)
        except Exception:
            return orig(backend, computation, devices, compile_options,
                        host_callbacks, *a, **kw)
        with lock:
            hit = _COMPILE_MEMO.get(key)
        if hit is not None:
            return hit
        exe = orig(backend, computation, devices, compile_options,
                   host_callbacks, *a, **kw)
        with lock:
            return _COMPILE_MEMO.setdefault(key, exe)

    _compiler.compile_or_get_cached = memoized


try:
    if not os.environ.get("DL4JTPU_TEST_NO_COMPILE_CACHE"):
        _install_compile_memo()
except Exception:
    pass


def pytest_runtest_teardown(item, nextitem):
    try:
        import jax as _jax
        if _jax.config.jax_compilation_cache_dir is not None:
            _jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh8: needs 8 devices (the forced host-device count above; "
        "skipped automatically when the process sees fewer)")


def pytest_runtest_setup(item):
    if item.get_closest_marker("mesh8") is not None:
        import jax as _jax
        if len(_jax.devices()) < 8:
            pytest.skip("needs 8 devices "
                        "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")


@pytest.fixture
def mesh8():
    """Subprocess environment with 8 virtual CPU devices. The host-device
    flag only takes effect before jax initializes, so tests that need a
    DIFFERENT device count than this process (or a clean jax) must spawn a
    child with this env rather than mutate XLA_FLAGS in place."""
    from deeplearning4j_tpu.exec import host_device_env
    return host_device_env(8)
