"""Test config: force CPU with 8 virtual devices so sharding/multi-chip tests
run anywhere, fast and deterministically (parity with the reference's
`local[N]` Spark test masters — SURVEY.md §4 'distributed tests without a
real cluster').

The environment pins JAX_PLATFORMS to the axon TPU tunnel; tests must NOT
claim the real TPU chip (it is a single shared grant used by the benchmark
driver, and a wedged tunnel would hang the whole suite). We both force the
platform env var and drop the axon PJRT factory if it was registered by the
image's sitecustomize before jax initializes any backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax
    import jax._src.xla_bridge as _xb

    # sitecustomize imported jax at interpreter start with JAX_PLATFORMS=axon
    # already baked into the config default — override it explicitly.
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "mesh8: needs 8 devices (the forced host-device count above; "
        "skipped automatically when the process sees fewer)")


def pytest_runtest_setup(item):
    if item.get_closest_marker("mesh8") is not None:
        import jax as _jax
        if len(_jax.devices()) < 8:
            pytest.skip("needs 8 devices "
                        "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")


@pytest.fixture
def mesh8():
    """Subprocess environment with 8 virtual CPU devices. The host-device
    flag only takes effect before jax initializes, so tests that need a
    DIFFERENT device count than this process (or a clean jax) must spawn a
    child with this env rather than mutate XLA_FLAGS in place."""
    from deeplearning4j_tpu.exec import host_device_env
    return host_device_env(8)
