"""Clustering/KNN/t-SNE tests (parity role: nearestneighbor-core + plot tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    VPTree, KDTree, SpTree, QuadTree, KMeansClustering, NearestNeighbors,
)
from deeplearning4j_tpu.plot import Tsne, BarnesHutTsne


def _blobs(n_per=50, d=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[0] * d, [10] + [0] * (d - 1), [0, 10] + [0] * (d - 2)],
                       np.float64)
    pts = np.concatenate([c + rng.randn(n_per, d) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return pts, labels


def _brute_knn(pts, q, k):
    d = np.sqrt(((pts - q) ** 2).sum(1))
    order = np.argsort(d)[:k]
    return list(order), list(d[order])


def test_vptree_exact():
    pts, _ = _blobs()
    tree = VPTree(pts)
    q = pts[7] + 0.01
    idx, dist = tree.knn(q, 5)
    bidx, bdist = _brute_knn(pts, q, 5)
    assert set(idx) == set(bidx)
    assert np.allclose(sorted(dist), sorted(bdist), atol=1e-9)


def test_vptree_cosine():
    pts, _ = _blobs(seed=3)
    tree = VPTree(pts, distance="cosine")
    idx, dist = tree.knn(pts[0], 3)
    normed = pts / np.linalg.norm(pts, axis=1, keepdims=True)
    bd = 1 - normed @ (pts[0] / np.linalg.norm(pts[0]))
    assert set(idx) == set(np.argsort(bd)[:3])


def test_kdtree_exact():
    pts, _ = _blobs(seed=1)
    tree = KDTree(pts)
    q = pts[33] + 0.05
    idx, dist = tree.knn(q, 4)
    bidx, _ = _brute_knn(pts, q, 4)
    assert set(idx) == set(bidx)


def test_device_knn_matches_brute():
    pts, _ = _blobs(seed=2)
    nn = NearestNeighbors(pts)
    idx, dist = nn.knn(pts[:10], 6)
    for qi in range(10):
        bidx, bdist = _brute_knn(pts.astype(np.float32), pts[qi].astype(np.float32), 6)
        assert set(idx[qi]) == set(bidx)
        assert np.allclose(sorted(dist[qi]), sorted(bdist), atol=1e-3)


def test_kmeans_recovers_blobs():
    pts, labels = _blobs(n_per=60, seed=4)
    km = KMeansClustering(k=3, seed=5).apply_to(pts)
    assert km.centroids.shape == (3, 4)
    # each true cluster maps to one kmeans cluster almost purely
    for c in range(3):
        assign = km.assignments[labels == c]
        dominant = np.bincount(assign).max()
        assert dominant / len(assign) > 0.95
    pred = km.predict(pts[:5])
    assert pred.shape == (5,)


def test_quadtree_and_sptree():
    pts2 = _blobs(n_per=30, d=2, seed=6)[0]
    qt = QuadTree(pts2)
    assert qt.root.count == len(pts2)
    st = SpTree(pts2)
    neg, sum_q = st.compute_non_edge_forces(pts2[0], theta=0.5)
    assert neg.shape == (2,)
    assert sum_q > 0


def test_tsne_separates_blobs():
    pts, labels = _blobs(n_per=30, seed=7)
    emb = Tsne(perplexity=10, max_iter=250, seed=1).fit(pts)
    assert emb.shape == (90, 2)
    # cluster centroid distances in embedding >> intra-cluster spread
    cents = np.stack([emb[labels == c].mean(0) for c in range(3)])
    spread = np.mean([emb[labels == c].std() for c in range(3)])
    min_sep = np.inf
    for i in range(3):
        for j in range(i + 1, 3):
            min_sep = min(min_sep, np.linalg.norm(cents[i] - cents[j]))
    assert min_sep > 2 * spread


@pytest.mark.slow
def test_barnes_hut_tsne_runs():
    pts, _ = _blobs(n_per=20, seed=8)
    emb = BarnesHutTsne(theta=0.5, max_iter=60, seed=1).fit(pts)
    assert emb.shape == (60, 2)
    assert np.isfinite(emb).all()
