"""Training-perf seams (ISSUE 11): fused optimizer update, train-precision
policy, flash-attention training route, grad-phase measurement routing,
and the autotune persist→consult roundtrip.

The load-bearing claims pinned here:
- the fused grad→update→apply program (nn/fused_update.py) is BITWISE
  equal to the per-leaf optax chain it replaces — for SGD/Nesterov/Adam,
  with elementwise clipping and iteration-indexed LR schedules, for both
  params and opt state, over multiple steps;
- ``apply_external_updates`` compiles exactly ONE program per (model,
  updater), registers it in the /programs registry, and donates params +
  opt state (old buffers die, new outputs reuse them);
- the bf16 train-precision policy keeps stored params f32, pins the loss
  trajectory within tolerance of f32, composes with remat='selective',
  and leaves inference untouched;
- the attention layer seam routes the TRAINING forward through the same
  decision as inference (train=True asks for both phases) and the flash
  kernel's gradients match the dense path at pinned tolerance;
- every KERNELS_TPU.json row with grad data routes the backward by its
  measurement (the fwd-only version of this regression lives in
  tests/test_exec.py); the scan backward is numerically equal to the
  Pallas backward it stands in for;
- a persisted autotune table is consulted for at least one fwd and one
  grad route after a cache reset;
- tensor-parallel callers bypass the fused path (raveling row- and
  column-sharded leaves would gather every shard).
"""

import json
import os
from types import SimpleNamespace

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration, ops
from deeplearning4j_tpu import exec as ex
from deeplearning4j_tpu.exec import routing
from deeplearning4j_tpu.nn import fused_update as fu
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs, Schedule, Sgd

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


def _assert_bitwise(a, b, what=""):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype, what
        assert (np.asarray(x) == np.asarray(y)).all(), what


# --------------------------------------------------- standalone fused update

class TestFusedUpdateParity:
    """build_fused_update vs the per-member optax loop, bitwise."""

    def _params(self):
        ks = jax.random.split(jax.random.PRNGKey(0), 5)
        return {
            "l0": {"W": jax.random.normal(ks[0], (6, 8)),
                   "b": jnp.zeros((8,))},
            "l1": {"W": jax.random.normal(ks[1], (8, 8)),
                   "b": jax.random.normal(ks[2], (8,))},
            "l2": {"W": jax.random.normal(ks[3], (8, 3)),
                   "b": jax.random.normal(ks[4], (3,))},
        }

    def _grads(self, params, step):
        return jax.tree_util.tree_map(
            lambda p: jnp.cos(p * (step + 1)) * 0.7, params)

    @pytest.mark.parametrize("make_tx", [
        lambda: optax.sgd(0.05),
        lambda: Nesterovs(0.05).to_optax(),
        lambda: Adam(1e-2, schedule=Schedule(
            kind="exponential", initial=1e-2, decay_rate=0.95)).to_optax(),
        # elementwise clipping composes into the flat program
        lambda: optax.chain(optax.clip(0.5),
                            optax.add_decayed_weights(1e-4),
                            Adam(1e-2).to_optax()),
    ], ids=["sgd", "nesterov", "adam-exp-schedule", "clip-wd-adam"])
    def test_bitwise_over_steps(self, make_tx):
        params = self._params()
        transforms = {k: make_tx() for k in params}
        group_keys = {k: "same" for k in params}
        fused = fu.build_fused_update(params, transforms, group_keys)
        assert fused.fused_keys, "expected the group to actually fuse"

        ref_p = dict(params)
        ref_o = {k: transforms[k].init(ref_p[k]) for k in params}
        fus_p = dict(params)
        fus_o = {k: transforms[k].init(fus_p[k]) for k in params}
        for step in range(3):
            grads = self._grads(ref_p, step)
            for k in params:
                u, o = transforms[k].update(grads[k], ref_o[k], ref_p[k])
                ref_p[k] = optax.apply_updates(ref_p[k], u)
                ref_o[k] = o
            fus_p, fus_o = fused.apply(fus_p, fus_o, grads)
            _assert_bitwise(fus_p, ref_p, f"params step {step}")
            _assert_bitwise(fus_o, ref_o, f"opt state step {step}")

    def test_global_norm_clip_falls_back(self):
        # clip_by_global_norm reduces ACROSS leaves — concatenating members
        # would change its norm, so such groups must not fuse
        params = self._params()
        transforms = {k: optax.chain(optax.clip_by_global_norm(1.0),
                                     optax.sgd(0.1)) for k in params}
        fused = fu.build_fused_update(params, transforms,
                                      {k: None for k in params})
        assert not fused.fused_keys
        grads = self._grads(params, 0)
        ref = {k: optax.apply_updates(
            params[k], transforms[k].update(
                grads[k], transforms[k].init(params[k]), params[k])[0])
            for k in params}
        got, _ = fused.apply(params,
                             {k: transforms[k].init(params[k])
                              for k in params}, grads)
        _assert_bitwise(got, ref)


def _mlp(updater, n_in=6, hidden=8, n_out=3, seed=42, **conf_kw):
    b = (NeuralNetConfiguration.builder().seed(seed).updater(updater)
         .weight_init("xavier"))
    for k, v in conf_kw.items():
        b = getattr(b, k)(v)
    conf = (b.list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_in=hidden, n_out=n_out,
                               activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _xy(n=16, n_in=6, n_out=3, seed=0):
    rs = np.random.RandomState(seed)
    x = jnp.asarray(rs.randn(n, n_in).astype(np.float32))
    y = jnp.asarray(np.eye(n_out, dtype=np.float32)[
        rs.randint(0, n_out, size=n)])
    return x, y


class TestFusedUpdateInContainers:
    def test_model_fit_bitwise_vs_per_leaf(self):
        x, y = _xy()
        nets = []
        try:
            for flag in (True, False):
                fu.set_fused_update(flag)
                net = _mlp(Adam(1e-2, schedule=Schedule(
                    kind="exponential", initial=1e-2, decay_rate=0.9)))
                for _ in range(3):
                    net.fit(np.asarray(x), np.asarray(y))
                nets.append(net)
        finally:
            fu.set_fused_update(None)
        _assert_bitwise(nets[0].params, nets[1].params, "params")
        _assert_bitwise(nets[0].opt_state, nets[1].opt_state, "opt state")

    def test_external_updates_compile_once_and_register(self):
        net = _mlp(Sgd(0.1))
        grads = [jax.tree_util.tree_map(jnp.ones_like, p)
                 for p in net.params]
        c0 = net._compile_count
        net.apply_external_updates(grads)
        assert net._compile_count == c0 + 1
        ent = ex.get_programs().get(net._prog_caller, "apply_updates")
        assert ent is not None
        # second step with fresh grads: same program, no new compile
        grads2 = [jax.tree_util.tree_map(lambda g: g * 0.5, p)
                  for p in net.params]
        net.apply_external_updates(grads2)
        assert net._compile_count == c0 + 1

    def test_external_updates_donate_buffers(self):
        net = _mlp(Sgd(0.1))
        grads = [jax.tree_util.tree_map(jnp.zeros_like, p)
                 for p in net.params]
        net.apply_external_updates(grads)      # compile with donation
        old_params, old_opt = net.params, net.opt_state
        # device-commit so the inputs are real device buffers
        jax.block_until_ready(old_params)
        net.apply_external_updates(grads)
        donated = [l for l in _leaves((old_params, old_opt))
                   if hasattr(l, "is_deleted") and l.is_deleted()]
        assert donated, "donated inputs should be consumed (buffers dead)"

    def test_tensor_parallel_gate_uses_per_leaf_path(self):
        # TP callers pass fused=False / model_size>1 executors skip the
        # fused path: raveling row- and column-sharded leaves would gather
        # every shard. The per-leaf result must still be identical.
        net = _mlp(Adam(1e-2))
        grads = [jax.tree_util.tree_map(jnp.ones_like, p)
                 for p in net.params]
        p_fused, o_fused = net._dp_apply_updates(net.params, net.opt_state,
                                                 grads)
        calls = []
        orig_apply = net._fused.apply
        net._fused.apply = lambda *a: (calls.append(1), orig_apply(*a))[1]
        try:
            net._exec = SimpleNamespace(model_size=2)
            p_leaf, o_leaf = net._dp_apply_updates(net.params, net.opt_state,
                                                   grads)
        finally:
            net._exec = None
            net._fused.apply = orig_apply
        assert not calls, "model_size>1 must not take the fused path"
        _assert_bitwise(p_fused, p_leaf)
        _assert_bitwise(o_fused, o_leaf)


# ------------------------------------------------------ train precision bf16

class TestTrainPrecisionPolicy:
    def _fit(self, train_precision, remat=False, steps=3):
        old = ex.get_executor()
        try:
            ex.set_executor(ex.Executor(train_precision=train_precision))
            kw = {"remat": "selective"} if remat else {}
            net = _mlp(Adam(1e-2), **kw)
            x, y = _xy()
            for _ in range(steps):
                net.fit(np.asarray(x), np.asarray(y))
            out = net.output(np.asarray(x))
            return net, float(net.get_score()), np.asarray(out)
        finally:
            ex.set_executor(old)

    def test_params_stay_f32_and_loss_pinned(self):
        net32, s32, out32 = self._fit("f32")
        net16, s16, out16 = self._fit("bf16")
        for leaf in _leaves(net16.params):
            assert leaf.dtype == jnp.float32
        # pinned trajectory tolerance: measured delta ~4e-4 after 3 steps
        assert abs(s32 - s16) <= 2e-2
        # inference is NOT under the policy: both outputs are f32 and close
        assert out16.dtype == np.float32
        np.testing.assert_allclose(out16, out32, atol=5e-2)

    def test_composes_with_selective_remat(self):
        _, s_plain, _ = self._fit("bf16")
        _, s_remat, _ = self._fit("bf16", remat=True)
        # remat replays the SAME bf16 forward — identical math, same score
        assert s_plain == pytest.approx(s_remat, abs=1e-6)

    def test_env_var_resolution(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_TRAIN_PRECISION", "bf16")
        assert ex.Executor().train_precision == "bf16"
        monkeypatch.setenv("DL4JTPU_TRAIN_PRECISION", "f32")
        assert ex.Executor().train_dtype is None
        with pytest.raises(ValueError):
            ex.Executor(train_precision="fp16")


# ------------------------------------------- flash-attention training route

class TestFlashTrainingRoute:
    def _qkv(self, B=2, T=16, H=2, Dh=8):
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        shape = (B, T, H, Dh)
        return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)

    def test_training_forward_asks_with_train_true(self, monkeypatch):
        from deeplearning4j_tpu.nn.layers.attention import (
            scaled_dot_product_attention)
        seen = []

        def spy(bh, t, dh, causal, train=False, backend=None, min_t=4096):
            seen.append({"train": train, "backend": backend, "min_t": min_t})
            return "pallas"
        monkeypatch.setattr(routing, "flash_attn_route", spy)
        q, k, v = self._qkv()
        try:
            ops.set_helpers_enabled(True, interpret=True)
            scaled_dot_product_attention(q, k, v, causal=True, train=True)
            scaled_dot_product_attention(q, k, v, causal=True, train=False)
        finally:
            ops.set_helpers_enabled(None)
        assert [s["train"] for s in seen] == [True, False]
        # interpret mode: deterministic gate (min_t=0), no backend screen —
        # the SAME decision for the training and inference forward
        assert all(s["min_t"] == 0 and s["backend"] is None for s in seen)

    def test_flash_vs_dense_gradient_parity(self):
        from deeplearning4j_tpu.nn.layers.attention import (
            scaled_dot_product_attention)
        q, k, v = self._qkv()

        def loss(q, k, v, causal):
            o = scaled_dot_product_attention(q, k, v, causal=causal,
                                             train=True)
            return (o * jnp.cos(o)).sum()

        for causal in (False, True):
            try:
                ops.set_helpers_enabled(True, interpret=True)
                routing.set_route("flash_attn", "pallas")
                f_val, f_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                    q, k, v, causal)
                routing.set_route("flash_attn", "scan")
                d_val, d_grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                    q, k, v, causal)
            finally:
                routing.set_route("flash_attn", None)
                ops.set_helpers_enabled(None)
            # pinned seam tolerance: the kernel accumulates the softmax
            # streaming-style, so parity is a tolerance, not bitwise
            assert abs(float(f_val) - float(d_val)) <= 1e-4
            for fg, dg in zip(f_grads, d_grads):
                np.testing.assert_allclose(np.asarray(fg), np.asarray(dg),
                                           atol=2e-4, rtol=1e-3)


# ----------------------------------------------------- grad-phase routing

@pytest.fixture
def clean_routing():
    m = dict(routing._MEASURED)
    mg = dict(routing._MEASURED_GRAD)
    fm = dict(routing._FLASH_MEASURED)
    loaded = routing._file_loaded
    yield
    routing._MEASURED.clear(), routing._MEASURED.update(m)
    routing._MEASURED_GRAD.clear(), routing._MEASURED_GRAD.update(mg)
    routing._FLASH_MEASURED.clear(), routing._FLASH_MEASURED.update(fm)
    routing._file_loaded = loaded


class TestGradRouteRegression:
    """Every shipped row with grad data routes the backward by it —
    the grad-phase twin of tests/test_exec.py TestMeasurementFileRouting."""

    def _rows(self, kernel):
        with open(os.path.join(ROOT, "KERNELS_TPU.json")) as f:
            return [r for r in json.load(f)["results"]
                    if r.get("kernel") == kernel
                    and (r.get("grad_route") in ("pallas", "scan")
                         or r.get("grad_speedup") is not None)]

    def test_every_lstm_grad_row_routes_by_measurement(self, clean_routing):
        rows = self._rows("fused_lstm")
        assert len(rows) >= 10             # the file really ships grad data
        routing.load_measurements_file()
        for r in rows:
            want = r.get("grad_route") or (
                "pallas" if r["grad_speedup"] > 1 else "scan")
            got = routing.lstm_grad_route(r["B"], r["H"], t=r["T"],
                                          dtype=r["dtype"])
            assert got == want, (r, got)

    def test_every_flash_grad_row_gates_training_route(self, clean_routing):
        rows = self._rows("flash_attention")
        assert len(rows) >= 5
        routing.load_measurements_file()
        for r in rows:
            key = (r["BH"], r["T"], r["Dh"], bool(r.get("causal")))
            grad = r.get("grad_route") or (
                "pallas" if r["grad_speedup"] > 1 else "scan")
            got = routing.flash_attn_route(*key, train=True, backend="tpu")
            if grad == "scan":
                # a losing backward keeps the TRAINING shape dense even
                # when the forward wins
                assert got == "scan", (r, got)
            else:
                fwd = routing._FLASH_MEASURED.get(("fwd",) + key)
                if fwd == "pallas":
                    assert got == "pallas", (r, got)

    def test_scan_bwd_matches_pallas_bwd(self):
        # the scan backward is the routed stand-in for the Pallas backward:
        # same residual contract, numerically equal gradients
        from deeplearning4j_tpu.ops import lstm_pallas as lp
        b, t, h = 2, 8, 16
        ks = jax.random.split(jax.random.PRNGKey(1), 6)
        gate_in = jax.random.normal(ks[0], (t, b, 4 * h))
        rw = jax.random.normal(ks[1], (h, 4 * h)) * 0.1
        h0 = jax.random.normal(ks[2], (b, h))
        c0 = jax.random.normal(ks[3], (b, h))
        hs, tc, cprev, gates, _ = lp._scan_fwd(gate_in, rw, h0, c0,
                                               save_reserve=True)
        dhs = jax.random.normal(ks[4], (t, b, h))
        dcT = jax.random.normal(ks[5], (b, h))
        out_p = lp._bwd_call(gates, tc, cprev, rw, dhs, dcT, interpret=True)
        out_s = lp._scan_bwd(gates, tc, cprev, rw, dhs, dcT)
        for a, b_ in zip(out_p, out_s):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-5, rtol=1e-5)


class TestAutotuneRoundtrip:
    def test_persisted_table_consulted_for_fwd_and_grad(
            self, tmp_path, monkeypatch, clean_routing):
        from deeplearning4j_tpu.exec import autotune
        monkeypatch.setenv("DL4JTPU_JAX_CACHE", str(tmp_path))
        # shapes chosen to exist in NO shipped table, with the fwd winning
        # and the grad losing — so each phase's answer can only come from
        # the persisted autotune rows
        row = {"kernel": "fused_lstm", "B": 3, "T": 5, "H": 7,
               "dtype": "float32", "fwd_speedup": 1.5, "grad_speedup": 0.5,
               "backend": "cpu", "autotuned": True}
        flash = {"kernel": "flash_attention", "BH": 3, "T": 40, "Dh": 24,
                 "causal": False, "fwd_speedup": 2.0, "grad_speedup": 0.5,
                 "backend": "cpu", "autotuned": True}
        path = autotune.save_rows([row, flash])
        assert os.path.basename(path) == "autotune_cpu.json"

        routing._reset_measurement_cache()
        # heuristic alone would say scan (B*H tiny) — pallas proves the
        # persisted fwd row was consulted
        assert routing.lstm_fwd_route(3, 7, t=5, dtype="float32") == "pallas"
        # grad default is pallas — scan proves the grad row was consulted
        assert routing.lstm_grad_route(3, 7, t=5, dtype="float32") == "scan"
        # training flash route: measured losing grad keeps the shape dense
        assert routing.flash_attn_route(3, 40, 24, False, train=True,
                                        backend="tpu") == "scan"
        assert routing.flash_attn_route(3, 40, 24, False, train=False,
                                        backend="tpu") == "pallas"

    def test_save_rows_merges_by_shape(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.exec import autotune
        monkeypatch.setenv("DL4JTPU_JAX_CACHE", str(tmp_path))
        r1 = {"kernel": "fused_lstm", "B": 1, "T": 2, "H": 3,
              "dtype": "float32", "fwd_speedup": 0.5}
        autotune.save_rows([r1])
        r2 = dict(r1, fwd_speedup=2.0)
        autotune.save_rows([r2, {"kernel": "fused_lstm", "B": 9, "T": 9,
                                 "H": 9, "dtype": "float32",
                                 "fwd_speedup": 1.1}])
        rows = autotune.load_table()
        assert len(rows) == 2
        mine = [r for r in rows if r["B"] == 1]
        assert mine[0]["fwd_speedup"] == 2.0
