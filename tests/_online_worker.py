"""Online-learning victim for the SIGKILL chaos test (not a pytest module).

Runs the full online loop — stream → guarded fine-tune → checkpoint →
gate → promote over HTTP into the PARENT's serving server — and kills
itself at the two nastiest instants:

- ``--kill-after-saves K``  — SIGKILL the instant the K-th checkpoint
  save returns (mid-fine-tune: manifest just rotated, no promotion yet);
- ``--kill-at-promotion``   — SIGKILL from the Deployer's
  ``chaos_mid_promotion`` hook, i.e. after the serving target swapped but
  before the deploy intent file says ``live`` (mid-promotion).

A relaunch without kill flags must resume from the manifest
(``trainer.resume``), converge the deploy state (``deployer.recover``)
and finish its rounds — while the parent's server keeps answering
/predict the whole time, never on a torn model.

Usage: _online_worker.py --dir D --server-url URL --rounds N
                         [--kill-after-saves K] [--kill-at-promotion]
"""

import argparse
import os
import signal
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (
        _xla + " --xla_force_host_platform_device_count=8").strip()


def _self_kill():
    os.kill(os.getpid(), signal.SIGKILL)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--dir", required=True)
    parser.add_argument("--server-url", required=True)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--kill-after-saves", type=int, default=0)
    parser.add_argument("--kill-at-promotion", action="store_true")
    parser.add_argument("--phase", type=int, default=0)
    args = parser.parse_args(argv)

    from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
    setup_compile_cache()       # relaunches must not re-pay XLA compiles

    from deeplearning4j_tpu.data.streaming import StreamingDataSetIterator
    from deeplearning4j_tpu.online import (BatchGuard, Deployer,
                                           DriftingProblem, HttpTarget,
                                           OnlineLearningService,
                                           OnlineTrainer, PromotionGate)
    from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager
    from deeplearning4j_tpu.serving.replica import build_model

    prob = DriftingProblem()
    net = build_model("mlp")
    scratch = build_model("mlp")
    mgr = CheckpointManager(os.path.join(args.dir, "ck"), keep_last=3)
    it = StreamingDataSetIterator(batch_size=16)
    trainer = OnlineTrainer(net, it, mgr, guard=BatchGuard(net),
                            batches_per_round=4)
    resumed = trainer.resume()
    print(f"WORKER_RESUMED from={resumed}", flush=True)

    if args.kill_after_saves > 0:
        real_save = mgr.save
        count = [0]

        def killing_save(model, normalizer=None):
            path = real_save(model, normalizer=normalizer)
            count[0] += 1
            if count[0] >= args.kill_after_saves:
                print("WORKER_SELF_KILL after_save", flush=True)
                sys.stdout.flush()
                _self_kill()
            return path
        mgr.save = killing_save

    ex, ey = prob.eval_set(128, phase=args.phase)
    # a permissive quality bar: the chaos test is about crash recovery,
    # not gate selectivity — promotions must actually happen to be killed
    gate = PromotionGate(ex, ey, min_improvement=-1.0)
    chaos = None
    if args.kill_at_promotion:
        def chaos():
            print("WORKER_SELF_KILL mid_promotion", flush=True)
            sys.stdout.flush()
            _self_kill()
    dep = Deployer(mgr, targets=[HttpTarget(args.server_url)],
                   state_path=os.path.join(args.dir, "deploy.json"),
                   chaos_mid_promotion=chaos)
    outcome = dep.recover()
    print(f"WORKER_RECOVERED outcome={outcome}", flush=True)
    svc = OnlineLearningService(trainer, gate, dep, scratch,
                                regression_margin=1.0)

    # batch seeds continue from the restored iteration counter so a
    # resumed worker trains on fresh data, not a replay of the same rows
    seed = int(net.iteration) + 1
    for rnd in range(args.rounds):
        for _ in range(trainer.batches_per_round):
            x, y = prob.batch(16, phase=args.phase, seed=seed)
            seed += 1
            it.push(x, y, batched=True)
        out = svc.step()
        print(f"WORKER_ROUND {rnd} trained={out['trained']} "
              f"promoted={out['promoted']} version={out['version']}",
              flush=True)
    print("WORKER_DONE", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
