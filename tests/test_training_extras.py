"""Listeners, early stopping, transfer learning
(parity role: reference listener/earlystopping/transferlearning test suites)."""

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.data.fetchers import load_iris
from deeplearning4j_tpu.optimize import (
    ScoreIterationListener, CollectScoresIterationListener, PerformanceListener,
    EvaluativeListener, CheckpointListener,
)
from deeplearning4j_tpu.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition, DataSetLossCalculator,
    InMemoryModelSaver, LocalFileModelSaver,
)
from deeplearning4j_tpu.transferlearning import (
    TransferLearning, FineTuneConfiguration, TransferLearningHelper,
)


def _net(n_in=4, n_hidden=16, n_out=3, seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=n_hidden, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def test_listeners_fire():
    x, y = load_iris()
    net = _net()
    collect = CollectScoresIterationListener()
    net.set_listeners(ScoreIterationListener(5), collect, PerformanceListener(5))
    for _ in range(12):
        net.fit(DataSet(x, y))
    assert len(collect.scores) == 12
    assert collect.scores[-1][1] < collect.scores[0][1]


def test_evaluative_and_checkpoint_listeners(tmp_path):
    x, y = load_iris()
    ds = DataSet(x, y)
    net = _net()
    ev = EvaluativeListener(ds, frequency=5)
    cp = CheckpointListener(str(tmp_path), every_n_iterations=4, keep_last=2)
    net.set_listeners(ev, cp)
    for _ in range(10):
        net.fit(ds)
    assert len(ev.evaluations) == 2
    zips = list(tmp_path.glob("*.zip"))
    assert len(zips) == 2  # keep_last enforced


def test_early_stopping_max_epochs(tmp_path):
    x, y = load_iris()
    it = ListDataSetIterator(DataSet(x, y), 50)
    net = _net()
    esc = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 150)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
        iteration_termination_conditions=[InvalidScoreIterationTerminationCondition()],
        model_saver=LocalFileModelSaver(str(tmp_path)))
    result = EarlyStoppingTrainer(esc, net, it).fit()
    assert result.total_epochs == 5
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.best_model is not None
    assert (tmp_path / "bestModel.zip").exists()
    assert len(result.score_vs_epoch) == 5


def test_early_stopping_no_improvement():
    x, y = load_iris()
    it = ListDataSetIterator(DataSet(x, y), 150)
    net = _net()
    esc = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 150)),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(100),
            ScoreImprovementEpochTerminationCondition(3, min_improvement=10.0)],
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingTrainer(esc, net, it).fit()
    # improvement threshold of 10 nats/epoch is unattainable → stops at 3
    assert result.total_epochs <= 5
    assert result.termination_details == "ScoreImprovementEpochTerminationCondition"


def test_transfer_learning_freeze_and_replace_head():
    x, y = load_iris()
    base = _net()
    for _ in range(60):
        base.fit(DataSet(x, y))
    w0_before = np.asarray(base.params[0]["W"])

    new_net = (TransferLearning.Builder(base)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
               .set_feature_extractor(0)
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
               .build())
    assert isinstance(new_net.layers[0], FrozenLayer)
    assert len(new_net.layers) == 3
    for _ in range(20):
        new_net.fit(DataSet(x, y))
    # frozen layer params unchanged, head params trained
    assert np.allclose(np.asarray(new_net.params[0]["W"]), w0_before)
    assert new_net.evaluate(DataSet(x, y)).accuracy() > 0.8


def test_transfer_nout_replace():
    base = _net()
    new_net = (TransferLearning.Builder(base)
               .n_out_replace(1, 12, "xavier")
               .build())
    assert new_net.layers[1].n_out == 12
    assert new_net.layers[2].n_in == 12
    out = new_net.output(np.random.rand(3, 4).astype(np.float32))
    assert out.shape == (3, 3)


def test_transfer_learning_helper_featurize():
    x, y = load_iris()
    base = _net()
    frozen = (TransferLearning.Builder(base)
              .set_feature_extractor(1)
              .build())
    helper = TransferLearningHelper(frozen)
    feats = helper.featurize(DataSet(x, y))
    assert feats.features.shape == (150, 8)
    s_before = frozen.score(DataSet(x, y))
    for _ in range(40):
        helper.fit_featurized(feats)
    assert frozen.score(DataSet(x, y)) < s_before
