"""Listeners, early stopping, transfer learning
(parity role: reference listener/earlystopping/transferlearning test suites)."""

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.data.fetchers import load_iris
from deeplearning4j_tpu.optimize import (
    ScoreIterationListener, CollectScoresIterationListener, PerformanceListener,
    EvaluativeListener, CheckpointListener,
)
from deeplearning4j_tpu.earlystopping import (
    EarlyStoppingConfiguration, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition, DataSetLossCalculator,
    InMemoryModelSaver, LocalFileModelSaver,
)
from deeplearning4j_tpu.transferlearning import (
    TransferLearning, FineTuneConfiguration, TransferLearningHelper,
)


def _net(n_in=4, n_hidden=16, n_out=3, seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=n_hidden, activation="relu"))
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def test_listeners_fire():
    x, y = load_iris()
    net = _net()
    collect = CollectScoresIterationListener()
    net.set_listeners(ScoreIterationListener(5), collect, PerformanceListener(5))
    for _ in range(12):
        net.fit(DataSet(x, y))
    assert len(collect.scores) == 12
    assert collect.scores[-1][1] < collect.scores[0][1]


def test_evaluative_and_checkpoint_listeners(tmp_path):
    x, y = load_iris()
    ds = DataSet(x, y)
    net = _net()
    ev = EvaluativeListener(ds, frequency=5)
    cp = CheckpointListener(str(tmp_path), every_n_iterations=4, keep_last=2)
    net.set_listeners(ev, cp)
    for _ in range(10):
        net.fit(ds)
    assert len(ev.evaluations) == 2
    zips = list(tmp_path.glob("*.zip"))
    assert len(zips) == 2  # keep_last enforced


def test_early_stopping_max_epochs(tmp_path):
    x, y = load_iris()
    it = ListDataSetIterator(DataSet(x, y), 50)
    net = _net()
    esc = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 150)),
        epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
        iteration_termination_conditions=[InvalidScoreIterationTerminationCondition()],
        model_saver=LocalFileModelSaver(str(tmp_path)))
    result = EarlyStoppingTrainer(esc, net, it).fit()
    assert result.total_epochs == 5
    assert result.termination_reason == "EpochTerminationCondition"
    assert result.best_model is not None
    assert (tmp_path / "bestModel.zip").exists()
    assert len(result.score_vs_epoch) == 5


def test_early_stopping_no_improvement():
    x, y = load_iris()
    it = ListDataSetIterator(DataSet(x, y), 150)
    net = _net()
    esc = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator(DataSet(x, y), 150)),
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(100),
            ScoreImprovementEpochTerminationCondition(3, min_improvement=10.0)],
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingTrainer(esc, net, it).fit()
    # improvement threshold of 10 nats/epoch is unattainable → stops at 3
    assert result.total_epochs <= 5
    assert result.termination_details == "ScoreImprovementEpochTerminationCondition"


def test_transfer_learning_freeze_and_replace_head():
    x, y = load_iris()
    base = _net()
    for _ in range(60):
        base.fit(DataSet(x, y))
    w0_before = np.asarray(base.params[0]["W"])

    new_net = (TransferLearning.Builder(base)
               .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
               .set_feature_extractor(0)
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
               .build())
    assert isinstance(new_net.layers[0], FrozenLayer)
    assert len(new_net.layers) == 3
    for _ in range(20):
        new_net.fit(DataSet(x, y))
    # frozen layer params unchanged, head params trained
    assert np.allclose(np.asarray(new_net.params[0]["W"]), w0_before)
    assert new_net.evaluate(DataSet(x, y)).accuracy() > 0.8


def test_transfer_nout_replace():
    base = _net()
    new_net = (TransferLearning.Builder(base)
               .n_out_replace(1, 12, "xavier")
               .build())
    assert new_net.layers[1].n_out == 12
    assert new_net.layers[2].n_in == 12
    out = new_net.output(np.random.rand(3, 4).astype(np.float32))
    assert out.shape == (3, 3)


def test_transfer_learning_helper_featurize():
    x, y = load_iris()
    base = _net()
    frozen = (TransferLearning.Builder(base)
              .set_feature_extractor(1)
              .build())
    helper = TransferLearningHelper(frozen)
    feats = helper.featurize(DataSet(x, y))
    assert feats.features.shape == (150, 8)
    s_before = frozen.score(DataSet(x, y))
    for _ in range(40):
        helper.fit_featurized(feats)
    assert frozen.score(DataSet(x, y)) < s_before


class TestWeightNoise:
    """Parity: nn/conf/weightnoise/ (IWeightNoise, DropConnect, WeightNoise)
    — applied to params at forward time during training only."""

    def _net(self, wn):
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.05))
                .weight_init("xavier").weight_noise(wn).list()
                .layer(DenseLayer(n_in=6, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def _data(self, n=64):
        rs = np.random.RandomState(0)
        x = rs.randn(n, 6).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        return x, y

    def test_dropconnect_trains_and_inference_is_deterministic(self):
        from deeplearning4j_tpu.nn.weightnoise import DropConnect
        net = self._net(DropConnect(weight_retain_prob=0.8))
        x, y = self._data()
        l0 = net.score(x=x, y=y)
        for _ in range(40):
            net.fit(x, y)
        assert net.score(x=x, y=y) < l0 * 0.8
        # noise is train-only: repeated inference must be identical
        np.testing.assert_array_equal(np.asarray(net.output(x)),
                                      np.asarray(net.output(x)))

    def test_weight_noise_changes_training_loss_stochastically(self):
        from deeplearning4j_tpu.nn.weightnoise import WeightNoise
        import jax.numpy as jnp
        net = self._net(WeightNoise(stddev=0.3))
        x, y = self._data(16)
        # same params, two iterations: the train loss differs because the
        # noise is resampled per step via the iteration-folded rng
        l1, _ = net._loss(net.params, net.state, jnp.asarray(x),
                          jnp.asarray(y),
                          __import__("jax").random.PRNGKey(1), None, None)
        l2, _ = net._loss(net.params, net.state, jnp.asarray(x),
                          jnp.asarray(y),
                          __import__("jax").random.PRNGKey(2), None, None)
        assert float(l1) != float(l2)

    def test_weight_noise_serde_round_trip(self):
        from deeplearning4j_tpu.nn.weightnoise import DropConnect
        net = self._net(DropConnect(weight_retain_prob=0.7))
        from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
        back = MultiLayerConfiguration.from_json(net.conf.to_json())
        wn = back.layers[0].weight_noise
        assert isinstance(wn, DropConnect)
        assert wn.weight_retain_prob == 0.7

    def test_weight_noise_reaches_output_layer_and_wrappers(self):
        """Noise must hit the output layer's loss path and recurse into
        wrapper layers' nested param dicts (Bidirectional)."""
        import jax, jax.numpy as jnp
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import OutputLayer
        from deeplearning4j_tpu.nn.weightnoise import WeightNoise, DropConnect

        # output-layer-only net: two rng keys must give different train loss
        conf = (NeuralNetConfiguration.builder().seed(1)
                .weight_noise(WeightNoise(stddev=0.5)).list()
                .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(8, 4), jnp.float32)
        y = jnp.asarray(np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)])
        l1, _ = net._loss(net.params, net.state, x, y,
                          jax.random.PRNGKey(1), None, None)
        l2, _ = net._loss(net.params, net.state, x, y,
                          jax.random.PRNGKey(2), None, None)
        assert float(l1) != float(l2), "output layer params never noised"

        # nested dict recursion: DropConnect(0.5) must zero some leaves
        dc = DropConnect(weight_retain_prob=0.5)
        nested = {"fwd": {"W": jnp.ones((8, 8))}, "bwd": {"W": jnp.ones((8, 8))}}
        noised = dc.apply(nested, jax.random.PRNGKey(0))
        assert float(jnp.sum(noised["fwd"]["W"] == 0)) > 0
        assert float(jnp.sum(noised["bwd"]["W"] == 0)) > 0
