"""Tests: stats storage/listener/UI server + KNN REST service.

Parity patterns: reference ui tests boot PlayUIServer and post stats
(SURVEY.md §4 'UI tests'), nearestneighbor-server tests hit the REST API
with real vectors."""

import json
import os
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (InMemoryStatsStorage, FileStatsStorage,
                                   StatsReport, StatsListener, UIServer,
                                   RemoteUIStatsStorageRouter)


def _report(sid="s1", it=0, score=1.0):
    return StatsReport(session_id=sid, iteration=it, score=score,
                       timestamp=123.0, iteration_time_ms=5.0,
                       param_stats={"0": {"mean": 0.1, "std": 0.2,
                                          "min": -1.0, "max": 1.0,
                                          "norm": 3.0}})


class TestStorage:
    def test_binary_roundtrip(self):
        r = _report()
        r2 = StatsReport.from_bytes(r.to_bytes())
        assert r2.session_id == "s1" and r2.iteration == 0
        assert r2.score == 1.0 and r2.param_stats["0"]["norm"] == 3.0

    def test_bad_magic_raises(self):
        with pytest.raises(ValueError, match="not a StatsReport"):
            StatsReport.from_bytes(b"XXXX" + b"\x00" * 40)

    def test_in_memory_pubsub(self):
        st = InMemoryStatsStorage()
        got = []
        st.register_stats_listener(got.append)
        st.put_update(_report(it=1))
        st.put_update(_report(it=2))
        assert st.list_session_ids() == ["s1"]
        assert [r.iteration for r in st.get_all_updates("s1")] == [1, 2]
        assert st.get_latest_update("s1").iteration == 2
        assert len(got) == 2

    def test_file_storage_persists_and_reloads(self, tmp_path):
        p = str(tmp_path / "stats.bin")
        st = FileStatsStorage(p)
        st.put_update(_report(it=1, score=2.5))
        st.put_update(_report(sid="s2", it=7))
        st.close()
        st2 = FileStatsStorage(p)
        assert st2.list_session_ids() == ["s1", "s2"]
        assert st2.get_latest_update("s1").score == 2.5
        st2.close()

    def test_file_storage_ignores_truncated_tail(self, tmp_path):
        p = str(tmp_path / "stats.bin")
        st = FileStatsStorage(p)
        st.put_update(_report(it=1))
        st.close()
        with open(p, "ab") as fh:            # simulate crash mid-write
            fh.write(b"\xff\xff\x00\x00partial")
        st2 = FileStatsStorage(p)
        assert [r.iteration for r in st2.get_all_updates("s1")] == [1]
        st2.close()


class TestStatsListenerAndServer:
    def _train_tiny(self, storage):
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                .list()
                .layer(DenseLayer(n_in=3, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        lst = StatsListener(storage, session_id="train_sess")
        net.set_listeners(lst)
        rs = np.random.RandomState(0)
        x = rs.randn(32, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        for _ in range(5):
            net.fit(x, y)
        return net

    def test_listener_collects_and_ui_serves(self):
        storage = InMemoryStatsStorage()
        self._train_tiny(storage)
        ups = storage.get_all_updates("train_sess")
        assert len(ups) == 5
        assert np.isfinite(ups[-1].score)
        assert ups[-1].param_stats            # param summaries collected
        assert ups[1].update_stats            # deltas from 2nd iteration
        assert storage.get_static_info("train_sess")["numLayers"] == 2

        ui = UIServer(port=0)
        try:
            ui.attach(storage)
            base = f"http://127.0.0.1:{ui.port}"
            sids = json.loads(urllib.request.urlopen(
                base + "/train/sessions", timeout=5).read())
            assert "train_sess" in sids
            ov = json.loads(urllib.request.urlopen(
                base + "/train/overview?sid=train_sess", timeout=5).read())
            assert len(ov["scores"]) == 5
            assert ov["latestParamStats"]
            page = urllib.request.urlopen(base + "/", timeout=5).read()
            assert b"training overview" in page
            model = json.loads(urllib.request.urlopen(
                base + "/train/model?sid=train_sess", timeout=5).read())
            assert model["numLayers"] == 2
        finally:
            ui.stop()

    def test_remote_router_round_trip(self):
        ui = UIServer(port=0)
        try:
            remote_storage = ui.enable_remote_listener()
            router = RemoteUIStatsStorageRouter(
                f"http://127.0.0.1:{ui.port}")
            router.put_static_info("remote_sess", {"numLayers": 3})
            router.put_update(_report(sid="remote_sess", it=9, score=0.5))
            ups = remote_storage.get_all_updates("remote_sess")
            assert len(ups) == 1 and ups[0].iteration == 9
            assert remote_storage.get_static_info(
                "remote_sess")["numLayers"] == 3
        finally:
            ui.stop()


class TestKnnServer:
    def test_server_and_client(self):
        from deeplearning4j_tpu.clustering.knn_server import (
            NearestNeighborsServer, NearestNeighborsClient)
        rs = np.random.RandomState(0)
        pts = rs.randn(50, 8).astype(np.float32)
        srv = NearestNeighborsServer(pts, port=0).start()
        try:
            cli = NearestNeighborsClient(f"http://127.0.0.1:{srv.port}")
            # query by corpus index: nearest non-self neighbours
            res = cli.knn(index=3, k=5)
            assert len(res) == 5
            assert all(r["index"] != 3 for r in res)
            dists = [r["distance"] for r in res]
            assert dists == sorted(dists)
            # query by new vector: point 7 itself must come back first
            res2 = cli.knn_new(pts[7], k=3)
            assert res2[0][0]["index"] == 7
            assert res2[0][0]["distance"] < 1e-4
        finally:
            srv.stop()

    def test_client_error_propagation(self):
        from deeplearning4j_tpu.clustering.knn_server import (
            NearestNeighborsServer, NearestNeighborsClient)
        srv = NearestNeighborsServer(np.eye(4, dtype=np.float32),
                                     port=0).start()
        try:
            cli = NearestNeighborsClient(f"http://127.0.0.1:{srv.port}")
            with pytest.raises(RuntimeError):
                cli.knn(index=999, k=1)      # out of range
        finally:
            srv.stop()


class TestModelSystemActivationPages:
    def _train_conv(self, storage):
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                                  DenseLayer, OutputLayer)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.updaters import Sgd
        from deeplearning4j_tpu.ui import ConvolutionalIterationListener
        conf = (NeuralNetConfiguration.builder().seed(5).updater(Sgd(0.1))
                .weight_init("xavier")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=3, stride=1,
                                        activation="relu"))
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.set_listeners(
            StatsListener(storage, session_id="conv_sess"),
            ConvolutionalIterationListener(storage, frequency=2,
                                           session_id="conv_sess"))
        rs = np.random.RandomState(0)
        x = rs.randn(8, 8, 8, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]
        for _ in range(4):
            net.fit(x, y)
        return net

    def test_model_system_activation_endpoints(self):
        storage = InMemoryStatsStorage()
        self._train_conv(storage)
        ui = UIServer(port=0)
        try:
            ui.attach(storage)
            base = f"http://127.0.0.1:{ui.port}"
            # HTML pages
            for path, marker in (("/train/model", b"per-layer"),
                                 ("/train/system", b"system"),
                                 ("/train/activations", b"activations")):
                page = urllib.request.urlopen(base + path, timeout=5).read()
                assert marker in page, path
            # model data: per-layer series with named groups + log ratios
            d = json.loads(urllib.request.urlopen(
                base + "/train/model/data?sid=conv_sess", timeout=5).read())
            assert any("ConvolutionLayer" in g for g in d["series"])
            some = next(iter(d["series"].values()))
            assert len(some["iterations"]) == 4
            assert len(some["logRatio"]) == 4
            import math
            assert any(isinstance(v, float) and not math.isnan(v)
                       for v in some["logRatio"][1:])
            # system data
            s = json.loads(urllib.request.urlopen(
                base + "/train/system/data?sid=conv_sess", timeout=5).read())
            assert len(s["memRssMb"]) == 4 and s["memRssMb"][-1] > 0
            # activations data: PNG grids for the conv layer
            a = json.loads(urllib.request.urlopen(
                base + "/train/activations/data?sid=conv_sess",
                timeout=5).read())
            assert a["images"], "no activation captures"
            import base64
            png = base64.b64decode(next(iter(a["images"].values())))
            assert png[:8] == b"\x89PNG\r\n\x1a\n"
        finally:
            ui.stop()


def test_webreporter_async_remote_training():
    """WebReporter (async queue, WebReporter.java parity): a real training
    run with StatsListener pointed at a remote UI server delivers static
    info + per-iteration updates without blocking the train loop."""
    import numpy as np
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.ui import UIServer, StatsListener, WebReporter

    ui = UIServer(port=0)
    try:
        remote_storage = ui.enable_remote_listener()
        reporter = WebReporter(f"http://127.0.0.1:{ui.port}")
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.add_listeners(StatsListener(reporter, frequency=1,
                                        session_id="ws"))
        rs = np.random.RandomState(0)
        x = rs.rand(32, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 32)]
        for _ in range(4):
            net.fit(x, y)
        reporter.flush()
        ups = remote_storage.get_all_updates("ws")
        assert len(ups) >= 3
        assert remote_storage.get_static_info("ws")["numLayers"] == 2
        assert reporter.dropped == 0
        reporter.close()
    finally:
        ui.stop()


def test_webreporter_down_collector_never_blocks():
    """A dead collector must not stall training: records drop, fit runs."""
    import time
    import numpy as np
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.ui import StatsListener, WebReporter

    reporter = WebReporter("http://127.0.0.1:9", retries=1, timeout=0.1)
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.add_listeners(StatsListener(reporter, frequency=1))
    x = np.random.rand(16, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[np.random.randint(0, 2, 16)]
    t0 = time.perf_counter()
    for _ in range(3):
        net.fit(x, y)
    assert time.perf_counter() - t0 < 30     # no per-iteration stalls
    reporter.close()
