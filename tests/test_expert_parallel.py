"""Expert-parallel MoE tests (TPU-idiomatic extension; oracle = per-token
dense expert application)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.expert_parallel import (
    init_moe_params, shard_moe_params, moe_ffw, moe_ffw_dense_reference,
)

D, H, E = 8, 16, 4


def _params(seed=0):
    return init_moe_params(jax.random.PRNGKey(seed), D, H, E)


class TestMoE:
    def test_matches_dense_reference_with_ample_capacity(self):
        params = _params()
        x = jnp.asarray(np.random.RandomState(1).randn(32, D), jnp.float32)
        y, aux = moe_ffw(params, x, capacity_factor=E * 1.0)  # C = T, no drops
        want = moe_ffw_dense_reference(params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
        assert float(aux) > 0

    def test_capacity_drops_zero_tokens(self):
        params = _params(2)
        x = jnp.asarray(np.random.RandomState(2).randn(64, D), jnp.float32)
        y_tight, _ = moe_ffw(params, x, capacity_factor=0.25)
        y_ample, _ = moe_ffw(params, x, capacity_factor=E * 1.0)
        dropped = np.asarray(jnp.all(y_tight == 0, axis=-1))
        assert dropped.any(), "tight capacity should drop some tokens"
        kept = ~dropped
        np.testing.assert_allclose(np.asarray(y_tight)[kept],
                                   np.asarray(y_ample)[kept],
                                   rtol=1e-4, atol=1e-5)

    def test_sharded_run_matches_unsharded(self):
        """Experts sharded over the mesh 'expert' axis: same outputs, XLA
        inserts the all-to-alls."""
        mesh = Mesh(np.array(jax.devices()[:E]), ("expert",))
        params = _params(3)
        x = jnp.asarray(np.random.RandomState(3).randn(32, D), jnp.float32)
        y_ref, aux_ref = moe_ffw(params, x, capacity_factor=2.0)

        sharded = shard_moe_params(params, mesh)
        assert len(sharded["W1"].sharding.device_set) == E
        with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") \
                else mesh:
            y_sh, aux_sh = jax.jit(moe_ffw, static_argnames="capacity_factor")(
                sharded, x, capacity_factor=2.0)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(aux_sh), float(aux_ref), rtol=1e-4)

    def test_trainable_end_to_end(self):
        """Router + experts learn a mapping; aux loss keeps routing spread."""
        params = _params(4)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(64, D), jnp.float32)
        tgt = jnp.asarray(np.tanh(rs.randn(64, D)), jnp.float32)

        @jax.jit
        def step(params, x, tgt):
            def loss(p):
                y, aux = moe_ffw(p, x, capacity_factor=2.0)
                return jnp.mean((y - tgt) ** 2) + 0.01 * aux
            l, g = jax.value_and_grad(loss)(params)
            return jax.tree_util.tree_map(lambda p, gg: p - 0.3 * gg,
                                          params, g), l

        losses = []
        for _ in range(200):
            params, l = step(params, x, tgt)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.6, losses[::40]
