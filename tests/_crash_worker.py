"""Training victim for the SIGKILL soak test (not a pytest module).

Builds the same deterministic net + data as tests/test_resilience.py,
trains with a CheckpointListener, and lets the parent SIGKILL it mid-run
— a real process death, not an in-process exception. Progress is visible
to the parent through the checkpoint directory itself (every zip is
written atomically, so whatever the kill leaves behind must be loadable).

Usage: _crash_worker.py <ckpt_dir> <epochs> <step_delay_ms>
"""

import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_xla = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla:
    os.environ["XLA_FLAGS"] = (
        _xla + " --xla_force_host_platform_device_count=8").strip()


def build_net(chunk_steps=4):
    """Tiny deterministic MLP; small chunk cap so the iteration counter
    advances in several fit_scan jumps per epoch."""
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    net._CHUNK_MAX_STEPS = chunk_steps
    return net


def build_data():
    """48 examples, batch 8 → 6 iterations/epoch; shuffle=True so resume
    must also reproduce the iterator's RNG position, not just the params."""
    import numpy as np
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    rs = np.random.RandomState(7)
    x = rs.rand(48, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 48)]
    return ListDataSetIterator(DataSet(x, y), 8, shuffle=True)


class _Throttle:
    """Slow each iteration so the parent's SIGKILL lands mid-run."""

    def __init__(self, delay_s):
        self.delay_s = delay_s

    def iteration_done(self, model, iteration, epoch):
        time.sleep(self.delay_s)

    def on_epoch_end(self, model):
        pass


def main():
    ckpt_dir, epochs, delay_ms = (sys.argv[1], int(sys.argv[2]),
                                  float(sys.argv[3]))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.resilience.checkpoint import CheckpointListener
    net = build_net()
    net.listeners.append(_Throttle(delay_ms / 1000.0))
    listener = CheckpointListener(ckpt_dir, every_n_iterations=2,
                                  keep_last=3)
    net.fit(build_data(), epochs=epochs, checkpoint=listener)
    print(f"WORKER_DONE iteration={net.iteration}")


if __name__ == "__main__":
    main()
