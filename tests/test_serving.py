"""Serving engine: shape-bucketed inference + micro-batcher + HTTP endpoint.

The load-bearing claims pinned here:
- the bucketed ``output()`` fast path is BITWISE-equal to the exact-shape
  forward for every tested batch size (padding is numerics-neutral because
  inference computes each output row from its own input row alone);
- a mixed-size request stream (sizes 1..64) compiles at most
  ⌈log2(64)⌉+1 programs where the seed path compiled once per distinct
  size (counted via the engine's trace hook);
- the micro-batcher answers every concurrent request with its own slice
  while merging them into fewer device calls;
- the HTTP endpoint round-trips the knn_server-style Base64 f32 wire
  format, and ``/warmup`` leaves the process able to serve the whole
  ladder without another trace.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                ComputationGraph)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, LSTM, OutputLayer,
    RnnOutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.serving import (
    InferenceClient, InferenceEngine, InferenceServer, MicroBatcher,
    bucket_for, bucket_ladder)


def _mlp():
    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------ ladder helpers

def test_bucket_ladder_and_bucket_for():
    assert bucket_ladder(64) == [1, 2, 4, 8, 16, 32, 64]
    assert bucket_ladder(64, min_bucket=8) == [8, 16, 32, 64]
    assert [bucket_for(n, 64) for n in (1, 2, 3, 5, 8, 9, 64)] \
        == [1, 2, 4, 8, 8, 16, 64]
    assert bucket_for(100, 64) == 64          # clamped to the top bucket
    with pytest.raises(ValueError):
        bucket_for(0, 64)


# ----------------------------------------------------------- bitwise parity

def test_bucketed_output_bitwise_equal_mlp():
    net = _mlp()
    rs = np.random.RandomState(0)
    for n in (1, 3, 5, 7, 11, 13, 27):        # none of these is a bucket
        x = rs.rand(n, 4).astype(np.float32)
        bucketed = np.asarray(net.output(x))
        direct = np.asarray(net.output(x, bucketed=False))
        assert bucketed.shape == (n, 3)
        assert np.array_equal(bucketed, direct), f"batch {n} diverged"


def test_bucketed_output_bitwise_equal_conv_bn():
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3,
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(1)
    # one fit so BN running stats are non-trivial at inference
    net.fit(rs.rand(8, 12, 12, 1).astype(np.float32),
            np.eye(5, dtype=np.float32)[rs.randint(0, 5, 8)])
    for n in (1, 5, 9, 17):
        x = rs.rand(n, 12, 12, 1).astype(np.float32)
        assert np.array_equal(np.asarray(net.output(x)),
                              np.asarray(net.output(x, bucketed=False)))


def test_bucketed_output_bitwise_equal_lstm_with_mask():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-3))
            .list()
            .layer(LSTM(n_in=6, n_out=8))
            .layer(RnnOutputLayer(n_in=8, n_out=4, activation="softmax",
                                  loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(2)
    x = rs.rand(5, 10, 6).astype(np.float32)
    m = (rs.rand(5, 10) > 0.3).astype(np.float32)
    assert np.array_equal(np.asarray(net.output(x, mask=m)),
                          np.asarray(net.output(x, mask=m, bucketed=False)))


def test_bucketed_output_computation_graph():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
            .weight_init("xavier").graph_builder()
            .add_inputs("in").set_input_types(InputType.feed_forward(4))
            .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "d")
            .set_outputs("out").build())
    cg = ComputationGraph(conf).init()
    rs = np.random.RandomState(4)
    for n in (1, 5, 9):
        x = rs.rand(n, 4).astype(np.float32)
        assert np.array_equal(np.asarray(cg.output(x)),
                              np.asarray(cg.output(x, bucketed=False)))
    # 3 distinct sizes → at most 3 bucket programs (1, 8, 16)
    assert cg.serving_engine().trace_count <= 3


# --------------------------------------------------------- compile counting

def test_mixed_size_stream_compiles_at_most_the_ladder():
    """Sizes 1..64 through the bucketed path: ≤ ⌈log2(64)⌉+1 programs where
    the exact-shape seed path would compile 64."""
    net = _mlp()
    eng = net.serving_engine()
    rs = np.random.RandomState(5)
    for n in range(1, 65):
        out = np.asarray(net.output(rs.rand(n, 4).astype(np.float32)))
        assert out.shape == (n, 3)
    assert eng.trace_count <= 7, \
        f"{eng.trace_count} programs for sizes 1..64 (ladder allows 7)"


def test_oversize_batch_chunks_through_top_bucket():
    net = _mlp()
    eng = net.serving_engine(max_batch=8)
    assert eng.max_batch == 8
    rs = np.random.RandomState(6)
    x = rs.rand(21, 4).astype(np.float32)           # 8 + 8 + 5→pad 8
    assert np.array_equal(np.asarray(eng.predict(x)),
                          np.asarray(net.output(x, bucketed=False)))
    assert eng.trace_count <= 2                     # bucket 8 (+ bucket 8 pad)


def test_warmup_precompiles_the_ladder():
    net = _mlp()
    eng = net.serving_engine()
    buckets = eng.warmup((4,), max_batch=16)
    assert buckets == [1, 2, 4, 8, 16]
    traces_after_warmup = eng.trace_count
    rs = np.random.RandomState(7)
    for n in (1, 3, 6, 11, 16):
        net.output(rs.rand(n, 4).astype(np.float32))
    assert eng.trace_count == traces_after_warmup   # no new programs
    assert eng.warmup_seconds is not None
    stats = eng.stats()
    assert stats["compiled_programs"] == traces_after_warmup


# ------------------------------------------------------- pipelined evaluate

def test_evaluate_pipelined_matches_per_batch_eval():
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    net = _mlp()
    rs = np.random.RandomState(8)
    batches = [DataSet(rs.rand(n, 4).astype(np.float32),
                       np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)])
               for n in (5, 3, 8, 1, 6)]
    ev = net.evaluate(batches)
    ref = Evaluation()
    for ds in batches:
        ref.eval(ds.labels,
                 np.asarray(net.output(ds.features, bucketed=False)))
    assert ev.accuracy() == ref.accuracy()
    assert np.array_equal(ev.confusion, ref.confusion)


# ------------------------------------------------------------ micro-batcher

def test_micro_batcher_coalesces_and_demuxes():
    net = _mlp()
    eng = net.serving_engine()
    eng.warmup((4,), max_batch=64)
    mb = MicroBatcher(eng, max_batch=64, max_latency_ms=20.0).start()
    try:
        rs = np.random.RandomState(9)
        reqs = [rs.rand(1 + i % 5, 4).astype(np.float32) for i in range(24)]
        futs = [mb.submit(x) for x in reqs]
        for x, fut in zip(reqs, futs):
            got = fut.result(timeout=30)
            assert np.array_equal(got,
                                  np.asarray(net.output(x, bucketed=False)))
        stats = mb.stats()
        assert stats["requests"] == 24
        assert stats["device_calls"] < 24       # coalescing actually merged
    finally:
        mb.stop()


def test_micro_batcher_stop_rejects_new_submits():
    from deeplearning4j_tpu.resilience.errors import BatcherStoppedError
    net = _mlp()
    mb = MicroBatcher(net.serving_engine(), max_latency_ms=1.0)
    mb.start()
    mb.stop()
    # stopped is terminal for submit(): fail fast instead of hanging a
    # Future forever (the old restart-on-submit behavior raced the drain)
    with pytest.raises(BatcherStoppedError):
        mb.submit(np.zeros((2, 4), np.float32))
    # an explicit start() is still allowed to bring it back
    mb.start()
    fut = mb.submit(np.zeros((2, 4), np.float32))
    assert fut.result(timeout=30).shape == (2, 3)
    mb.stop()


# ------------------------------------------------------------- HTTP serving

def test_http_server_roundtrip_warmup_and_stats():
    net = _mlp()
    srv = InferenceServer(net, port=0, max_latency_ms=5.0).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        warm = cli.warmup([4], max_batch=8)
        assert warm["buckets"] == [1, 2, 4, 8]
        rs = np.random.RandomState(10)
        x = rs.rand(5, 4).astype(np.float32)
        assert np.array_equal(cli.predict(x),
                              np.asarray(net.output(x, bucketed=False)))
        v = cli.predict(x[0])                   # 1-D vector: batch of 1
        assert v.shape == (3,)
        assert np.array_equal(v, np.asarray(net.output(x[:1]))[0])
        stats = cli.stats()
        assert stats["engine"]["compiled_programs"] >= 4
        assert stats["batcher"]["requests"] >= 2
        # malformed payload comes back as a structured 400, not a hung
        # socket (and not a 500 — see test_resilience for the full matrix)
        with pytest.raises(ValueError, match="undecodable|reshape|decode"):
            cli._request("/predict", {"ndarray": {"shape": [2], "data": "!"}})
    finally:
        srv.stop()


def test_http_concurrent_clients_share_device_calls():
    net = _mlp()
    srv = InferenceServer(net, port=0, max_batch=64,
                          max_latency_ms=25.0).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        cli.warmup([4], max_batch=64)
        rs = np.random.RandomState(11)
        results = {}

        def call(i):
            x = rs.rand(1 + i % 3, 4).astype(np.float32)
            results[i] = (x, cli.predict(x))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 12
        for x, out in results.values():
            assert np.array_equal(out,
                                  np.asarray(net.output(x, bucketed=False)))
        assert srv.batcher.stats()["device_calls"] < 12
    finally:
        srv.stop()


# --------------------------------------------------------------- keep-alive

def test_http_keep_alive_reuses_and_reconnects():
    net = _mlp()
    srv = InferenceServer(net, port=0, max_latency_ms=5.0).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        assert cli.health()["status"] == "ok"
        c1 = cli._conn()
        assert c1.sock is not None            # server kept the socket open
        cli.stats()
        assert cli._conn() is c1              # same connection, no re-dial
        # a dead keep-alive socket (server restart, idle reap) reconnects
        # once inside the call instead of failing the request
        c1.sock.close()
        assert cli.health()["status"] == "ok"
        assert cli._conn() is not c1
        # opt-out path: one connection per call still works
        cold = InferenceClient(f"http://127.0.0.1:{srv.port}",
                               keep_alive=False)
        assert cold.health()["status"] == "ok"
        assert getattr(cold._local, "conn", None) is None
        # each worker thread gets its OWN persistent connection
        seen = {}

        def probe(i):
            cli.health()
            seen[i] = cli._conn()

        ts = [threading.Thread(target=probe, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        conns = list(seen.values()) + [cli._conn()]
        assert len({id(c) for c in conns}) == len(conns)
    finally:
        srv.stop()


def test_http_server_speaks_http11():
    net = _mlp()
    srv = InferenceServer(net, port=0).start()
    try:
        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/healthz")
        r1 = conn.getresponse()
        assert r1.version == 11
        assert r1.getheader("Content-Length") is not None
        r1.read()
        # errors carry Content-Length too — required for 1.1 persistence
        conn.request("GET", "/no-such-path")
        r2 = conn.getresponse()
        assert r2.status == 404
        assert r2.getheader("Content-Length") is not None
        r2.read()
        conn.close()
    finally:
        srv.stop()


def test_warmed_server_serves_first_predict_without_new_compiles():
    """Regression (compile-cache contract): after /warmup walks the bucket
    ladder through the persistent compile cache, the FIRST real /predict —
    over real HTTP — must ride an already-compiled program: trace_count
    (exact compiled-program counter) stays unchanged."""
    net = _mlp()
    srv = InferenceServer(net, port=0, max_latency_ms=2.0).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        cli.warmup([4], max_batch=8)
        compiled = cli.stats()["engine"]["compiled_programs"]
        assert compiled >= 4                  # ladder [1, 2, 4, 8]
        rs = np.random.RandomState(12)
        x = rs.rand(3, 4).astype(np.float32)
        out = cli.predict(x)
        assert np.array_equal(out, np.asarray(net.output(x, bucketed=False)))
        assert cli.stats()["engine"]["compiled_programs"] == compiled
    finally:
        srv.stop()


def test_client_reconnects_when_connection_dies_mid_response():
    """Regression: reconnect-once used to cover only sockets that died
    BEFORE the request went out; a connection dropped AFTER headers,
    mid-body, surfaced http.client.IncompleteRead to the caller. The
    client now redials once and replays — the exact path a replica
    restart-in-place exercises against pooled keep-alive connections."""
    import json
    import socket

    good = json.dumps({"tokens": [1, 2], "prompt_len": 1}).encode()
    accepts = []
    lst = socket.socket()
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(2)
    port = lst.getsockname()[1]

    def serve():
        for i in range(2):
            c, _ = lst.accept()
            accepts.append(i)
            c.settimeout(10)
            try:
                data = b""
                while b"\r\n\r\n" not in data:
                    data += c.recv(65536)
            except OSError:
                pass
            if i == 0:
                # promise 100 body bytes, deliver 10, then kill the socket
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Type: application/json\r\n"
                          b"Content-Length: 100\r\n\r\n0123456789")
            else:
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Type: application/json\r\n"
                          + f"Content-Length: {len(good)}\r\n\r\n".encode()
                          + good)
            c.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    cli = InferenceClient(f"http://127.0.0.1:{port}", retries=1)
    try:
        status, body, _ = cli.post_raw("/generate", b"{}")
        assert status == 200
        assert body == good                   # the REPLAYED full response
        assert accepts == [0, 1]              # it really redialed
    finally:
        cli.close()
        lst.close()
    t.join(timeout=10)


# ----------------------------------------------------- measured bucket ladder
class TestMeasuredLadder:
    """Measurement-driven bucket ladders (docs/QUANTIZATION.md §ladders):
    the DP over observed traffic never pads more than pow2, never uses
    more rungs, always keeps max_batch; the engine records its traffic and
    per-rung costs and can switch ladders live."""

    def test_bucket_for_with_explicit_ladder(self):
        lad = [3, 7, 16]
        assert bucket_for(1, 16, ladder=lad) == 3
        assert bucket_for(3, 16, ladder=lad) == 3
        assert bucket_for(4, 16, ladder=lad) == 7
        assert bucket_for(9, 16, ladder=lad) == 16
        assert bucket_for(99, 16, ladder=lad) == 16   # oversize: top rung
        with pytest.raises(ValueError):
            bucket_for(0, 16, ladder=lad)

    def test_autotune_never_worse_than_pow2(self):
        from deeplearning4j_tpu.serving.engine import autotune_ladder
        rs = np.random.RandomState(0)
        for max_batch in (16, 64, 256):
            sizes = rs.randint(1, max_batch + 1, 12)
            counts = {int(s): int(c) for s, c in
                      zip(sizes, rs.randint(1, 200, len(sizes)))}
            pow2 = bucket_ladder(max_batch)
            lad = autotune_ladder(counts, max_batch)
            assert lad[-1] == max_batch
            assert len(lad) <= len(pow2)
            pad_pow2 = sum(c * (bucket_for(s, max_batch) - s)
                           for s, c in counts.items())
            pad_auto = sum(c * (bucket_for(s, max_batch, ladder=lad) - s)
                           for s, c in counts.items())
            assert pad_auto <= pad_pow2, (lad, counts)

    def test_autotune_exact_sizes_reach_zero_pad(self):
        from deeplearning4j_tpu.serving.engine import autotune_ladder
        counts = {5: 100, 9: 40, 13: 7}
        lad = autotune_ladder(counts, 16)
        pad = sum(c * (bucket_for(s, 16, ladder=lad) - s)
                  for s, c in counts.items())
        assert pad == 0
        assert lad[-1] == 16

    def test_autotune_empty_traffic_is_pow2(self):
        from deeplearning4j_tpu.serving.engine import autotune_ladder
        assert autotune_ladder({}, 64) == bucket_ladder(64)

    def test_prune_ladder_merges_costly_rungs(self):
        from deeplearning4j_tpu.serving.engine import prune_ladder
        counts = {3: 1}            # one request near the bottom rung
        ladder = [4, 8, 16]
        # rung 4: compile costs 10s, padding 3→8 would cost ~4 rows of a
        # 1ms/row program — pruning must merge rung 4 upward
        costs = {4: {"compile_s": 10.0, "run_s": 0.004}}
        out = prune_ladder(ladder, counts, costs)
        assert 4 not in out and out[-1] == 16
        # cheap compile is kept
        costs = {4: {"compile_s": 1e-9, "run_s": 10.0}}
        assert prune_ladder([4, 8, 16], counts, costs) == [4, 8, 16]

    def test_engine_autotune_reduces_pad_and_respects_compiles(self):
        net = _mlp()
        eng = InferenceEngine(net, max_batch=32)
        X = np.zeros((32, 4), np.float32)
        for n in (5, 5, 5, 9, 9, 13):
            eng.predict_host(X[:n])
        pow2_traces = eng.trace_count
        proposal = eng.autotune(apply=True)
        assert proposal[-1] == 32
        assert len(proposal) <= len(bucket_ladder(32))
        assert eng.stats()["ladder_autotuned"]
        assert eng.stats()["bucket_ladder"] == proposal
        pad_before = eng.stats()["pad_rows"]
        for n in (5, 9, 13):
            eng.predict_host(X[:n])
        # exact-size rungs: zero NEW pad rows on the autotuned ladder
        assert eng.stats()["pad_rows"] == pad_before
        # switching ladders costs at most one compile per new rung
        assert eng.trace_count <= pow2_traces + len(proposal)

    def test_warmup_records_rung_costs(self):
        net = _mlp()
        eng = InferenceEngine(net, max_batch=8)
        ladder = eng.warmup((4,))
        assert sorted(eng.rung_costs) == sorted(ladder)
        for b in ladder:
            assert eng.rung_costs[b]["run_s"] >= 0.0
            assert eng.rung_costs[b]["compile_s"] >= 0.0
        # warmup traffic must not pollute the autotune histogram
        assert eng._size_counts == {}

    def test_tail_chunks_rebucket_not_top_bucket(self):
        """An oversize batch's TAIL goes through bucket_for(tail): 21 rows
        at max_batch=8 run as 8+8+5 → the 5-row tail pads to bucket 8 only
        by the pow2 rule (3 pad rows), never re-padded as a full top-bucket
        chunk; the pad-waste metric counts exactly those rows."""
        net = _mlp()
        eng = InferenceEngine(net, max_batch=8)
        X = np.random.RandomState(0).randn(21, 4).astype(np.float32)
        out = eng.predict_host(X)
        assert out.shape[0] == 21
        assert eng.stats()["pad_rows"] == 3          # only the 5→8 tail pad
        # and with a ladder rung at the tail size, the tail pads ZERO
        eng2 = InferenceEngine(net, max_batch=8)
        eng2.ladder = [5, 8]
        out2 = eng2.predict_host(X)
        assert np.allclose(out2, out, atol=1e-6)
        assert eng2.stats()["pad_rows"] == 0
