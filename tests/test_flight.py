"""Training flight recorder: in-trace telemetry, anomalies, black box.

The load-bearing claims pinned here:
- attaching a recorder leaves training BITWISE-identical (fit and
  fit_scan, MLN and CG, fused and per-leaf updater paths) at the SAME
  pinned compile count — the telemetry is one fused side-output of the
  one train-step program, and the K-sampling predicate is traced, so
  changing nothing but the recorder never adds a program;
- sampling cadence: only iterations with ``it % K == 0`` land in the
  ring, for the per-step path and for ``fit_scan`` blocks;
- the telemetry values are the real norms (update-norm matches the
  host-computed ``||new - old||``);
- the crash-safe spill: periodic spills leave a readable strict-prefix
  black box when the process dies between spills (simulated SIGKILL =
  read the file without the final ``spill()``), and a NaN-diverged run
  auto-spills a record naming the FIRST non-finite layer;
- the AnomalyDetector state machine (grad_spike vs EMA, ratio band,
  dead_update, sticky non_finite) and its ``health_info()`` contract;
- StatsListener's default recorder path syncs NO param leaf to host
  (the numpy path stays available as the parity oracle);
- the online trainer's post-step quarantine counter carries layer
  provenance as a SECOND suffixed label value (the plain reason keeps
  counting);
- ``GET /train/diagnostics`` serves the document (404 without a
  recorder) and ``flight_counter_events`` turns it into mergeable
  Perfetto counter tracks.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import (ComputationGraph, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.monitor.collect import (flight_counter_events,
                                                merge_docs)
from deeplearning4j_tpu.monitor.flight import (AnomalyDetector,
                                               FlightRecorder, STAT_COLS)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd


COL = {c: i for i, c in enumerate(STAT_COLS)}


def _mlp(seed=42, updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _cg(seed=7):
    g = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
         .weight_init("xavier")
         .graph_builder().add_inputs("in")
         .set_input_types(InputType.feed_forward(6))
         .add_layer("h", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "h")
         .set_outputs("out").build())
    return ComputationGraph(g).init()


def _data(n_in, n_out, batch=8, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(batch, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, batch)]
    return x, y


def _leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


def _bitwise(a, b):
    la, lb = _leaves(a), _leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(x, y) for x, y in zip(la, lb))


def _counter_value(name, **labels):
    fam = get_registry()._families.get(name)
    if fam is None:
        return 0.0
    if not fam.labelnames:
        return fam.value
    want = tuple(str(labels[k]) for k in fam.labelnames)
    for key, child in fam.children():
        if key == want:
            return child.value
    return 0.0


# ------------------------------------------------- bitwise + compile pins

def test_mln_fit_bitwise_on_vs_off_and_cadence():
    x, y = _data(4, 3)
    off, on = _mlp(), _mlp()
    rec = FlightRecorder(sample_every=2, capacity=64)
    on.attach_flight_recorder(rec)
    for _ in range(5):
        off.fit(x, y)
        on.fit(x, y)
    assert _bitwise(off.params, on.params)
    assert off._compile_count == on._compile_count == 1
    its = [r["iteration"] for r in rec.records()]
    assert its == [0, 2, 4]                       # K-cadence, per-step path
    assert rec.layer_names == ["0:DenseLayer", "1:OutputLayer"]


def test_mln_fit_scan_bitwise_on_vs_off_and_cadence():
    rs = np.random.RandomState(3)
    xs = rs.randn(6, 8, 4).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (6, 8))]
    off, on = _mlp(), _mlp()
    rec = FlightRecorder(sample_every=2, capacity=64)
    on.attach_flight_recorder(rec)
    off.fit_scan(xs, ys)
    on.fit_scan(xs, ys)
    assert _bitwise(off.params, on.params)
    assert off._compile_count == on._compile_count == 1
    assert [r["iteration"] for r in rec.records()] == [0, 2, 4]


def test_cg_fit_and_scan_bitwise_layer_names():
    x, y = _data(6, 3, seed=5)
    off, on = _cg(), _cg()
    rec = FlightRecorder(sample_every=1, capacity=64)
    on.attach_flight_recorder(rec)
    for _ in range(3):
        off.fit(x, y)
        on.fit(x, y)
    assert _bitwise(off.params, on.params)
    assert off._compile_count == on._compile_count == 1
    assert rec.layer_names == ["h", "out"]
    assert [r["iteration"] for r in rec.records()] == [0, 1, 2]

    rs = np.random.RandomState(9)
    xs = rs.randn(4, 8, 6).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (4, 8))]
    off2, on2 = _cg(), _cg()
    on2.attach_flight_recorder(FlightRecorder(sample_every=2))
    off2.fit_scan(xs, ys)
    on2.fit_scan(xs, ys)
    assert _bitwise(off2.params, on2.params)
    assert [r["iteration"] for r in on2._flight.records()] == [0, 2]


def test_per_leaf_updater_path_bitwise_on_vs_off():
    # the recorder composes with the per-leaf (non-fused) optimizer loop —
    # the fused path's parity oracle — identically
    from deeplearning4j_tpu.nn import fused_update
    x, y = _data(4, 3, seed=11)
    fused_update.set_fused_update(False)
    try:
        off, on = _mlp(), _mlp()
        on.attach_flight_recorder(FlightRecorder())
        for _ in range(3):
            off.fit(x, y)
            on.fit(x, y)
        assert _bitwise(off.params, on.params)
        assert off._compile_count == on._compile_count == 1
    finally:
        fused_update.set_fused_update(True)


# --------------------------------------------------------- value sanity

def test_telemetry_values_match_host_norms():
    x, y = _data(4, 3, seed=2)
    net = _mlp()
    rec = FlightRecorder(sample_every=1)
    net.attach_flight_recorder(rec)
    old = [_leaves(p) for p in net.params]
    net.fit(x, y)
    new = [_leaves(p) for p in net.params]
    stats = rec.latest()["stats"]
    assert stats.shape == (2, len(STAT_COLS))
    for i in range(2):
        upd = np.sqrt(sum(((b.astype(np.float64) - a) ** 2).sum()
                          for a, b in zip(old[i], new[i])))
        par = np.sqrt(sum((b.astype(np.float64) ** 2).sum()
                          for b in new[i]))
        assert np.isclose(stats[i, COL["update_norm"]], upd, rtol=1e-4)
        assert np.isclose(stats[i, COL["param_norm"]], par, rtol=1e-4)
        assert stats[i, COL["grad_norm"]] > 0.0
        assert stats[i, COL["non_finite"]] == 0.0


# ------------------------------------------------------- crash-safe spill

def test_periodic_spill_leaves_prefix_after_simulated_sigkill(tmp_path):
    path = str(tmp_path / "flight.json")
    x, y = _data(4, 3, seed=4)
    net = _mlp()
    rec = FlightRecorder(sample_every=1, capacity=64,
                         spill_path=path, spill_every=3)
    net.attach_flight_recorder(rec)
    # 9 iterations: the pending bound (8) forces one lazy drain, which
    # fires the every-3-records periodic spills; iteration 8 stays
    # pending and iterations 6..7 post-date the last spill
    for _ in range(9):
        net.fit(x, y)
    # simulated SIGKILL: read the file WITHOUT spill()/drain on this rec
    doc = FlightRecorder.restore(path)
    its = [r["iteration"] for r in doc["records"]]
    assert its == [0, 1, 2, 3, 4, 5]              # strict prefix survives
    assert doc["layer_names"] == rec.layer_names
    assert doc["cols"] == list(STAT_COLS)
    assert doc["records"][0]["stats"].shape == (2, len(STAT_COLS))
    assert doc["first_non_finite"] is None
    # a live process can always force the full ring out
    rec.spill()
    full = FlightRecorder.restore(path)
    assert [r["iteration"] for r in full["records"]] == list(range(9))


def test_nan_run_auto_spills_first_non_finite_layer(tmp_path):
    path = str(tmp_path / "blackbox.json")
    x, y = _data(4, 3, seed=6)
    net = _mlp()
    rec = FlightRecorder(sample_every=1, spill_path=path, spill_every=10_000)
    net.attach_flight_recorder(rec)
    net.fit(x, y)                                  # one healthy step
    bad = x.copy()
    bad[0, 0] = np.nan
    net.fit(bad, y)                                # poisons layer 0 forward
    fnf = rec.first_non_finite()
    assert fnf == {"layer": "0:DenseLayer", "iteration": 1}
    h = rec.health_info()
    assert h["status"] == "degraded" and h["reason"] == "train_non_finite"
    # the auto-spill fired on the non-finite record itself — the black
    # box on disk already names the layer, no clean shutdown needed
    doc = FlightRecorder.restore(path)
    assert doc["first_non_finite"]["layer"] == "0:DenseLayer"
    assert any(a["kind"] == "non_finite" for a in doc["anomalies"])
    assert json.load(open(path)) is not None       # valid JSON (no inf/nan)


# ------------------------------------------------------- anomaly machine

def _rows(gn=1.0, un=1e-2, pn=1.0, ratio=1e-2, nf=0.0, L=2, **overrides):
    """(L, 5) record; overrides like ``gn0=50`` target one layer."""
    a = np.zeros((L, len(STAT_COLS)), np.float32)
    for i in range(L):
        vals = {"gn": gn, "un": un, "pn": pn, "ratio": ratio, "nf": nf}
        for k, v in overrides.items():
            if k.endswith(str(i)):
                vals[k[:-len(str(i))]] = v
        a[i] = [vals["gn"], vals["un"], vals["pn"], vals["ratio"],
                vals["nf"]]
    return a


def test_anomaly_detector_grad_spike_and_recovery():
    det = AnomalyDetector(["a", "b"])
    it = 0
    for _ in range(4):                             # warmup, all accepted
        assert det.observe(it, _rows()) == []
        it += 1
    raised = det.observe(it, _rows(gn0=50.0))      # 50 > 10x EMA(=1)
    assert [a["kind"] for a in raised] == ["grad_spike"]
    assert raised[0]["layer"] == "a"
    h = det.health_info()
    assert h["status"] == "degraded" and h["reason"] == "train_anomaly"
    assert h["kinds"] == ["grad_spike"]
    for _ in range(5):                             # ages out of the window
        it += 1
        det.observe(it, _rows())
    assert det.active() == []
    assert det.health_info() is None


def test_anomaly_detector_ratio_band_and_dead_update():
    det = AnomalyDetector(["a", "b"])
    for it in range(3):
        det.observe(it, _rows())
    hi = det.observe(3, _rows(ratio1=0.5))
    assert [(a["kind"], a["layer"]) for a in hi] == [("ratio_high", "b")]
    lo = det.observe(4, _rows(ratio0=1e-6))
    assert [(a["kind"], a["layer"]) for a in lo] == [("ratio_low", "a")]
    # ratio anomalies never degrade health
    assert det.health_info() is None
    # dead_update: fires once, at exactly dead_steps consecutive zeros
    assert det.observe(5, _rows(un0=0.0)) == []
    assert det.observe(6, _rows(un0=0.0)) == []
    dead = det.observe(7, _rows(un0=0.0))
    assert [(a["kind"], a["layer"]) for a in dead] == [("dead_update", "a")]
    assert det.observe(8, _rows(un0=0.0)) == []    # no re-raise while dead
    assert det.observe(9, _rows()) == []           # recovery resets the run


def test_anomaly_detector_non_finite_sticky_and_mask():
    det = AnomalyDetector(["a", "b"])
    raised = det.observe(0, _rows(nf1=1.0))
    assert [(a["kind"], a["layer"]) for a in raised] == [("non_finite", "b")]
    assert det.first_non_finite == {"layer": "b", "iteration": 0}
    for it in range(1, 10):                        # sticky: never recovers
        det.observe(it, _rows())
    h = det.health_info()
    assert h["status"] == "degraded" and h["reason"] == "train_non_finite"
    assert h["first_non_finite"]["layer"] == "b"
    # a masked (paramless) layer's rows are never judged
    det2 = AnomalyDetector(["a", "b"], [True, False])
    assert det2.observe(0, _rows(nf1=1.0)) == []
    assert det2.first_non_finite is None


# ------------------------------------------------------- StatsListener

def test_stats_listener_recorder_path_syncs_no_params(monkeypatch):
    from deeplearning4j_tpu.ui import stats_listener as sl
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    # the legacy numpy path's only entry points are _summary and the
    # _last_params host copy — poison the former and watch the latter
    def _boom(*a, **k):
        raise AssertionError("recorder path must not host-sync params")
    monkeypatch.setattr(sl, "_summary", _boom)

    x, y = _data(4, 3, seed=8)
    net = _mlp()
    net.attach_flight_recorder(FlightRecorder(sample_every=1))
    storage = InMemoryStatsStorage()
    lst = sl.StatsListener(storage, session_id="flight_sess")
    net.set_listeners(lst)
    for _ in range(3):
        net.fit(x, y)
    assert lst._last_params is None                # no host param copy, ever
    ups = storage.get_all_updates("flight_sess")
    assert len(ups) == 3
    ps, us = ups[-1].param_stats, ups[-1].update_stats
    assert set(ps) == {"0:DenseLayer", "1:OutputLayer"}
    assert ps["0:DenseLayer"]["norm"] > 0
    assert us["0:DenseLayer"]["ratio"] > 0
    assert us["1:OutputLayer"]["non_finite"] == 0.0


def test_stats_listener_numpy_oracle_matches_recorder_path():
    from deeplearning4j_tpu.ui.stats_listener import StatsListener
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
    x, y = _data(4, 3, seed=12)
    net = _mlp()
    net.attach_flight_recorder(FlightRecorder(sample_every=1))
    storage = InMemoryStatsStorage()
    net.set_listeners(
        StatsListener(storage, session_id="rec_sess"),
        StatsListener(storage, session_id="np_sess", numpy_stats=True))
    for _ in range(2):
        net.fit(x, y)
    rec_up = storage.get_all_updates("rec_sess")[-1]
    np_up = storage.get_all_updates("np_sess")[-1]
    # the numpy oracle reports per-leaf norms ("0:DenseLayer/W"); the
    # recorder reports the per-layer group norm — they must agree as
    # sqrt(sum of squared leaf norms)
    for gname, stats in rec_up.param_stats.items():
        leaf_sq = sum(v["norm"] ** 2 for k, v in np_up.param_stats.items()
                      if k.startswith(gname + "/"))
        assert np.isclose(stats["norm"], np.sqrt(leaf_sq), rtol=1e-4)
    for gname, stats in rec_up.update_stats.items():
        leaf_sq = sum(v["norm"] ** 2 for k, v in np_up.update_stats.items()
                      if k.startswith(gname + "/"))
        assert np.isclose(stats["norm"], np.sqrt(leaf_sq), rtol=1e-4)


# ------------------------------------------------------- online provenance

def test_online_post_step_quarantine_carries_layer_provenance(tmp_path):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.online import OnlineTrainer
    from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager

    x, y = _data(4, 3, seed=13)
    plain0 = _counter_value("dl4jtpu_online_quarantined_batches_total",
                            reason="post_step_non_finite")

    # an absurd LR diverges in two steps: step 1 blows params up (still
    # finite), step 2's forward overflows → non-finite grads, which the
    # recorder pins to its layer before the post-step score check fires
    net = _mlp(updater=Sgd(1e15))
    net.attach_flight_recorder(FlightRecorder(sample_every=1))
    batches = iter([DataSet(x, y), DataSet(x, y)])
    tr = OnlineTrainer(net, batches, CheckpointManager(tmp_path / "a"),
                       batches_per_round=2)
    assert tr.run_round() is None                  # rejected, no checkpoint
    layer = net._flight.first_non_finite()["layer"]
    assert layer in ("0:DenseLayer", "1:OutputLayer")
    assert _counter_value("dl4jtpu_online_quarantined_batches_total",
                          reason="post_step_non_finite") == plain0 + 1
    assert _counter_value(
        "dl4jtpu_online_quarantined_batches_total",
        reason=f"post_step_non_finite:{layer}") >= 1

    # without a recorder only the PLAIN label moves — existing consumers
    # of {reason="post_step_non_finite"} see both runs
    suffixed = _counter_value(
        "dl4jtpu_online_quarantined_batches_total",
        reason=f"post_step_non_finite:{layer}")
    net2 = _mlp(updater=Sgd(1e15))
    tr2 = OnlineTrainer(net2, iter([DataSet(x, y), DataSet(x, y)]),
                        CheckpointManager(tmp_path / "b"),
                        batches_per_round=2)
    assert tr2.run_round() is None
    assert _counter_value("dl4jtpu_online_quarantined_batches_total",
                          reason="post_step_non_finite") == plain0 + 2
    assert _counter_value(
        "dl4jtpu_online_quarantined_batches_total",
        reason=f"post_step_non_finite:{layer}") == suffixed


# ------------------------------------------------------- HTTP + Perfetto

def test_train_diagnostics_endpoint_and_404():
    from deeplearning4j_tpu.serving import InferenceServer
    x, y = _data(4, 3, seed=14)
    net = _mlp()
    rec = FlightRecorder(sample_every=1)
    net.attach_flight_recorder(rec)
    for _ in range(3):
        net.fit(x, y)
    srv = InferenceServer(net, port=0, flight_recorder=rec).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/train/diagnostics"
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read().decode())
        assert doc["layers"] == ["0:DenseLayer", "1:OutputLayer"]
        assert doc["cols"] == list(STAT_COLS)
        assert [r_["iteration"] for r_ in doc["records"]] == [0, 1, 2]
        assert doc["first_non_finite"] is None
    finally:
        srv.stop()

    bare = InferenceServer(_mlp(), port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{bare.port}/train/diagnostics",
                timeout=10)
        assert e.value.code == 404
    finally:
        bare.stop()


def test_flight_counter_events_merge_into_fleet_trace():
    x, y = _data(4, 3, seed=15)
    net = _mlp()
    rec = FlightRecorder(sample_every=1)
    net.attach_flight_recorder(rec)
    for _ in range(2):
        net.fit(x, y)
    diag = rec.diagnostics()
    events = flight_counter_events(diag, pid="train-telemetry test")
    assert events[0]["ph"] == "M"
    counters = [e for e in events if e["ph"] == "C"]
    assert len(counters) == len(diag["records"]) * len(STAT_COLS)
    assert {e["name"] for e in counters} \
        == {f"train/{c}" for c in STAT_COLS}
    assert all(set(e["args"]) == {"0:DenseLayer", "1:OutputLayer"}
               for e in counters)
    merged = merge_docs([{"traceEvents": events}])
    timed = [e for e in merged["traceEvents"] if "ts" in e
             and e["ph"] != "M"]
    assert min(e["ts"] for e in timed) == 0        # rebased timeline
    assert any(e["ph"] == "M" for e in merged["traceEvents"])
