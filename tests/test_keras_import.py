"""Keras HDF5 import tests (model: reference deeplearning4j-modelimport/
src/test — e2e imports against bundled Keras HDF5 resources; here fixtures
are written in-test with h5py in the exact Keras 2 save format)."""

import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from deeplearning4j_tpu.modelimport import (
    KerasModelImport, import_keras_sequential_model_and_weights,
    import_keras_model_and_weights, InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException)


def _write_keras_h5(path, model_cfg, weights, training_cfg=None):
    """weights: {layer_name: [(weight_name, array), ...]}"""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_cfg).encode()
        if training_cfg is not None:
            f.attrs["training_config"] = json.dumps(training_cfg).encode()
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [k.encode() for k in weights], dtype="S64")
        for lname, ws in weights.items():
            g = mw.create_group(lname)
            g.attrs["weight_names"] = np.array(
                [wn.encode() for wn, _ in ws], dtype="S128")
            for wn, arr in ws:
                g.create_dataset(wn, data=arr)


def _seq_cfg(layers):
    return {"class_name": "Sequential", "config": {"layers": layers},
            "keras_version": "2.2.4", "backend": "tensorflow"}


def test_sequential_mlp_import(tmp_path):
    rng = np.random.default_rng(0)
    W1, b1 = rng.normal(size=(4, 8)).astype("f4"), rng.normal(size=(8,)).astype("f4")
    W2, b2 = rng.normal(size=(8, 3)).astype("f4"), rng.normal(size=(3,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 8, "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "d2", "units": 3, "activation": "softmax", "use_bias": True}},
    ])
    p = str(tmp_path / "mlp.h5")
    _write_keras_h5(p, cfg, {
        "d1": [("d1/kernel:0", W1), ("d1/bias:0", b1)],
        "d2": [("d2/kernel:0", W2), ("d2/bias:0", b2)],
    }, training_cfg={"loss": "categorical_crossentropy", "optimizer_config": {}})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(5, 4)).astype("f4")
    got = np.asarray(net.output(x))
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    want = np.exp(z - z.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # loss attached -> trainable output layer
    from deeplearning4j_tpu.nn.layers import OutputLayer
    assert isinstance(net.layers[-1], OutputLayer)
    assert net.layers[-1].loss == "mcxent"


def test_sequential_convnet_import(tmp_path):
    rng = np.random.default_rng(1)
    K = rng.normal(size=(3, 3, 2, 4), scale=0.5).astype("f4")
    bK = rng.normal(size=(4,)).astype("f4")
    Wd = rng.normal(size=(4 * 4 * 4, 5), scale=0.2).astype("f4")
    bd = rng.normal(size=(5,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "Conv2D", "config": {
            "name": "c1", "filters": 4, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "same", "activation": "relu",
            "use_bias": True, "data_format": "channels_last",
            "batch_input_shape": [None, 8, 8, 2]}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "p1", "pool_size": [2, 2], "strides": [2, 2],
            "padding": "valid"}},
        {"class_name": "Flatten", "config": {"name": "fl"}},
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 5, "activation": "linear",
            "use_bias": True}},
    ])
    p = str(tmp_path / "cnn.h5")
    _write_keras_h5(p, cfg, {
        "c1": [("c1/kernel:0", K), ("c1/bias:0", bK)],
        "d1": [("d1/kernel:0", Wd), ("d1/bias:0", bd)],
    })
    net = KerasModelImport.import_keras_model(p)
    # compare against the same net built natively with the same weights
    import jax.numpy as jnp
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              SubsamplingLayer, DenseLayer)
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    ref = NeuralNetConfiguration.builder().list() \
        .layer(ConvolutionLayer(n_out=4, kernel_size=3, stride=1,
                                convolution_mode="same", activation="relu")) \
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2)) \
        .layer(DenseLayer(n_out=5, activation="identity")) \
        .set_input_type(InputType.convolutional(8, 8, 2)).build()
    from deeplearning4j_tpu import MultiLayerNetwork
    refnet = MultiLayerNetwork(ref).init()
    refnet.params[0]["W"] = jnp.asarray(K)
    refnet.params[0]["b"] = jnp.asarray(bK)
    refnet.params[2]["W"] = jnp.asarray(Wd)
    refnet.params[2]["b"] = jnp.asarray(bd)
    x = rng.normal(size=(3, 8, 8, 2)).astype("f4")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(refnet.output(x)),
                               rtol=1e-4, atol=1e-5)


def test_lstm_gate_reorder(tmp_path):
    rng = np.random.default_rng(2)
    I, H, T, B = 3, 4, 6, 2
    K = rng.normal(size=(I, 4 * H), scale=0.3).astype("f4")
    R = rng.normal(size=(H, 4 * H), scale=0.3).astype("f4")
    b = rng.normal(size=(4 * H,), scale=0.1).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "LSTM", "config": {
            "name": "l1", "units": H, "activation": "tanh",
            "recurrent_activation": "sigmoid", "use_bias": True,
            "return_sequences": True, "batch_input_shape": [None, T, I]}},
    ])
    p = str(tmp_path / "lstm.h5")
    _write_keras_h5(p, cfg, {
        "l1": [("l1/kernel:0", K), ("l1/recurrent_kernel:0", R),
               ("l1/bias:0", b)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(B, T, I)).astype("f4")
    got = np.asarray(net.output(x))

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    h = np.zeros((B, H), "f4")
    c = np.zeros((B, H), "f4")
    want = []
    for t in range(T):
        z = x[:, t] @ K + h @ R + b
        i = sig(z[:, 0:H])
        f = sig(z[:, H:2 * H])
        g = np.tanh(z[:, 2 * H:3 * H])
        o = sig(z[:, 3 * H:4 * H])
        c = f * c + i * g
        h = o * np.tanh(c)
        want.append(h.copy())
    want = np.stack(want, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_lstm_return_sequences_false(tmp_path):
    """Keras default return_sequences=False must import as last-step output."""
    rng = np.random.default_rng(5)
    I, H, T = 3, 4, 5
    K = rng.normal(size=(I, 4 * H), scale=0.3).astype("f4")
    R = rng.normal(size=(H, 4 * H), scale=0.3).astype("f4")
    b = np.zeros(4 * H, "f4")
    Wd = rng.normal(size=(H, 2), scale=0.5).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "LSTM", "config": {
            "name": "l1", "units": H, "activation": "tanh",
            "recurrent_activation": "sigmoid", "use_bias": True,
            "batch_input_shape": [None, T, I]}},
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 2, "activation": "linear",
            "use_bias": False}},
    ])
    p = str(tmp_path / "lstm_cls.h5")
    _write_keras_h5(p, cfg, {
        "l1": [("l1/kernel:0", K), ("l1/recurrent_kernel:0", R),
               ("l1/bias:0", b)],
        "d1": [("d1/kernel:0", Wd)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, T, I)).astype("f4")
    got = np.asarray(net.output(x))
    assert got.shape == (2, 2)   # (B, k), not (B, T, k)


def test_batchnorm_running_stats(tmp_path):
    gamma = np.array([1.5, 0.5], "f4")
    beta = np.array([0.1, -0.2], "f4")
    mean = np.array([0.3, -0.4], "f4")
    var = np.array([2.0, 0.5], "f4")
    cfg = _seq_cfg([
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 2, "activation": "linear",
            "use_bias": False, "batch_input_shape": [None, 2]}},
        {"class_name": "BatchNormalization", "config": {
            "name": "bn", "epsilon": 1e-3, "momentum": 0.99}},
    ])
    W = np.eye(2, dtype="f4")
    p = str(tmp_path / "bn.h5")
    _write_keras_h5(p, cfg, {
        "d1": [("d1/kernel:0", W)],
        "bn": [("bn/gamma:0", gamma), ("bn/beta:0", beta),
               ("bn/moving_mean:0", mean), ("bn/moving_variance:0", var)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = np.array([[1.0, 1.0], [0.0, 2.0]], "f4")
    got = np.asarray(net.output(x))   # inference mode -> running stats
    want = gamma * (x - mean) / np.sqrt(var + 1e-3) + beta
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_functional_model_with_add(tmp_path):
    rng = np.random.default_rng(3)
    W1 = rng.normal(size=(4, 4)).astype("f4")
    W2 = rng.normal(size=(4, 4)).astype("f4")
    Wo = rng.normal(size=(4, 2)).astype("f4")
    cfg = {
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "in",
                 "config": {"name": "in", "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "a",
                 "config": {"name": "a", "units": 4, "activation": "relu",
                            "use_bias": False},
                 "inbound_nodes": [[["in", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "b",
                 "config": {"name": "b", "units": 4, "activation": "relu",
                            "use_bias": False},
                 "inbound_nodes": [[["in", 0, 0, {}]]]},
                {"class_name": "Add", "name": "add",
                 "config": {"name": "add"},
                 "inbound_nodes": [[["a", 0, 0, {}], ["b", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 2,
                            "activation": "linear", "use_bias": False},
                 "inbound_nodes": [[["add", 0, 0, {}]]]},
            ],
            "input_layers": [["in", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
        "keras_version": "2.2.4", "backend": "tensorflow",
    }
    p = str(tmp_path / "fn.h5")
    _write_keras_h5(p, cfg, {
        "a": [("a/kernel:0", W1)],
        "b": [("b/kernel:0", W2)],
        "out": [("out/kernel:0", Wo)],
    })
    net = import_keras_model_and_weights(p)
    x = rng.normal(size=(3, 4)).astype("f4")
    got = np.asarray(net.output(x))
    want = (np.maximum(x @ W1, 0) + np.maximum(x @ W2, 0)) @ Wo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_errors(tmp_path):
    p = str(tmp_path / "bad.h5")
    with h5py.File(p, "w") as f:
        f.create_dataset("x", data=np.zeros(3))
    with pytest.raises(InvalidKerasConfigurationException):
        import_keras_sequential_model_and_weights(p)
    cfg = _seq_cfg([{"class_name": "Permute", "config": {
        "name": "r", "dims": [2, 1],
        "batch_input_shape": [None, 4, 3]}}])
    p2 = str(tmp_path / "unsup.h5")
    _write_keras_h5(p2, cfg, {})
    with pytest.raises(UnsupportedKerasConfigurationException):
        import_keras_sequential_model_and_weights(p2)


# --------------------------------------------------------------- golden files
# Real Keras-produced HDF5 fixtures (generated by Keras 3.13 / TF backend,
# legacy h5 writer) committed under tests/resources/keras_golden with their
# recorded predictions — the importer must forward-match actual Keras output,
# not a self-authored encoding of the format (parity role: the reference's
# bundled modelimport/src/test/resources fixtures).

import os

_GOLD = os.path.join(os.path.dirname(__file__), "resources", "keras_golden")


def test_golden_sequential_conv1d_reshape():
    """Real Keras Sequential: Conv1D(same) > MaxPooling1D > Conv1D >
    UpSampling1D > ZeroPadding1D > Flatten > Dense > Reshape > Flatten >
    Dense(softmax). Forward must match Keras's own predictions."""
    net = KerasModelImport.import_keras_model(
        os.path.join(_GOLD, "keras_golden.h5"))
    d = np.load(os.path.join(_GOLD, "keras_golden_io.npz"))
    out = np.asarray(net.output(d["x"]))
    np.testing.assert_allclose(out, d["y"], atol=1e-5)


def test_golden_functional_conv1d_concat():
    """Real Keras functional model: two Conv1D branches > Concatenate >
    MaxPooling1D > Flatten > Dense > Reshape > Flatten > Dense (Keras 3
    keras_history inbound format)."""
    net = KerasModelImport.import_keras_model(
        os.path.join(_GOLD, "keras_golden_functional.h5"))
    d = np.load(os.path.join(_GOLD, "keras_golden_functional_io.npz"))
    out = np.asarray(net.output(d["x"]))
    np.testing.assert_allclose(out, d["y"], atol=1e-5)


# ------------------------------------------------- new translator coverage

def test_conv1d_pipeline_import(tmp_path):
    """Self-authored Keras-2-format Conv1D+pool+pad+upsample pipeline
    (covers the Keras 2 key spellings, which the goldens — Keras 3 — don't)."""
    rng = np.random.default_rng(5)
    W = rng.normal(size=(3, 4, 6)).astype("f4")
    b = rng.normal(size=(6,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "Conv1D", "config": {
            "name": "c1", "filters": 6, "kernel_size": [3], "strides": [1],
            "padding": "same", "activation": "relu", "use_bias": True,
            "batch_input_shape": [None, 8, 4]}},
        {"class_name": "ZeroPadding1D", "config": {"name": "zp",
                                                   "padding": [1, 1]}},
        {"class_name": "MaxPooling1D", "config": {
            "name": "p1", "pool_size": [2], "strides": [2],
            "padding": "valid"}},
        {"class_name": "UpSampling1D", "config": {"name": "u1", "size": 2}},
        {"class_name": "Flatten", "config": {"name": "f"}},
        {"class_name": "Dense", "config": {
            "name": "d", "units": 3, "activation": "softmax",
            "use_bias": True}},
    ])
    Wd = rng.normal(size=(60, 3)).astype("f4")
    bd = rng.normal(size=(3,)).astype("f4")
    p = str(tmp_path / "conv1d.h5")
    _write_keras_h5(p, cfg, {
        "c1": [("c1/kernel:0", W), ("c1/bias:0", b)],
        "d": [("d/kernel:0", Wd), ("d/bias:0", bd)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, 8, 4)).astype("f4")
    out = np.asarray(net.output(x))
    assert out.shape == (2, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-5)  # softmax rows

    from deeplearning4j_tpu.nn.layers import (
        Convolution1DLayer, ZeroPadding1DLayer, Subsampling1DLayer,
        Upsampling1D, FlattenLayer)
    kinds = [type(l) for l in net.layers]
    assert Convolution1DLayer in kinds and Subsampling1DLayer in kinds
    assert ZeroPadding1DLayer in kinds and Upsampling1D in kinds
    assert FlattenLayer in kinds


def test_atrous_and_lrn_import(tmp_path):
    """Keras-1 AtrousConvolution2D (dilated conv) + contrib LRN2D translate
    to ConvolutionLayer(dilation) and LocalResponseNormalization."""
    rng = np.random.default_rng(6)
    W = rng.normal(size=(3, 3, 2, 4)).astype("f4")
    b = rng.normal(size=(4,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "AtrousConvolution2D", "config": {
            "name": "ac", "nb_filter": 4, "nb_row": 3, "nb_col": 3,
            "atrous_rate": [2, 2], "border_mode": "same",
            "activation": "relu", "bias": True,
            "batch_input_shape": [None, 8, 8, 2]}},
        {"class_name": "LRN2D", "config": {
            "name": "lrn", "alpha": 1e-4, "beta": 0.75, "k": 2, "n": 5}},
        {"class_name": "Flatten", "config": {"name": "f"}},
        {"class_name": "Dense", "config": {
            "name": "d", "units": 2, "activation": "softmax",
            "use_bias": True}},
    ])
    Wd = rng.normal(size=(256, 2)).astype("f4")
    bd = rng.normal(size=(2,)).astype("f4")
    p = str(tmp_path / "atrous.h5")
    _write_keras_h5(p, cfg, {
        "ac": [("ac/kernel:0", W), ("ac/bias:0", b)],
        "d": [("d/kernel:0", Wd), ("d/bias:0", bd)],
    })
    net = import_keras_sequential_model_and_weights(p)
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              LocalResponseNormalization)
    assert isinstance(net.layers[0], ConvolutionLayer)
    assert net.layers[0].dilation == (2, 2)
    assert isinstance(net.layers[1], LocalResponseNormalization)
    x = rng.normal(size=(2, 8, 8, 2)).astype("f4")
    out = np.asarray(net.output(x))
    assert out.shape == (2, 2) and np.isfinite(out).all()


def test_avg_pool_same_padding_keras_semantics():
    """Imported AveragePooling excludes padded positions from the divisor
    (Keras/TF) while the native layer default divides by kernel size
    (reference semantics) — both must be available."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.layers import Subsampling1DLayer

    x = jnp.asarray(np.arange(1.0, 6.0, dtype=np.float32)
                    .reshape(1, 5, 1))        # T=5: [1..5]
    keras_sem = Subsampling1DLayer(pooling_type="avg", kernel_size=2,
                                   stride=2, convolution_mode="same",
                                   avg_count_includes_padding=False)
    y, _ = keras_sem.apply({}, x)
    # windows: [1,2] [3,4] [5] -> 1.5, 3.5, 5.0 (last divisor is 1)
    np.testing.assert_allclose(np.asarray(y).ravel(), [1.5, 3.5, 5.0])
    ref_sem = Subsampling1DLayer(pooling_type="avg", kernel_size=2,
                                 stride=2, convolution_mode="same")
    y, _ = ref_sem.apply({}, x)
    np.testing.assert_allclose(np.asarray(y).ravel(), [1.5, 3.5, 2.5])


def test_reshape_wildcard_and_channels_first_guard(tmp_path):
    """Keras Reshape with a -1 dim resolves from the input size; a 3-D
    Reshape inside a channels_first model is refused loudly."""
    from deeplearning4j_tpu.nn.layers import ReshapeLayer
    from deeplearning4j_tpu.nn.conf.inputs import InputType

    r = ReshapeLayer(target_shape=(4, -1))
    t = r.output_type(InputType.feed_forward(12))
    assert t.kind == "rnn" and t.timeseries_length == 4 and t.size == 3

    cfg = _seq_cfg([
        {"class_name": "Conv2D", "config": {
            "name": "c", "filters": 2, "kernel_size": [3, 3],
            "data_format": "channels_first", "padding": "same",
            "batch_input_shape": [None, 2, 8, 8]}},
        {"class_name": "Reshape", "config": {
            "name": "r", "target_shape": [2, 32, 2]}},
    ])
    p = str(tmp_path / "cf_reshape.h5")
    _write_keras_h5(p, cfg, {})
    with pytest.raises(UnsupportedKerasConfigurationException):
        import_keras_sequential_model_and_weights(p)
