"""Keras HDF5 import tests (model: reference deeplearning4j-modelimport/
src/test — e2e imports against bundled Keras HDF5 resources; here fixtures
are written in-test with h5py in the exact Keras 2 save format)."""

import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from deeplearning4j_tpu.modelimport import (
    KerasModelImport, import_keras_sequential_model_and_weights,
    import_keras_model_and_weights, InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException)


def _write_keras_h5(path, model_cfg, weights, training_cfg=None):
    """weights: {layer_name: [(weight_name, array), ...]}"""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_cfg).encode()
        if training_cfg is not None:
            f.attrs["training_config"] = json.dumps(training_cfg).encode()
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [k.encode() for k in weights], dtype="S64")
        for lname, ws in weights.items():
            g = mw.create_group(lname)
            g.attrs["weight_names"] = np.array(
                [wn.encode() for wn, _ in ws], dtype="S128")
            for wn, arr in ws:
                g.create_dataset(wn, data=arr)


def _seq_cfg(layers):
    return {"class_name": "Sequential", "config": {"layers": layers},
            "keras_version": "2.2.4", "backend": "tensorflow"}


def test_sequential_mlp_import(tmp_path):
    rng = np.random.default_rng(0)
    W1, b1 = rng.normal(size=(4, 8)).astype("f4"), rng.normal(size=(8,)).astype("f4")
    W2, b2 = rng.normal(size=(8, 3)).astype("f4"), rng.normal(size=(3,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 8, "activation": "relu",
            "use_bias": True, "batch_input_shape": [None, 4]}},
        {"class_name": "Dense", "config": {
            "name": "d2", "units": 3, "activation": "softmax", "use_bias": True}},
    ])
    p = str(tmp_path / "mlp.h5")
    _write_keras_h5(p, cfg, {
        "d1": [("d1/kernel:0", W1), ("d1/bias:0", b1)],
        "d2": [("d2/kernel:0", W2), ("d2/bias:0", b2)],
    }, training_cfg={"loss": "categorical_crossentropy", "optimizer_config": {}})
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(5, 4)).astype("f4")
    got = np.asarray(net.output(x))
    h = np.maximum(x @ W1 + b1, 0.0)
    z = h @ W2 + b2
    want = np.exp(z - z.max(-1, keepdims=True))
    want /= want.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # loss attached -> trainable output layer
    from deeplearning4j_tpu.nn.layers import OutputLayer
    assert isinstance(net.layers[-1], OutputLayer)
    assert net.layers[-1].loss == "mcxent"


def test_sequential_convnet_import(tmp_path):
    rng = np.random.default_rng(1)
    K = rng.normal(size=(3, 3, 2, 4), scale=0.5).astype("f4")
    bK = rng.normal(size=(4,)).astype("f4")
    Wd = rng.normal(size=(4 * 4 * 4, 5), scale=0.2).astype("f4")
    bd = rng.normal(size=(5,)).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "Conv2D", "config": {
            "name": "c1", "filters": 4, "kernel_size": [3, 3],
            "strides": [1, 1], "padding": "same", "activation": "relu",
            "use_bias": True, "data_format": "channels_last",
            "batch_input_shape": [None, 8, 8, 2]}},
        {"class_name": "MaxPooling2D", "config": {
            "name": "p1", "pool_size": [2, 2], "strides": [2, 2],
            "padding": "valid"}},
        {"class_name": "Flatten", "config": {"name": "fl"}},
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 5, "activation": "linear",
            "use_bias": True}},
    ])
    p = str(tmp_path / "cnn.h5")
    _write_keras_h5(p, cfg, {
        "c1": [("c1/kernel:0", K), ("c1/bias:0", bK)],
        "d1": [("d1/kernel:0", Wd), ("d1/bias:0", bd)],
    })
    net = KerasModelImport.import_keras_model(p)
    # compare against the same net built natively with the same weights
    import jax.numpy as jnp
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                              SubsamplingLayer, DenseLayer)
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    ref = NeuralNetConfiguration.builder().list() \
        .layer(ConvolutionLayer(n_out=4, kernel_size=3, stride=1,
                                convolution_mode="same", activation="relu")) \
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2)) \
        .layer(DenseLayer(n_out=5, activation="identity")) \
        .set_input_type(InputType.convolutional(8, 8, 2)).build()
    from deeplearning4j_tpu import MultiLayerNetwork
    refnet = MultiLayerNetwork(ref).init()
    refnet.params[0]["W"] = jnp.asarray(K)
    refnet.params[0]["b"] = jnp.asarray(bK)
    refnet.params[2]["W"] = jnp.asarray(Wd)
    refnet.params[2]["b"] = jnp.asarray(bd)
    x = rng.normal(size=(3, 8, 8, 2)).astype("f4")
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(refnet.output(x)),
                               rtol=1e-4, atol=1e-5)


def test_lstm_gate_reorder(tmp_path):
    rng = np.random.default_rng(2)
    I, H, T, B = 3, 4, 6, 2
    K = rng.normal(size=(I, 4 * H), scale=0.3).astype("f4")
    R = rng.normal(size=(H, 4 * H), scale=0.3).astype("f4")
    b = rng.normal(size=(4 * H,), scale=0.1).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "LSTM", "config": {
            "name": "l1", "units": H, "activation": "tanh",
            "recurrent_activation": "sigmoid", "use_bias": True,
            "return_sequences": True, "batch_input_shape": [None, T, I]}},
    ])
    p = str(tmp_path / "lstm.h5")
    _write_keras_h5(p, cfg, {
        "l1": [("l1/kernel:0", K), ("l1/recurrent_kernel:0", R),
               ("l1/bias:0", b)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(B, T, I)).astype("f4")
    got = np.asarray(net.output(x))

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    h = np.zeros((B, H), "f4")
    c = np.zeros((B, H), "f4")
    want = []
    for t in range(T):
        z = x[:, t] @ K + h @ R + b
        i = sig(z[:, 0:H])
        f = sig(z[:, H:2 * H])
        g = np.tanh(z[:, 2 * H:3 * H])
        o = sig(z[:, 3 * H:4 * H])
        c = f * c + i * g
        h = o * np.tanh(c)
        want.append(h.copy())
    want = np.stack(want, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_lstm_return_sequences_false(tmp_path):
    """Keras default return_sequences=False must import as last-step output."""
    rng = np.random.default_rng(5)
    I, H, T = 3, 4, 5
    K = rng.normal(size=(I, 4 * H), scale=0.3).astype("f4")
    R = rng.normal(size=(H, 4 * H), scale=0.3).astype("f4")
    b = np.zeros(4 * H, "f4")
    Wd = rng.normal(size=(H, 2), scale=0.5).astype("f4")
    cfg = _seq_cfg([
        {"class_name": "LSTM", "config": {
            "name": "l1", "units": H, "activation": "tanh",
            "recurrent_activation": "sigmoid", "use_bias": True,
            "batch_input_shape": [None, T, I]}},
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 2, "activation": "linear",
            "use_bias": False}},
    ])
    p = str(tmp_path / "lstm_cls.h5")
    _write_keras_h5(p, cfg, {
        "l1": [("l1/kernel:0", K), ("l1/recurrent_kernel:0", R),
               ("l1/bias:0", b)],
        "d1": [("d1/kernel:0", Wd)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = rng.normal(size=(2, T, I)).astype("f4")
    got = np.asarray(net.output(x))
    assert got.shape == (2, 2)   # (B, k), not (B, T, k)


def test_batchnorm_running_stats(tmp_path):
    gamma = np.array([1.5, 0.5], "f4")
    beta = np.array([0.1, -0.2], "f4")
    mean = np.array([0.3, -0.4], "f4")
    var = np.array([2.0, 0.5], "f4")
    cfg = _seq_cfg([
        {"class_name": "Dense", "config": {
            "name": "d1", "units": 2, "activation": "linear",
            "use_bias": False, "batch_input_shape": [None, 2]}},
        {"class_name": "BatchNormalization", "config": {
            "name": "bn", "epsilon": 1e-3, "momentum": 0.99}},
    ])
    W = np.eye(2, dtype="f4")
    p = str(tmp_path / "bn.h5")
    _write_keras_h5(p, cfg, {
        "d1": [("d1/kernel:0", W)],
        "bn": [("bn/gamma:0", gamma), ("bn/beta:0", beta),
               ("bn/moving_mean:0", mean), ("bn/moving_variance:0", var)],
    })
    net = import_keras_sequential_model_and_weights(p)
    x = np.array([[1.0, 1.0], [0.0, 2.0]], "f4")
    got = np.asarray(net.output(x))   # inference mode -> running stats
    want = gamma * (x - mean) / np.sqrt(var + 1e-3) + beta
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_functional_model_with_add(tmp_path):
    rng = np.random.default_rng(3)
    W1 = rng.normal(size=(4, 4)).astype("f4")
    W2 = rng.normal(size=(4, 4)).astype("f4")
    Wo = rng.normal(size=(4, 2)).astype("f4")
    cfg = {
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "in",
                 "config": {"name": "in", "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "a",
                 "config": {"name": "a", "units": 4, "activation": "relu",
                            "use_bias": False},
                 "inbound_nodes": [[["in", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "b",
                 "config": {"name": "b", "units": 4, "activation": "relu",
                            "use_bias": False},
                 "inbound_nodes": [[["in", 0, 0, {}]]]},
                {"class_name": "Add", "name": "add",
                 "config": {"name": "add"},
                 "inbound_nodes": [[["a", 0, 0, {}], ["b", 0, 0, {}]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "units": 2,
                            "activation": "linear", "use_bias": False},
                 "inbound_nodes": [[["add", 0, 0, {}]]]},
            ],
            "input_layers": [["in", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
        "keras_version": "2.2.4", "backend": "tensorflow",
    }
    p = str(tmp_path / "fn.h5")
    _write_keras_h5(p, cfg, {
        "a": [("a/kernel:0", W1)],
        "b": [("b/kernel:0", W2)],
        "out": [("out/kernel:0", Wo)],
    })
    net = import_keras_model_and_weights(p)
    x = rng.normal(size=(3, 4)).astype("f4")
    got = np.asarray(net.output(x))
    want = (np.maximum(x @ W1, 0) + np.maximum(x @ W2, 0)) @ Wo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_errors(tmp_path):
    p = str(tmp_path / "bad.h5")
    with h5py.File(p, "w") as f:
        f.create_dataset("x", data=np.zeros(3))
    with pytest.raises(InvalidKerasConfigurationException):
        import_keras_sequential_model_and_weights(p)
    cfg = _seq_cfg([{"class_name": "Reshape", "config": {
        "name": "r", "target_shape": [2, 2],
        "batch_input_shape": [None, 4]}}])
    p2 = str(tmp_path / "unsup.h5")
    _write_keras_h5(p2, cfg, {})
    with pytest.raises(UnsupportedKerasConfigurationException):
        import_keras_sequential_model_and_weights(p2)
