"""Input-pipeline tests: multi-worker ETL (AsyncDataSetIterator workers=N),
device-resident prefetch (DevicePrefetcher), per-stage stall accounting
(PipelineTimer), and the uint8 wire + device-side normalizer path through
fit/evaluate. Stress/soak variants are marked slow; one fast overlap smoke
test stays in tier-1."""

import queue
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator, DataSetIterator, ListDataSetIterator,
    MultipleEpochsIterator)
from deeplearning4j_tpu.data.prefetcher import DevicePrefetcher
from deeplearning4j_tpu.util.timing import PipelineTimer


def _mk_ds(n=64, d=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > d / 2).astype(int)]
    return DataSet(x, y)


def _base_iter(n=64, batch=8, seed=0):
    return ListDataSetIterator(_mk_ds(n, seed=seed), batch, shuffle=False)


def _features(it):
    return [np.asarray(ds.features) for ds in it]


# --------------------------------------------------------- multi-worker ETL

def test_multiworker_ordered_matches_base_exactly():
    base_seq = _features(_base_iter())
    for workers in (1, 2, 4):
        a = AsyncDataSetIterator(_base_iter(), queue_size=3, workers=workers,
                                 ordered=True)
        got = _features(a)
        assert len(got) == len(base_seq)
        for g, b in zip(got, base_seq):
            np.testing.assert_array_equal(g, b)


def test_multiworker_unordered_same_multiset():
    base_seq = _features(_base_iter())
    a = AsyncDataSetIterator(_base_iter(), queue_size=3, workers=4,
                             ordered=False)
    got = _features(a)
    assert len(got) == len(base_seq)
    key = lambda arr: arr.tobytes()
    assert sorted(key(g) for g in got) == sorted(key(b) for b in base_seq)


def test_transform_runs_and_preserves_order():
    """transform (the decode/augment hook) runs inside the workers; ordered
    mode still emits exact base order."""
    seen_threads = set()

    def tx(ds):
        seen_threads.add(threading.get_ident())
        return DataSet(np.asarray(ds.features) * 2.0, ds.labels)

    base_seq = _features(_base_iter())
    a = AsyncDataSetIterator(_base_iter(), queue_size=3, workers=3,
                             transform=tx)
    got = _features(a)
    for g, b in zip(got, base_seq):
        np.testing.assert_array_equal(g, b * 2.0)
    # the transform ran on worker threads, not the consumer
    assert threading.get_ident() not in seen_threads


def test_etl_error_delivers_prefix_then_raises():
    """A worker error propagates to the consumer; every in-order batch
    decoded before the failure is delivered first."""
    def tx(ds):
        if float(np.asarray(ds.features)[0, 0]) < 0:  # batch 3 poisoned
            raise ValueError("decode failed")
        return ds

    ds = _mk_ds(64)
    feats = np.asarray(ds.features).copy()
    feats[3 * 8, 0] = -1.0
    it = ListDataSetIterator(DataSet(feats, ds.labels), 8, shuffle=False)
    a = AsyncDataSetIterator(it, queue_size=2, workers=2, transform=tx)
    got = []
    with pytest.raises(ValueError, match="decode failed"):
        for b in a:
            got.append(b)
    assert len(got) == 3                     # exactly the pre-error prefix
    for i, g in enumerate(got):
        np.testing.assert_array_equal(np.asarray(g.features),
                                      feats[i * 8:(i + 1) * 8])


# ------------------------------------------------- shutdown / reset races

def test_shutdown_joins_workers_blocked_on_full_queue():
    """Regression: _shutdown vs worker q.put race. Workers blocked putting
    into a full queue must exit promptly — one drain pass is not enough
    because a worker can refill the freed slot before seeing the stop
    flag."""
    a = AsyncDataSetIterator(_base_iter(n=512, batch=4), queue_size=1,
                             workers=4)
    next(iter(a))                  # start workers, let the queue fill
    time.sleep(0.2)                # all workers now blocked in q.put
    threads = list(a._threads)
    t0 = time.perf_counter()
    a._shutdown()
    assert time.perf_counter() - t0 < 5.0
    assert all(not t.is_alive() for t in threads), "leaked worker thread"
    assert a._threads == [] and a._q is None


def test_double_reset_and_reuse():
    base_seq = _features(_base_iter())
    a = AsyncDataSetIterator(_base_iter(), queue_size=2, workers=2)
    a.reset()
    a.reset()                      # double reset must not wedge or leak
    got = _features(a)
    for g, b in zip(got, base_seq):
        np.testing.assert_array_equal(g, b)
    # partial consumption then re-iteration restarts cleanly
    it = iter(a)
    next(it)
    got = _features(a)
    assert len(got) == len(base_seq)
    for g, b in zip(got, base_seq):
        np.testing.assert_array_equal(g, b)
    a._shutdown()


@pytest.mark.slow
def test_reset_soak():
    a = AsyncDataSetIterator(_base_iter(n=128, batch=4), queue_size=2,
                             workers=4)
    for _ in range(40):
        it = iter(a)
        next(it)
        a.reset()                  # reset with workers mid-flight
    n_alive_before = threading.active_count()
    a._shutdown()
    assert threading.active_count() <= n_alive_before


@pytest.mark.slow
def test_backpressure_bounded_queue_soak():
    """Slow consumer: the bounded queue must hold (workers + queue_size)
    decoded batches at most — backpressure reaches the base."""
    decoded = []

    def tx(ds):
        decoded.append(1)
        return ds

    a = AsyncDataSetIterator(_base_iter(n=256, batch=4), queue_size=4,
                             workers=2, transform=tx)
    it = iter(a)
    next(it)
    time.sleep(0.3)                # workers fill the queue, then block
    # queue(4) + 2 in-flight per worker + 1 consumed + ordering stash slack
    assert len(decoded) <= 4 + 2 * 2 + 1 + 2
    # keep pulling from the SAME pass (a fresh iter() would restart it)
    rest = 0
    while True:
        try:
            next(a)
        except StopIteration:
            break
        rest += 1
    assert rest == 256 // 4 - 1
    a._shutdown()


# -------------------------------------------- compose: MultipleEpochs wrap

def test_multiple_epochs_inside_async():
    """Satellite: MultipleEpochsIterator wrapped in AsyncDataSetIterator —
    the async workers replay the base N times through the wrapper's
    reset-between-epochs logic, in order."""
    base_seq = _features(_base_iter())
    a = AsyncDataSetIterator(MultipleEpochsIterator(3, _base_iter()),
                             queue_size=3, workers=2)
    got = _features(a)
    assert len(got) == 3 * len(base_seq)
    for e in range(3):
        for g, b in zip(got[e * len(base_seq):(e + 1) * len(base_seq)],
                        base_seq):
            np.testing.assert_array_equal(g, b)


def test_multiple_epochs_forward_only_base():
    """Forward-only base (reset is a no-op): the epoch replay yields only
    what the stream still holds — no hang, no error."""
    from deeplearning4j_tpu.data.streaming import StreamingDataSetIterator
    s = StreamingDataSetIterator(batch_size=4, buffer_records=64)
    for i in range(16):
        s.push(np.full(3, i, np.float32),
               np.eye(2, dtype=np.float32)[i % 2])
    s.end()
    a = AsyncDataSetIterator(MultipleEpochsIterator(2, s), queue_size=2,
                             workers=2)
    got = _features(a)
    assert len(got) == 4               # one pass: the stream cannot rewind
    np.testing.assert_array_equal(got[0][:, 0], [0, 1, 2, 3])


# ------------------------------------------------------- device prefetcher

def test_prefetcher_overlap_smoke():
    """Tier-1 overlap invariant: while the consumer holds batch k (a step
    in flight), the prefetcher already has >= 1 further batch staged on
    device."""
    import jax
    pf = DevicePrefetcher(_base_iter(), depth=2)
    it = iter(pf)
    first = next(it)
    assert pf.buffered >= 1            # next batch staged while we "step"
    # staged items are device-resident jax arrays, not host numpy
    assert isinstance(first.features, jax.Array)
    nxt = pf._buf[0]
    assert isinstance(nxt.features, jax.Array)
    rest = 0
    while True:
        try:
            next(it)
        except StopIteration:
            break
        rest += 1
    assert 1 + rest == 64 // 8
    assert pf.buffered == 0


def test_prefetcher_payloads_and_timer():
    t = PipelineTimer()
    src = [("chunk", (np.ones((2, 3), np.float32), np.zeros(2, np.float32))),
           ("batch", _mk_ds(4))]
    out = list(DevicePrefetcher(src, depth=3, timer=t))
    assert out[0][0] == "chunk" and out[1][0] == "batch"
    import jax
    assert isinstance(out[0][1][0], jax.Array)
    assert isinstance(out[1][1].features, jax.Array)
    assert t.counts.get("h2d") == 2


def test_pipeline_timer_stall_semantics():
    t = PipelineTimer()
    t.start()
    t.add("fetch", 0.2)
    t.add("decode", 0.1)
    time.sleep(0.01)
    t.stop()
    t.wall = 1.0
    # no wait recorded -> naive fallback: inline fetch+decode+h2d is stall
    assert t.host_stall_frac() == pytest.approx(0.3)
    t.add("wait", 0.05)
    # wait recorded -> it IS the stall (sub-stages may nest inside it)
    assert t.host_stall_frac() == pytest.approx(0.05)
    s = t.summary()
    assert s["host_stall_frac"] == pytest.approx(0.05)
    assert s["fetch_sec"] == pytest.approx(0.2)


# --------------------------------------------- fit/evaluate through the pipe

def _tiny_net(seed=7):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _params_equal(a, b):
    import jax
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def test_fit_bitwise_identical_with_and_without_prefetch():
    """Acceptance: the prefetched path must train BITWISE identically to
    the naive path on the same batch stream (chunk boundaries and step
    order do not depend on prefetch depth)."""
    n1, n2 = _tiny_net(), _tiny_net()
    n1.fit(_base_iter(n=96, batch=8), epochs=2, prefetch=0)
    n2.fit(_base_iter(n=96, batch=8), epochs=2, prefetch=3)
    assert _params_equal(n1.params, n2.params)
    assert np.float32(n1.get_score()) == np.float32(n2.get_score())
    assert n2.last_pipeline_stats["host_stall_frac"] is not None


def test_fit_bitwise_identical_through_multiworker_etl():
    n1, n2 = _tiny_net(), _tiny_net()
    n1.fit(_base_iter(n=96, batch=8), epochs=1)
    n2.fit(AsyncDataSetIterator(_base_iter(n=96, batch=8), queue_size=3,
                                workers=4, ordered=True), epochs=1)
    assert _params_equal(n1.params, n2.params)


def test_cg_fit_prefetch_bitwise_parity():
    from deeplearning4j_tpu import NeuralNetConfiguration
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd

    def mk():
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(0.1))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8,
                                           activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                              activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out").build())
        return ComputationGraph(conf).init()

    g1, g2 = mk(), mk()
    g1.fit(_base_iter(n=64, batch=8), epochs=1, prefetch=0)
    g2.fit(_base_iter(n=64, batch=8), epochs=1, prefetch=2)
    assert _params_equal(g1.params, g2.params)


def test_train_eval_device_pp_parity():
    """Satellite: a net trained with an on-chip normalizer must evaluate
    through the SAME transform — uint8-wire eval equals pre-normalized
    float eval exactly."""
    from deeplearning4j_tpu.data.normalizers import ImagePreProcessingScaler

    rng = np.random.RandomState(1)
    xu = rng.randint(0, 256, size=(64, 4)).astype(np.uint8)
    y = np.eye(2, dtype=np.float32)[(xu.sum(1) > 510).astype(int)]

    def u8_iter():
        it = ListDataSetIterator(DataSet(xu, y), 8, shuffle=False)
        it.set_pre_processor(ImagePreProcessingScaler(device_side=True))
        return it

    net = _tiny_net()
    net.fit(u8_iter(), epochs=2)
    ev_u8 = net.evaluate(u8_iter())
    ev_f = net.evaluate(
        ListDataSetIterator(DataSet(xu.astype(np.float32) / 255.0, y), 8,
                            shuffle=False))
    assert ev_u8.accuracy() == ev_f.accuracy()

    # and the raw batches really did cross the iterator as uint8
    assert next(iter(u8_iter())).features.dtype == np.uint8


def test_uint8_wire_fetcher_default():
    from deeplearning4j_tpu.data.fetchers import MnistDataSetIterator
    it = MnistDataSetIterator(32, train=True, num_examples=64, shuffle=False)
    ds = next(iter(it))
    assert ds.features.dtype == np.uint8
    assert it.pre_processor is not None and it.pre_processor.device_side
    it_f = MnistDataSetIterator(32, train=True, num_examples=64,
                                shuffle=False, uint8_wire=False)
    ds_f = next(iter(it_f))
    assert ds_f.features.dtype.kind == "f"   # plain float, no wire encoding
    np.testing.assert_allclose(np.asarray(ds.features) / 255.0,
                               np.asarray(ds_f.features), atol=0.5 / 255)


@pytest.mark.slow
def test_streamed_bytes_pipeline_end_to_end():
    """Soak: decode-from-bytes ETL through workers + prefetch trains
    bitwise-identically to inline decode (the bench row's invariant)."""
    import zlib
    from deeplearning4j_tpu.data.streaming import (encode_record,
                                                   decode_record)

    ds = _mk_ds(128, seed=3)
    wire = [zlib.compress(
        encode_record(np.asarray(ds.features[i * 8:(i + 1) * 8]),
                      np.asarray(ds.labels[i * 8:(i + 1) * 8])).encode())
        for i in range(16)]

    def decode(blob):
        f, l = decode_record(zlib.decompress(blob).decode())
        return DataSet(f, l)

    class Blocks:
        def __init__(self):
            self._i = 0

        def reset(self):
            self._i = 0

        def __iter__(self):
            self.reset()
            return self

        def __next__(self):
            if self._i >= len(wire):
                raise StopIteration
            b = wire[self._i]
            self._i += 1
            return b

    class Inline(DataSetIterator):
        def __init__(self):
            self.base = Blocks()

        def reset(self):
            self.base.reset()

        def __next__(self):
            return self._emit(decode(next(self.base)))

    n1, n2 = _tiny_net(), _tiny_net()
    n1.fit(Inline(), epochs=3, prefetch=0)
    a = AsyncDataSetIterator(Blocks(), queue_size=4, workers=4,
                             transform=decode)
    n2.fit(a, epochs=3)
    a._shutdown()
    assert _params_equal(n1.params, n2.params)
