"""KV block migration + host-memory KV tier (serving/kv/migrate.py,
serving/kv/hosttier.py — docs/SERVING_TIER.md "Disaggregation").

The load-bearing claims pinned here:
- a migrated block chain continues decoding BITWISE-identically on the
  destination replica, at f32 AND bf16 compute, including chains whose
  tail block was produced by copy-on-write;
- the validity envelope rejects payloads from a different architecture
  (model_sig), block size, or element dtype, and a torn/corrupted
  payload is rejected with the destination pool completely untouched;
- evicted prefix blocks spill to the host tier and restore on a later
  chain hit with bitwise-identical output and ZERO new XLA programs;
- a weight swap purges the host tier AND the advertised chain-head
  digest (stale-affinity regression);
- ``PoolExhaustedError`` carries the occupancy detail /healthz reports.
"""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.serving import DecodeEngine
from deeplearning4j_tpu.serving.kv import (BlockPool, HostKVTier,
                                           KVMigrateError,
                                           PoolExhaustedError)
from deeplearning4j_tpu.zoo.simple import TinyTransformer

V = 13


def _transformer(max_len=64, compute_dtype=None, seed=7, n_layers=2):
    kw = {"compute_dtype": compute_dtype} if compute_dtype else {}
    return TinyTransformer(vocab_size=V, n_layers=n_layers, d_model=32,
                           n_heads=4, max_len=max_len, seed=seed,
                           **kw).init()


def _prompts(sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, V, size=n))) for n in sizes]


def _paged(net, slots=2, max_len=64, bs=8, **kw):
    return DecodeEngine(net, slots=slots, max_len=max_len, kv="paged",
                        kv_block_size=bs, prefix_cache=True,
                        chunk_tokens=8, **kw).start()


def _pool_snapshot(eng):
    p = eng._pool
    return (p.in_use, p.free_count, p.cached_count)


def _counter(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    want = tuple(str(labels[k]) for k in fam.labelnames)
    return sum(child.value for key, child in fam.children()
               if key == want)


def _retamper(payload):
    """Deep copy through JSON — exactly what a wire transfer does."""
    return json.loads(json.dumps(payload))


# ------------------------------------------------------------- migration

@pytest.mark.parametrize("dtype", [None, "bfloat16"],
                         ids=["f32", "bf16"])
def test_migrate_roundtrip_bitwise(dtype):
    src = _paged(_transformer(compute_dtype=dtype))
    dst = _paged(_transformer(compute_dtype=dtype))
    prompt = _prompts([20])[0]
    try:
        ref = src.generate(prompt, max_new_tokens=6)
        payload = src.kv_export(prompt)
        assert payload["n_blocks"] == 2          # (20-1)//8 claimable
        out = dst.kv_import(payload)
        assert out["imported_blocks"] == 2
        assert out["duplicate_blocks"] == 0
        got = dst.generate(prompt, max_new_tokens=6)
        assert got["tokens"] == ref["tokens"]    # continued decode, bitwise
        st = dst.stats()["kv"]
        assert st["prefix_hits"] >= 1            # it really used the chain
        assert st["migrate_imports"] == 1
        # the destination now serves rows bitwise-equal to the payload:
        # re-export the same chain and compare raw leaf bytes
        back = dst.kv_export(prompt)
        for a, b in zip(payload["leaves"], back["leaves"]):
            assert a["path"] == b["path"]
            assert a["data"] == b["data"]
        # re-importing the same payload is a no-op (first-writer-wins)
        again = dst.kv_import(_retamper(payload))
        assert again["imported_blocks"] == 0
        assert again["duplicate_blocks"] == 2
    finally:
        src.stop()
        dst.stop()


def test_migrate_midchain_cow_chain():
    src = _paged(_transformer())
    dst = _paged(_transformer())
    p1 = _prompts([20], seed=1)[0]
    p2 = p1[:12] + _prompts([8], seed=2)[0]      # diverges MID block 1
    try:
        r1 = src.generate(p1, max_new_tokens=6)
        r2 = src.generate(p2, max_new_tokens=6)
        assert src.stats()["kv"]["cow_copies"] >= 1
        # p2's chain tail block was written via copy-on-write; its
        # migrated bytes must still continue decode exactly
        out = dst.kv_import(src.kv_export(p2))
        assert out["imported_blocks"] == 2
        assert dst.generate(p2, max_new_tokens=6)["tokens"] == r2["tokens"]
        assert dst.generate(p1, max_new_tokens=6)["tokens"] == r1["tokens"]
    finally:
        src.stop()
        dst.stop()


def test_migrate_envelope_rejections():
    src = _paged(_transformer())
    prompt = _prompts([20])[0]
    try:
        src.generate(prompt, max_new_tokens=4)
        payload = src.kv_export(prompt)
    finally:
        src.stop()                               # the payload is a value

    # different architecture → model_sig mismatch
    dst = _paged(_transformer(n_layers=1))
    try:
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(_retamper(payload))
        assert ei.value.reason == "model_sig"
        assert _pool_snapshot(dst)[0] == 0
    finally:
        dst.stop()

    # same model, different block size
    dst = _paged(_transformer(), bs=16)
    try:
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(_retamper(payload))
        assert ei.value.reason == "block_size"
    finally:
        dst.stop()

    dst = _paged(_transformer())
    try:
        bad = _retamper(payload)
        for leaf in bad["leaves"]:
            leaf["dtype"] = "float64"            # wire says f64, pool is f32
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(bad)
        assert ei.value.reason == "dtype"

        bad = _retamper(payload)
        bad["vocab"] = V + 1
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(bad)
        assert ei.value.reason == "vocab"

        # every rejection was counted under its reason and none of them
        # touched the pool — the good payload still imports cleanly after
        assert _counter("dl4jtpu_kv_migrate_rejects_total",
                        engine=dst.id, reason="dtype") == 1
        assert _counter("dl4jtpu_kv_migrate_rejects_total",
                        engine=dst.id, reason="vocab") == 1
        assert _pool_snapshot(dst) == (0, dst._pool.usable, 0)
        assert dst.kv_import(payload)["imported_blocks"] == 2
    finally:
        dst.stop()


def test_migrate_torn_import_leaves_pool_unchanged():
    src = _paged(_transformer())
    dst = _paged(_transformer())
    prompt = _prompts([20])[0]
    try:
        ref = src.generate(prompt, max_new_tokens=4)
        payload = src.kv_export(prompt)
        torn = _retamper(payload)
        data = torn["leaves"][0]["data"]
        torn["leaves"][0]["data"] = data[:len(data) // 2]   # cut mid-body
        before = _pool_snapshot(dst)
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(torn)
        assert ei.value.reason == "torn"
        assert _pool_snapshot(dst) == before     # nothing allocated/indexed
        # flipped payload bytes (b64 still decodes, checksum breaks)
        flipped = _retamper(payload)
        d = flipped["leaves"][0]["data"]
        flipped["leaves"][0]["data"] = d[:-8] + ("AAAAAAA=" if d[-8:]
                                                 != "AAAAAAA=" else "BBBBBBA=")
        with pytest.raises(KVMigrateError) as ei:
            dst.kv_import(flipped)
        assert ei.value.reason == "torn"
        assert _pool_snapshot(dst) == before
        # the destination is unharmed: a cold generate still matches
        assert dst.generate(prompt, max_new_tokens=4)["tokens"] \
            == ref["tokens"]
    finally:
        src.stop()
        dst.stop()


# ------------------------------------------------------------- host tier

def test_host_tier_spill_restore_bitwise():
    prompts = _prompts((40, 40, 40, 40), seed=3)

    def run(host_kv_bytes):
        eng = _paged(_transformer(), kv_blocks=9,
                     host_kv_bytes=host_kv_bytes)
        try:
            outs = []
            for _ in range(2):                   # pass 2 re-hits pass 1's
                for p in prompts:                # evicted (spilled) chains
                    outs.append(eng.generate(p, max_new_tokens=4)["tokens"])
            st = eng.stats()
            info = eng.kv_pool_info()
            assert eng.trace_count == 1          # ONE step program, still
            assert st["kv"]["kv_programs"] <= 2
            return outs, st, info
        finally:
            eng.stop()

    base, _, _ = run(None)
    tiered, st, info = run(32 << 20)
    assert tiered == base                        # restores are bitwise
    tier = info["host_tier"]
    assert tier["spills"] > 0 and tier["blocks"] > 0
    assert st["kv"]["host_restores"] > 0
    assert st["kv"]["prefix_hits"] > 0           # the second pass hit
    assert info["blocks_in_use"] == 0            # no leak


def test_host_tier_budget_lru_and_idempotent_put():
    rows = {"k": np.zeros(25, dtype=np.float32)}     # 100 bytes/entry
    tier = HostKVTier(byte_budget=300, engine="t")
    for h in ("h1", "h2", "h3"):
        tier.put(h, "p", (1,), {"k": rows["k"].copy()})
    assert len(tier) == 3 and tier.bytes_used == 300
    tier.put("h1", "p", (1,), {"k": rows["k"].copy()})   # re-spill:
    assert len(tier) == 3 and tier.stats()["spills"] == 3   # refresh only
    tier.get("h2")                               # LRU-touch; entry stays
    assert tier.has("h2")
    tier.put("h4", "p", (1,), {"k": rows["k"].copy()})
    assert not tier.has("h3")                    # h3 became LRU and dropped
    assert tier.has("h1") and tier.has("h2")
    assert tier.stats()["drops"] == 1
    # an entry bigger than the whole budget is refused outright
    tier.put("huge", "p", (1,), {"k": np.zeros(200, dtype=np.float32)})
    assert not tier.has("huge")
    n = tier.purge()
    assert n == 3 and len(tier) == 0 and tier.bytes_used == 0


def test_swap_purges_host_tier_and_chain_heads():
    eng = _paged(_transformer(), kv_blocks=9, host_kv_bytes=32 << 20)
    try:
        for p in _prompts((40, 40, 40), seed=5):
            eng.generate(p, max_new_tokens=2)
        assert eng.stats()["kv"]["chain_heads"]  # affinity signal is live
        assert len(eng._host_tier) > 0
        net2 = _transformer(seed=11)
        eng.swap_weights(net2.params, net2.state)
        # stale-affinity regression: the swap must clear BOTH halves of
        # the routing signal — the advertised digest and the host tier
        # (stale KV restored under new weights would be silently wrong)
        assert eng.stats()["kv"]["chain_heads"] == []
        assert len(eng._host_tier) == 0
        assert eng._host_tier.stats()["bytes"] == 0
        out = eng.generate(_prompts((20,), seed=6)[0], max_new_tokens=2)
        assert len(out["tokens"]) == 2           # serving continues
        assert eng.stats()["kv"]["chain_heads"]  # and repopulates
    finally:
        eng.stop()


# ------------------------------------------------------ pool observability

def test_pool_exhausted_detail_and_high_water():
    p = BlockPool(6, 8)                          # 5 usable
    a = p.alloc(3)
    assert p.high_water == 3
    b = p.alloc(1)
    assert p.high_water == 4
    p.mark_cached(b[0])
    p.decref(b[0])                               # evictable, not in use
    with pytest.raises(PoolExhaustedError) as ei:
        p.alloc(4)
    e = ei.value
    assert (e.need, e.free, e.in_use, e.cached) == (4, 2, 3, 1)
    for x in a:
        p.decref(x)
    assert p.high_water == 4                     # sticky across release
