"""ComputationGraph gradient checks through DAG vertices.

Parity role: GradientCheckTestsComputationGraph.java (one of the reference's
13 gradient-check suites, SURVEY §4) — finite differences vs autodiff
through merge/elementwise/scale/shift/subset/stack vertex topologies.
"""

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.models import ComputationGraph
from deeplearning4j_tpu.nn.conf.graph_conf import (
    MergeVertex, ElementWiseVertex, ScaleVertex, ShiftVertex,
    L2NormalizeVertex, SubsetVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.util.gradient_check import gradient_check_fn

F, C = 4, 3


def _check(cg, x, y):
    def loss_fn(params):
        loss, _ = cg._loss(params, cg.state, [jnp.asarray(x)],
                           [jnp.asarray(y)], None)
        return loss

    fails, checked, worst = gradient_check_fn(loss_fn, cg.params,
                                              max_checks_per_array=12)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"
    assert checked > 0


def _data(b=5, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(b, F).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rs.randint(0, C, b)]
    return x, y


def _builder():
    return (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
            .weight_init("xavier").graph_builder()
            .add_inputs("in").set_input_types(InputType.feed_forward(F)))


def test_merge_vertex_gradients():
    g = _builder()
    g.add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
    g.add_layer("b", DenseLayer(n_out=4, activation="sigmoid"), "in")
    g.add_vertex("m", MergeVertex(), "a", "b")
    g.add_layer("out", OutputLayer(n_out=C, activation="softmax",
                                   loss="mcxent"), "m")
    cg = ComputationGraph(g.set_outputs("out").build()).init()
    _check(cg, *_data())


def test_elementwise_add_and_product_gradients():
    for op in ("add", "product"):
        g = _builder()
        g.add_layer("a", DenseLayer(n_out=6, activation="tanh"), "in")
        g.add_layer("b", DenseLayer(n_out=6, activation="tanh"), "in")
        g.add_vertex("ew", ElementWiseVertex(op=op), "a", "b")
        g.add_layer("out", OutputLayer(n_out=C, activation="softmax",
                                       loss="mcxent"), "ew")
        cg = ComputationGraph(g.set_outputs("out").build()).init()
        _check(cg, *_data(seed=1))


def test_scale_shift_l2norm_gradients():
    g = _builder()
    g.add_layer("h", DenseLayer(n_out=6, activation="tanh"), "in")
    g.add_vertex("sc", ScaleVertex(scale=0.5), "h")
    g.add_vertex("sh", ShiftVertex(shift=0.1), "sc")
    g.add_vertex("l2", L2NormalizeVertex(), "sh")
    g.add_layer("out", OutputLayer(n_out=C, activation="softmax",
                                   loss="mcxent"), "l2")
    cg = ComputationGraph(g.set_outputs("out").build()).init()
    _check(cg, *_data(seed=2))


def test_subset_vertex_gradients():
    g = _builder()
    g.add_layer("h", DenseLayer(n_out=8, activation="tanh"), "in")
    g.add_vertex("sub", SubsetVertex(from_idx=2, to_idx=5), "h")
    g.add_layer("out", OutputLayer(n_out=C, activation="softmax",
                                   loss="mcxent"), "sub")
    cg = ComputationGraph(g.set_outputs("out").build()).init()
    _check(cg, *_data(seed=4))


def test_multi_output_graph_gradients():
    """Two loss-bearing outputs fed from a shared trunk (the reference's
    multi-output CG gradient-check topology)."""
    g = _builder()
    g.add_layer("trunk", DenseLayer(n_out=6, activation="tanh"), "in")
    g.add_layer("out1", OutputLayer(n_out=C, activation="softmax",
                                    loss="mcxent"), "trunk")
    g.add_layer("out2", OutputLayer(n_out=2, activation="identity",
                                    loss="mse"), "trunk")
    cg = ComputationGraph(g.set_outputs("out1", "out2").build()).init()
    x, y1 = _data(seed=5)
    rs = np.random.RandomState(6)
    y2 = rs.randn(len(x), 2).astype(np.float32)

    def loss_fn(params):
        loss, _ = cg._loss(params, cg.state, [jnp.asarray(x)],
                           [jnp.asarray(y1), jnp.asarray(y2)], None)
        return loss

    fails, checked, worst = gradient_check_fn(loss_fn, cg.params,
                                              max_checks_per_array=12)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"
