"""Dropout family tests (parity role: nn/conf/dropout/ —
TestDropout-style semantics + gradient checks + serde sweep).
"""

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.nn.dropout import (
    IDropout, Dropout, AlphaDropout, GaussianDropout, GaussianNoise)

ALL_KINDS = [Dropout(p=0.3), AlphaDropout(p=0.1), GaussianDropout(rate=0.4),
             GaussianNoise(stddev=0.2)]


def _net(dropout):
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=8, activation="tanh", dropout=dropout))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    return MultiLayerNetwork(conf).init()


def test_inference_is_identity():
    """No dropout noise at inference (inverted dropout, like the reference)."""
    rs = np.random.RandomState(0)
    x = rs.randn(16, 5).astype(np.float32)
    ref = np.asarray(_net(None).output(x))
    for d in ALL_KINDS:
        net = _net(d)
        # same seed → same params → identical inference output
        np.testing.assert_allclose(np.asarray(net.output(x)), ref,
                                   rtol=1e-6, err_msg=type(d).__name__)


def test_statistical_semantics():
    """Each kind's defining moment property, measured on a big sample."""
    rng = jax.random.PRNGKey(0)
    x = jnp.ones((200, 500), jnp.float32) * 2.0

    out = Dropout(p=0.3).apply(x, rng)
    assert abs(float(out.mean()) - 2.0) < 0.02          # E preserved (inverted)
    assert abs(float((out == 0).mean()) - 0.3) < 0.02   # ~p zeros

    out = GaussianDropout(rate=0.4).apply(x, rng)
    assert abs(float(out.mean()) - 2.0) < 0.02          # multiplicative N(1,·)
    want_std = 2.0 * (0.4 / 0.6) ** 0.5
    assert abs(float(out.std()) - want_std) < 0.05

    out = GaussianNoise(stddev=0.2).apply(x, rng)
    assert abs(float(out.mean()) - 2.0) < 0.01          # additive N(0, 0.2)
    assert abs(float(out.std()) - 0.2) < 0.01

    # AlphaDropout: preserves mean/variance of a standardized input
    z = jax.random.normal(jax.random.PRNGKey(1), (200, 500))
    out = AlphaDropout(p=0.1).apply(z, rng)
    assert abs(float(out.mean())) < 0.02
    assert abs(float(out.std()) - 1.0) < 0.03


def test_gradient_check_each_kind():
    """Fixed-rng gradient check through every dropout kind — the noise is
    deterministic given the rng, so FD vs autodiff must agree."""
    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn

    rs = np.random.RandomState(5)
    x = rs.randn(4, 5)
    y = np.eye(3)[rs.randint(0, 3, 4)]
    for d in ALL_KINDS:
        net = _net(d)
        rng = jax.random.PRNGKey(7)

        def loss_fn(params):
            loss, _ = net._loss(params, net.state, jnp.asarray(x),
                                jnp.asarray(y), rng, None, None)
            return loss

        fails, checked, worst = gradient_check_fn(loss_fn, net.params,
                                                  max_checks_per_array=8)
        assert fails == 0, f"{type(d).__name__}: {fails}/{checked} " \
                           f"(worst {worst:.2e})"
        assert checked > 0


def test_serde_round_trip_layer_and_global():
    """All four kinds survive JSON round-trip both as a layer field and as
    the network-level default."""
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    for d in ALL_KINDS:
        conf = (NeuralNetConfiguration.builder().seed(1).dropout(d)
                .list()
                .layer(DenseLayer(n_out=4, dropout=d))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(3)).build())
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.global_conf.dropout == d
        assert conf2.layers[0].dropout == d
        assert isinstance(conf2.layers[0].dropout, type(d))


def test_training_with_dropout_learns():
    """End-to-end: a net with each dropout kind still trains."""
    rs = np.random.RandomState(2)
    x = rs.rand(64, 5).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x.sum(axis=1) * 2).astype(int) % 3]
    from deeplearning4j_tpu.data.dataset import DataSet
    ds = DataSet(x, y)
    for d in ALL_KINDS:
        net = _net(d)
        s0 = net.score(ds)
        for _ in range(30):
            net.fit(ds)
        assert net.score(ds) < s0, type(d).__name__
