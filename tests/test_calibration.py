"""EvaluationCalibration tests — bucketed counts vs hand-computed values
(VERDICT r1 #6; reference eval/EvaluationCalibration.java)."""

import numpy as np

from deeplearning4j_tpu.eval import EvaluationCalibration


def _tiny():
    # 4 examples, 2 classes; probabilities chosen to land in known bins
    labels = np.array([[1, 0],
                       [0, 1],
                       [1, 0],
                       [0, 1]], np.float32)
    preds = np.array([[0.95, 0.05],
                      [0.30, 0.70],
                      [0.45, 0.55],
                      [0.10, 0.90]], np.float32)
    return labels, preds


class TestReliability:
    def test_bucketed_counts_hand_computed(self):
        ec = EvaluationCalibration(reliability_num_bins=10,
                                   histogram_num_bins=10)
        labels, preds = _tiny()
        ec.eval(labels, preds)
        # class 0 probabilities: 0.95->bin9, 0.30->bin3, 0.45->bin4, 0.10->bin1
        tc0 = ec.rdiag_total_count[:, 0]
        assert tc0[9] == 1 and tc0[3] == 1 and tc0[4] == 1 and tc0[1] == 1
        assert tc0.sum() == 4
        # positives for class 0 land in bins 9 (0.95, label 1) and 4 (0.45, label 1)
        pc0 = ec.rdiag_pos_count[:, 0]
        assert pc0[9] == 1 and pc0[4] == 1 and pc0.sum() == 2
        # sum of predictions in bin 9 for class 0 is exactly 0.95
        np.testing.assert_allclose(ec.rdiag_sum_predictions[9, 0], 0.95)

    def test_reliability_diagram_values(self):
        ec = EvaluationCalibration(reliability_num_bins=2)
        labels = np.array([[1, 0], [0, 1], [1, 0], [0, 1]], np.float32)
        preds = np.array([[0.8, 0.2], [0.3, 0.7], [0.6, 0.4], [0.4, 0.6]],
                         np.float32)
        ec.eval(labels, preds)
        rd = ec.get_reliability_diagram(0)
        # class 0: lower bin [0,0.5): p=0.3 (label 0), p=0.4 (label 0)
        #          upper bin [0.5,1]: p=0.8 (label 1), p=0.6 (label 1)
        np.testing.assert_allclose(rd.mean_predicted_value, [0.35, 0.7])
        np.testing.assert_allclose(rd.fraction_positives, [0.0, 1.0])

    def test_p_equal_one_lands_in_last_bin(self):
        ec = EvaluationCalibration(reliability_num_bins=10)
        labels = np.array([[1.0, 0.0]], np.float32)
        preds = np.array([[1.0, 0.0]], np.float32)
        ec.eval(labels, preds)
        assert ec.rdiag_total_count[9, 0] == 1     # p == 1.0 edge case
        assert ec.rdiag_total_count[0, 1] == 1     # p == 0.0 → first bin


class TestHistograms:
    def test_label_and_prediction_counts(self):
        ec = EvaluationCalibration()
        labels, preds = _tiny()
        ec.eval(labels, preds)
        np.testing.assert_array_equal(ec.get_label_counts_each_class(), [2, 2])
        # argmax predictions: c0, c1, c1, c1
        np.testing.assert_array_equal(ec.get_prediction_counts_each_class(),
                                      [1, 3])

    def test_residual_histogram_hand_computed(self):
        ec = EvaluationCalibration(histogram_num_bins=10)
        labels = np.array([[1, 0]], np.float32)
        preds = np.array([[0.72, 0.28]], np.float32)
        ec.eval(labels, preds)
        # residuals: |1-0.72| = 0.28 -> bin 2 ; |0-0.28| = 0.28 -> bin 2
        h = ec.get_residual_plot_all_classes()
        assert h.bin_counts[2] == 2 and h.bin_counts.sum() == 2
        # per class: only label class 0 contributes, its residual 0.28
        h0 = ec.get_residual_plot(0)
        assert h0.bin_counts[2] == 1 and h0.bin_counts.sum() == 1
        h1 = ec.get_residual_plot(1)
        assert h1.bin_counts.sum() == 0

    def test_probability_histogram_per_class(self):
        ec = EvaluationCalibration(histogram_num_bins=4)
        labels, preds = _tiny()
        ec.eval(labels, preds)
        # label class 1 rows have P(class1) = 0.70 (bin 2), 0.90 (bin 3)
        h1 = ec.get_probability_histogram(1)
        assert h1.bin_counts[2] == 1 and h1.bin_counts[3] == 1
        assert h1.bin_counts.sum() == 2


class TestMaskingAndTimeSeries:
    def test_per_example_mask_excludes_rows(self):
        ec = EvaluationCalibration()
        labels, preds = _tiny()
        mask = np.array([1, 1, 0, 0], np.float32)
        ec.eval(labels, preds, mask)
        assert ec.rdiag_total_count[:, 0].sum() == 2
        np.testing.assert_array_equal(ec.get_label_counts_each_class(), [1, 1])
        np.testing.assert_array_equal(ec.get_prediction_counts_each_class(),
                                      [1, 1])

    def test_time_series_flattening_matches_2d(self):
        ec3 = EvaluationCalibration()
        labels, preds = _tiny()
        l3 = labels.reshape(2, 2, 2)
        p3 = preds.reshape(2, 2, 2)
        ec3.eval(l3, p3, np.ones((2, 2), np.float32))
        ec2 = EvaluationCalibration()
        ec2.eval(labels, preds)
        np.testing.assert_array_equal(ec3.rdiag_total_count,
                                      ec2.rdiag_total_count)
        np.testing.assert_array_equal(ec3.prob_overall, ec2.prob_overall)


class TestMergeAndECE:
    def test_merge_equals_joint_eval(self):
        labels, preds = _tiny()
        a = EvaluationCalibration().eval(labels[:2], preds[:2])
        b = EvaluationCalibration().eval(labels[2:], preds[2:])
        a.merge(b)
        joint = EvaluationCalibration().eval(labels, preds)
        np.testing.assert_array_equal(a.rdiag_total_count,
                                      joint.rdiag_total_count)
        np.testing.assert_array_equal(a.rdiag_pos_count, joint.rdiag_pos_count)
        np.testing.assert_allclose(a.rdiag_sum_predictions,
                                   joint.rdiag_sum_predictions)

    def test_ece_perfect_calibration_is_zero(self):
        ec = EvaluationCalibration(reliability_num_bins=1)
        # one bin: conf mean = 0.5, accuracy = 0.5 → ECE 0
        labels = np.array([[1, 0], [0, 1]], np.float32)
        preds = np.array([[0.5, 0.5], [0.5, 0.5]], np.float32)
        ec.eval(labels, preds)
        assert abs(ec.expected_calibration_error()) < 1e-12
        assert "ECE" in ec.stats()


def test_masked_column_cannot_win_argmax():
    """A masked-out class column must not be counted as the predicted class
    even when its raw probability is the max (per-output mask)."""
    ec = EvaluationCalibration()
    labels = np.array([[0, 1, 0]], np.float32)
    preds = np.array([[0.1, 0.3, 0.6]], np.float32)   # class 2 wins raw argmax
    mask = np.array([[1, 1, 0]], np.float32)          # ...but is masked out
    ec.eval(labels, preds, mask=mask)
    assert ec.prediction_counts[2] == 0
    assert ec.prediction_counts[1] == 1


def test_masked_label_column_excluded_from_per_class_stats():
    """Rows whose true-label column is masked out must not contribute to
    that class's residual/probability histograms."""
    ec = EvaluationCalibration()
    labels = np.array([[0, 1, 0]], np.float32)
    preds = np.array([[0.1, 0.3, 0.6]], np.float32)
    mask = np.array([[1, 0, 1]], np.float32)          # true class 1 masked
    ec.eval(labels, preds, mask=mask)
    assert ec.residual_by_class[:, 1].sum() == 0
    assert ec.prob_by_class[:, 1].sum() == 0
