"""Streaming ingest tests (parity role: dl4j-streaming's Kafka route tests —
producer thread feeds records, training consumes DataSets; see
deeplearning4j_tpu/data/streaming.py)."""

import queue
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    StreamingDataSetIterator, encode_record, decode_record, DataSet,
)
from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator


def test_two_thread_stream_batches_and_tail():
    it = StreamingDataSetIterator(batch_size=8, buffer_records=64)
    n = 35   # 4 full batches + tail of 3

    def producer():
        rs = np.random.RandomState(0)
        for i in range(n):
            it.push(rs.rand(4).astype(np.float32),
                    np.eye(3, dtype=np.float32)[i % 3])
            if i % 10 == 0:
                time.sleep(0.002)      # interleave with the consumer
        it.end()

    t = threading.Thread(target=producer)
    t.start()
    sizes, total = [], 0
    for ds in it:
        assert ds.features.shape[1:] == (4,)
        assert ds.labels.shape[1:] == (3,)
        sizes.append(ds.num_examples())
        total += ds.num_examples()
    t.join()
    assert total == n
    assert sizes == [8, 8, 8, 8, 3]


def test_stream_prebatched_blocks_and_drop_remainder():
    it = StreamingDataSetIterator(batch_size=4, drop_remainder=True)
    it.push(np.ones((6, 2), np.float32), np.ones((6, 1), np.float32),
            batched=True)
    it.end()
    out = list(it)
    assert len(out) == 1 and out[0].num_examples() == 4


def test_stream_backpressure_and_closed_push():
    it = StreamingDataSetIterator(batch_size=2, buffer_records=2,
                                  push_timeout=0.05)
    it.push(np.zeros(2), np.zeros(1))
    it.push(np.zeros(2), np.zeros(1))
    with pytest.raises(queue.Full):     # bounded buffer pushes back
        it.push(np.zeros(2), np.zeros(1))
    it.end()
    with pytest.raises(RuntimeError):
        it.push(np.zeros(2), np.zeros(1))
    assert next(it).num_examples() == 2


def test_wire_codec_roundtrip():
    f = np.random.RandomState(1).rand(5, 7).astype(np.float32)
    l = np.asarray([1, 0, 2], np.int32)
    f2, l2 = decode_record(encode_record(f, l))
    np.testing.assert_array_equal(f, f2)
    np.testing.assert_array_equal(l, l2)
    assert f2.dtype == f.dtype and l2.dtype == l.dtype


def test_stream_feeds_fit_through_async_prefetch():
    """End-to-end: producer thread → streaming iterator → async prefetch →
    MultiLayerNetwork.fit (the NDArrayPubSubRoute consumer role)."""
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(1)
            .list()
            .layer(DenseLayer(n_in=6, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    it = StreamingDataSetIterator(batch_size=16)

    def producer():
        rs = np.random.RandomState(2)
        for _ in range(8):
            x = rs.rand(16, 6).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[(x.sum(1) > 3).astype(int)]
            it.push(x, y, batched=True)
        it.end()

    t = threading.Thread(target=producer)
    t.start()
    net.fit(AsyncDataSetIterator(it, queue_size=2))
    t.join()
    assert net.iteration == 8
    assert np.isfinite(net.get_score())
