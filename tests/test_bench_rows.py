"""Tier-1 fast variants of the bench.py ``quantized`` and ``ladder`` rows.

The full rows run on the attached chip under the bench driver; these CI
variants (``fast=True``) run the same code path on CPU with tiny sizes
and keep every COUNT/ACCURACY assertion live — the accuracy-delta bars,
the int8 ≤ 0.30x weight-bytes ratio, the one-program-per-precision pin,
and the autotuned-ladder compile/pad-waste claims. Only the wall-clock
ratio assertions (int8 decode ≥ 1.2x bf16, speculative decode ≥ 1.8x
plain, affinity fan-out ≥ 1.5x random routing, host-tier restore ≥
recompute) are full-mode-only: CPU timings of a dequant-on-the-fly path
or a tiny draft model prove nothing about the TPU's memory-bound decode
step.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench  # noqa: E402


def test_quantized_row_fast():
    row = bench.bench_quantized(fast=True)
    assert row["unit"] == "tokens/sec"
    assert row["int8_bytes_ratio"] <= 0.30
    assert abs(row["accuracy_delta_int8"]) <= 0.01
    assert abs(row["accuracy_delta_fp8"]) <= 0.02
    assert row["compiled_decode_programs"] == [1, 1]
    assert set(row["serving_qps"]) == {"f32", "int8", "fp8"}


def test_train_perf_row_fast():
    row = bench.bench_train_perf(fast=True)
    # the function itself asserts fused-vs-per-leaf bitwise parity, the
    # pinned bf16 loss tolerance, and the MFU column's presence; here we
    # pin the row shape the bench driver publishes
    assert row["unit"] == "ratio"
    assert row["fused_bitwise"] is True
    assert row["mfu"] is not None
    bf16 = next(l for l in bench._EMITTED
                if "bf16 policy" in l["metric"])
    assert bf16["bf16_loss_delta"] <= bf16["bf16_loss_tol"]
    assert bf16["mfu"] is not None


def test_train_telemetry_row_fast():
    row = bench.bench_train_telemetry(fast=True)
    # the function itself asserts bitwise score parity across recorder
    # off / K=1 / K=20, the pinned one-program-per-config compile count,
    # and the K-cadence of recorded iterations; the <3% overhead bar is
    # full-mode-only (see module docstring). Here we pin the row shape.
    assert row["unit"] == "percent"
    assert row["bitwise_identical_score"] is True
    assert row["cadence_ok"] is True
    assert row["compiled_programs"] == [1, 1, 1]
    assert row["records_k1"] > row["records_k20"] > 0


def test_kv_storm_row_fast():
    row = bench.bench_kv_storm(fast=True)
    # the function itself asserts dense/paged bitwise output parity, the
    # one-step-program pin, ≤2 kv side programs, and full pool release
    assert row["unit"] == "tokens/sec"
    assert row["outputs_bitwise_equal"] is True
    assert row["compiled_programs"] == [1, 1]
    assert row["kv_programs"] <= 2
    assert row["prefill_chunks"] > 0


def test_kv_prefix_row_fast():
    row = bench.bench_kv_prefix(fast=True)
    assert row["unit"] == "x"
    assert row["outputs_bitwise_equal"] is True
    assert row["prefix_hits"] == 3                  # R-1 with fast R=4
    assert row["prefix_tokens_saved"] >= 3 * 16
    assert row["cow_copies"] == 0                   # boundary divergence


def test_kv_affinity_row_fast():
    row = bench.bench_kv_affinity(fast=True)
    # the function itself asserts zero failed requests, bitwise parity of
    # every routed output with a local standalone engine, the migration
    # into both decode replicas, and pool drain; the ≥1.5x effective
    # prefill throughput bar is full-mode-only
    assert row["unit"] == "x"
    assert row["outputs_bitwise_equal"] is True
    assert row["failed_requests"] == 0
    assert row["migrate_imports"] == 2             # both decode replicas
    assert row["decode_replica_prefix_hits"] >= 1
    assert row["affinity_hits"] >= 1


def test_kv_tier_row_fast():
    row = bench.bench_kv_tier(fast=True)
    # the function itself asserts bitwise output parity across the
    # tier-on/tier-off arms, spills + restores observed, the one-program
    # pin (restores are host-side block movement, ZERO new XLA programs),
    # and pool drain; the throughput and p99 bars are full-mode-only
    assert row["unit"] == "x"
    assert row["outputs_bitwise_equal"] is True
    assert row["host_spills"] > 0
    assert row["host_restores"] > 0
    assert row["pool_high_water"] > 0
    assert row["short_decode_p99_ms_tier"] > 0


def test_spec_decode_row_fast():
    row = bench.bench_spec_decode(fast=True)
    # the function itself asserts token-identical speculative outputs at
    # k=2 and k=4, the one-step/one-verify/one-draft compile pins, and
    # the distilled-draft acceptance floor; the ≥1.8x tokens/sec bar is
    # full-mode-only (CPU wall clock of a tiny LSTM proves nothing)
    assert row["unit"] == "tokens/sec"
    assert row["outputs_token_identical"] is True
    assert row["compiled_programs"] == [1, 1, 1]
    assert set(row["acceptance_rate"]) == {2, 4}
    assert all(r >= 0.3 for r in row["acceptance_rate"].values())
    assert row["draft_trace_agreement"] >= 0.9
    assert all(row["drafted_tokens"][k] >= row["accepted_tokens"][k] > 0
               for k in (2, 4))


def test_spec_tree_row_fast():
    row = bench.bench_spec_tree(fast=True)
    # the function itself asserts token-identical outputs for BOTH the
    # linear chain and the caterpillar tree, the compile pins, and that
    # the tree's mean accepted depth dominates the linear chain's; the
    # ≥1.3x tokens/sec bar is full-mode-only (see module docstring)
    assert row["unit"] == "tokens/sec"
    assert row["outputs_token_identical"] is True
    assert row["tree_nodes"] == 8                 # 1 + sum((3, 2, 2))
    assert (row["mean_accepted_depth"]["tree"]
            >= row["mean_accepted_depth"]["linear"])
    assert 0 < row["acceptance_rate"]["tree"] <= 1.0
    assert row["linear_tokens_per_sec"] > 0
    assert row["speedup_tree_vs_linear"] > 0


def test_self_draft_row_fast():
    row = bench.bench_self_draft(fast=True)
    # the function itself asserts token-identical self-drafted output,
    # the compile pins, and the near-ceiling int8 acceptance floor; the
    # ≥1.5x tokens/sec bar is full-mode-only (see module docstring)
    assert row["unit"] == "tokens/sec"
    assert row["outputs_token_identical"] is True
    assert row["self_draft"] == "int8"
    assert row["acceptance_rate"] >= 0.6
    assert row["mean_accepted_depth"] > 0
    assert row["speedup_vs_baseline"] > 0


def test_cold_start_row_fast():
    row = bench.bench_cold_start(fast=True)
    # the function itself asserts bitwise-equal first-request outputs and
    # ZERO compiles in the restore arm; the ≥5x ready-to-serve speedup and
    # sub-second restore walls are full-mode-only (see module docstring)
    assert row["unit"] == "x"
    assert row["outputs_bitwise_equal"] is True
    assert row["compiles_after_restore"] == 0
    assert row["artifact_programs"] >= 4       # 3 ladder rungs + decode step
    assert row["wall_restore_s"] < row["wall_retrace_s"]


def test_autoscale_row_fast():
    row = bench.bench_autoscale(fast=True)
    # the function itself asserts zero failed requests, fleet growth under
    # the tripled load, and the drain back to one replica; the p99-vs-SLO
    # bound is full-mode-only (in-process replicas pay their first-request
    # compile inside the storm)
    assert row["unit"] == "ms"
    assert row["failed_requests"] == 0
    assert row["replicas_peak"] > 1
    assert row["replicas_final"] == 1
    assert row["served_requests"] > 0


def test_ladder_row_fast():
    row = bench.bench_ladder(fast=True)
    assert row["unit"] == "percent"
    auto, pow2 = row["autotuned"], row["pow2"]
    assert auto["compiled_programs"] <= pow2["compiled_programs"]
    assert auto["pad_rows"] < pow2["pad_rows"]
    assert row["pad_rows_saved"] > 0
    # the row's vs_baseline IS the pad-waste fraction vs pow2 — must improve
    assert row["vs_baseline"] < 1.0


def test_elastic_row_fast():
    row = bench.bench_elastic(fast=True)
    # the function itself asserts bitwise digest agreement across the
    # REAL subprocess members (chain == single_process_reference) and the
    # threshold codec's >= 5x wire-byte reduction on charRNN; the
    # SIGKILL-mid-run soak, its recovery wall and the chain-vs-star
    # throughput claim are full-mode-only (tests/test_elastic.py's slow
    # soak covers the kill path in CI)
    assert row["unit"] == "s"
    assert row["workers"] == 2
    assert row["kill_at_step"] is None
    assert row["bitwise_parity"] is True
    assert row["failed_steps"] == 0
    assert row["replacements"] == 0
    assert row["generations"] == 1
    # comms columns: real wire traffic, comm/compute split, compression
    cc = row["chain_comms"]
    assert cc["bytes_per_step"] > 0
    assert 0 < cc["comm_frac"] < 1.0
    assert cc["compression_ratio"] == 1.0        # dense chain is exact
    assert row["threshold_wire_reduction"] >= 5.0
    assert row["chain_vs_star_tput"] is None     # full-mode-only claim
