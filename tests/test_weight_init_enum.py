"""WeightInit coverage diff against the reference enum.

Enumerates every scheme in the reference's WeightInit enum
(deeplearning4j-nn/src/main/java/org/deeplearning4j/nn/weights/
WeightInit.java:68) and asserts each is implemented with the documented
statistics — so a scheme silently dropped from nn/weights.py fails here by
name rather than disappearing from coverage.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.weights import init_weights

FI, FO = 400, 300
N = FI + FO

# (reference enum name, expected std for normal schemes or uniform bound,
#  kind). Statistics per the reference's javadoc.
REFERENCE_ENUM = [
    ("DISTRIBUTION", None, "distribution"),
    ("ZERO", 0.0, "const"),
    ("ONES", 1.0, "const"),
    ("SIGMOID_UNIFORM", 4.0 * np.sqrt(6.0 / N), "uniform"),
    ("NORMAL", 1.0 / np.sqrt(FI), "normal"),
    ("LECUN_NORMAL", 1.0 / np.sqrt(FI), "normal"),
    ("UNIFORM", 1.0 / np.sqrt(FI), "uniform"),
    ("XAVIER", np.sqrt(2.0 / N), "normal"),
    ("XAVIER_UNIFORM", np.sqrt(6.0 / N), "uniform"),
    ("XAVIER_FAN_IN", np.sqrt(1.0 / FI), "normal"),
    ("XAVIER_LEGACY", 1.0 / np.sqrt(FI + FO), "normal"),  # WeightInitUtil.java:106
    ("RELU", np.sqrt(2.0 / FI), "normal"),
    ("RELU_UNIFORM", np.sqrt(6.0 / FI), "uniform"),
    ("IDENTITY", None, "identity"),
    ("LECUN_UNIFORM", 3.0 / np.sqrt(FI), "uniform"),   # WeightInitUtil.java:88
    ("VAR_SCALING_NORMAL_FAN_IN", np.sqrt(1.0 / FI), "normal"),
    ("VAR_SCALING_NORMAL_FAN_OUT", np.sqrt(1.0 / FO), "normal"),
    ("VAR_SCALING_NORMAL_FAN_AVG", np.sqrt(2.0 / N), "normal"),
    ("VAR_SCALING_UNIFORM_FAN_IN", 3.0 / np.sqrt(FI), "uniform"),
    ("VAR_SCALING_UNIFORM_FAN_OUT", 3.0 / np.sqrt(FO), "uniform"),
    ("VAR_SCALING_UNIFORM_FAN_AVG", 3.0 / np.sqrt(N / 2.0), "uniform"),
]


def test_enum_is_fully_enumerated():
    assert len(REFERENCE_ENUM) == 21           # the full reference enum


@pytest.mark.parametrize("name,stat,kind",
                         REFERENCE_ENUM, ids=[r[0] for r in REFERENCE_ENUM])
def test_reference_scheme_implemented(name, stat, kind):
    rng = jax.random.PRNGKey(7)
    if kind == "identity":
        w = np.asarray(init_weights(rng, (64, 64), name.lower()))
        np.testing.assert_allclose(w, np.eye(64), atol=0)
        return
    dist = ("normal", 0.0, 0.05) if kind == "distribution" else None
    w = np.asarray(init_weights(rng, (FI, FO), name.lower(),
                                distribution=dist))
    assert w.shape == (FI, FO)
    if kind == "const":
        np.testing.assert_allclose(w, stat, atol=0)
    elif kind == "normal":
        assert abs(w.std() - stat) < 0.05 * stat, (w.std(), stat)
        assert abs(w.mean()) < 3 * stat / np.sqrt(w.size)
    elif kind == "uniform":
        eps = 1e-6 * stat                      # float32 bound rounding
        assert w.min() >= -stat - eps and w.max() <= stat + eps
        # uniform on [-b, b] has std b/sqrt(3); catches a normal mislabeled
        assert abs(w.std() - stat / np.sqrt(3)) < 0.05 * stat
    elif kind == "distribution":
        assert abs(w.std() - 0.05) < 0.01
