"""Gradient data-plane tests (exec/comms.py) — all in-process.

Three layers:

- **Pure pieces** — ``bucketize`` edge cases (ragged last bucket, tiny
  model smaller than one bucket), the exact sparse/dense wire encoding
  roundtrip, and ``ThresholdCodec``'s bitwise parity with the existing
  ``parallel.compression.EncodingHandler`` (residual carry + threshold
  trajectory).
- **Chain arithmetic** — N ``ChainComms`` members on loopback threads must
  produce output BITWISE-equal to the star coordinator's rank-ordered
  ``total + v`` loop and single f32 division, including across ragged
  buckets and repeated steps on the same sockets.
- **Elasticity** — a peer death mid-allreduce surfaces ``CommsError`` (not
  a hang), survivors ``configure()`` a new generation over loopback and
  complete; residuals reset on the generation change (the stale-residual
  fencing regression).
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.exec.comms import (ChainComms, CommsAbortedError,
                                           CommsError, ThresholdCodec,
                                           bucketize, decode_bucket,
                                           encode_bucket)


# ---------------------------------------------------------------------------
# bucketize
# ---------------------------------------------------------------------------

def test_bucketize_head_plus_fixed_body_with_ragged_tail():
    per = 256 * 1024       # 1 MB of f32
    n = 1 + per + per + 100
    got = bucketize(n, bucket_mb=1.0)
    assert got == [(0, 1), (1, 1 + per), (1 + per, 1 + 2 * per),
                   (1 + 2 * per, n)]
    # buckets tile [0, n) exactly
    assert got[0][0] == 0 and got[-1][1] == n
    assert all(a2 == b1 for (_, b1), (a2, _) in zip(got, got[1:]))


def test_bucketize_tiny_model_single_ragged_bucket():
    # model far smaller than one bucket: head + one ragged body bucket
    assert bucketize(5, bucket_mb=4.0) == [(0, 1), (1, 5)]
    # degenerate: the vector IS the head
    assert bucketize(1, bucket_mb=4.0) == [(0, 1)]
    with pytest.raises(ValueError):
        bucketize(0, bucket_mb=4.0)


def test_bucketize_exact_multiple_has_no_ragged_tail():
    per = max(1, int(0.001 * 1024 * 1024) // 4)
    got = bucketize(1 + 3 * per, bucket_mb=0.001)
    assert len(got) == 4
    assert all(b - a == per for a, b in got[1:])


# ---------------------------------------------------------------------------
# exact wire encoding
# ---------------------------------------------------------------------------

def test_encode_bucket_sparse_when_it_wins_dense_otherwise():
    dense = np.arange(1, 9, dtype=np.float32)          # all nonzero
    wire, payload = encode_bucket(dense)
    assert wire == 0 and len(payload) == dense.size * 4
    np.testing.assert_array_equal(decode_bucket(wire, payload, 8), dense)

    sparse = np.zeros(100, np.float32)
    sparse[[3, 97]] = [-2.5, 7.0]                      # 2·8 < 100·4
    wire, payload = encode_bucket(sparse)
    assert wire == 1 and len(payload) == 2 * 8
    np.testing.assert_array_equal(decode_bucket(wire, payload, 100), sparse)


def test_decode_bucket_rejects_corrupt_payloads():
    with pytest.raises(CommsError):
        decode_bucket(0, b"\0" * 8, 3)         # dense size mismatch
    with pytest.raises(CommsError):
        decode_bucket(1, b"\0" * 12, 4)        # sparse not 8-aligned
    bad_idx = (np.array([9], np.int32).tobytes()
               + np.array([1.0], np.float32).tobytes())
    with pytest.raises(CommsError):
        decode_bucket(1, bad_idx, 4)           # index out of range


# ---------------------------------------------------------------------------
# threshold codec parity with the scaleout implementation
# ---------------------------------------------------------------------------

def test_threshold_codec_matches_encoding_handler_bitwise():
    """The wire codec re-implements EncodingHandler in host numpy; decoded
    message, residual carry and the adaptive-threshold trajectory must
    stay bitwise-identical over many steps."""
    from deeplearning4j_tpu.parallel.compression import (EncodingHandler,
                                                         threshold_decode)
    n, steps = 400, 12
    rng = np.random.default_rng(0)
    ref = EncodingHandler(threshold=1e-2, min_threshold=1e-4,
                          threshold_step=1e-3, capacity_fraction=0.1)
    ours = ThresholdCodec(n, threshold=1e-2, min_threshold=1e-4,
                          threshold_step=1e-3, capacity_fraction=0.1)
    for _ in range(steps):
        g = rng.normal(scale=0.05, size=n).astype(np.float32)
        idx, vals, _ = ref.encode(g)
        ref_msg = np.asarray(threshold_decode(idx, vals, n))
        msg = ours.encode(g)
        np.testing.assert_array_equal(msg, ref_msg)
        np.testing.assert_array_equal(ours.residual, np.asarray(ref.residual))
        assert ours.threshold == pytest.approx(ref.threshold, abs=0)


def test_threshold_codec_reset_clears_residual_and_threshold_walk():
    from deeplearning4j_tpu.monitor import get_registry
    c = ThresholdCodec(50, threshold=1e-2, capacity_fraction=0.2)
    c.encode(np.full(50, 0.5, np.float32))
    assert np.abs(c.residual).sum() > 0
    assert c.threshold != pytest.approx(1e-2, abs=0)   # walked by adapt
    before = get_registry().render()
    c.reset()
    assert not c.residual.any()
    assert c.threshold == 1e-2
    assert c.resets == 1
    after = get_registry().render()
    assert "dl4jtpu_cluster_residual_resets_total" in after
    assert after != before


# ---------------------------------------------------------------------------
# the chain itself (loopback, in-process threads)
# ---------------------------------------------------------------------------

def _form_chain(n, generation=1, **kw):
    members = [ChainComms(**kw) for _ in range(n)]
    eps = {r: ("127.0.0.1", m.data_port) for r, m in enumerate(members)}
    errs = []

    def cfg(r):
        try:
            members[r].configure(generation, r, n, eps)
        except BaseException as e:     # noqa: BLE001 — surfaced below
            errs.append((r, e))

    ts = [threading.Thread(target=cfg, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return members, eps


def _chain_step(members, step, vecs, rows):
    out = [None] * len(members)
    errs = []

    def go(r):
        try:
            out[r] = members[r].allreduce(step, vecs[r], rows[r])
        except BaseException as e:     # noqa: BLE001
            errs.append((r, e))

    ts = [threading.Thread(target=go, args=(r,)) for r in range(len(members))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    return out, errs


def _star_reference(vecs, rows):
    """PR 19's coordinator arithmetic: rank-ordered ``total + v`` then one
    f32 division — the bitwise oracle."""
    total = None
    for v in vecs:
        total = v.copy() if total is None else total + v
    return total / np.float32(sum(rows))


@pytest.mark.parametrize("world", [2, 3])
def test_dense_chain_bitwise_equals_star_across_steps(world):
    n = 1001                     # head + 40 ragged micro-buckets
    members, _ = _form_chain(world, bucket_mb=0.0001)
    try:
        rng = np.random.default_rng(7)
        rows = [11] * (world - 1) + [10]
        for step in range(3):    # several steps over the SAME sockets
            vecs = [rng.normal(size=n).astype(np.float32)
                    for _ in range(world)]
            out, errs = _chain_step(members, step, vecs, rows)
            assert not errs, errs
            want = _star_reference(vecs, rows)
            for r in range(world):
                np.testing.assert_array_equal(out[r], want)
            assert members[0].last["buckets"] > 30
    finally:
        for m in members:
            m.close()


def test_tiny_model_and_world_one_short_circuit():
    # smaller than any bucket: 2 buckets, still exact
    members, _ = _form_chain(2, bucket_mb=4.0)
    try:
        vecs = [np.array([2.0, 4.0, 6.0], np.float32),
                np.array([1.0, 3.0, 5.0], np.float32)]
        out, errs = _chain_step(members, 0, vecs, [1, 1])
        assert not errs, errs
        np.testing.assert_array_equal(out[0], _star_reference(vecs, [1, 1]))
        assert members[0].last["buckets"] == 2
    finally:
        for m in members:
            m.close()
    # world of one never touches a socket
    solo = ChainComms()
    try:
        solo.configure(1, 0, 1, {})
        got = solo.allreduce(0, np.array([3.0, 9.0], np.float32), 2)
        np.testing.assert_array_equal(got, np.array([1.5, 4.5], np.float32))
    finally:
        solo.close()


def test_threshold_chain_transports_exact_compressed_sums():
    """With codec="threshold" each member compresses its OWN contribution
    once; the chain's job is to move those messages EXACTLY. The reduced
    output must equal the star arithmetic applied to the encoded
    messages (head element always exact)."""
    n = 257
    members, _ = _form_chain(2, codec="threshold", bucket_mb=0.0001,
                             codec_opts={"threshold": 1e-2,
                                         "capacity_fraction": 0.1})
    try:
        rng = np.random.default_rng(3)
        vecs = [rng.normal(scale=0.05, size=n).astype(np.float32)
                for _ in range(2)]
        refs = [ThresholdCodec(n - 1, threshold=1e-2, capacity_fraction=0.1)
                for _ in range(2)]
        want_msgs = [np.concatenate([v[:1], c.encode(v[1:])])
                     for v, c in zip(vecs, refs)]
        out, errs = _chain_step(members, 0, vecs, [4, 4])
        assert not errs, errs
        want = _star_reference(want_msgs, [4, 4])
        np.testing.assert_array_equal(out[0], want)
        np.testing.assert_array_equal(out[1], want)
        # sparse wire actually engaged and beat dense
        assert members[0].last["compression_ratio"] > 1.5
        # residual carried worker-side
        assert np.abs(members[0].codec_state.residual).sum() > 0
    finally:
        for m in members:
            m.close()


def test_peer_death_mid_allreduce_raises_comms_error_then_chain_reforms():
    """SIGKILL equivalent: rank 1 of 3 vanishes mid-exchange (sockets torn)
    — both survivors surface CommsError promptly instead of hanging; a new
    generation then reconfigures rank 0 and old rank 2 as a 2-chain on the
    SAME listeners and reduces correctly."""
    n = 40_000
    members, _ = _form_chain(3, bucket_mb=0.01)
    try:
        vecs = [np.full(n, float(r + 1), np.float32) for r in range(3)]
        killed = threading.Event()

        def assassin():
            killed.wait(timeout=10)
            members[1].close()          # tears both of rank 1's sockets

        t = threading.Thread(target=assassin)
        t.start()
        out = [None, None]
        errs = []

        def survivor(r):
            try:
                if r == 0:
                    killed.set()        # die once rank 0 is inside
                out[0 if r == 0 else 1] = \
                    members[r].allreduce(0, vecs[r], 1)
            except CommsError as e:
                errs.append((r, e))

        ts = [threading.Thread(target=survivor, args=(r,)) for r in (0, 2)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(timeout=30)
        t.join(timeout=15)
        # many-bucket exchange with a torn middle: at least one survivor
        # must observe the failure (whichever side the cut reached first),
        # and nobody may hang
        assert errs, "no survivor noticed the dead peer"
        assert all(not th.is_alive() for th in ts)

        # reform: generation 2, survivors re-ranked 0 and 1
        surv = [members[0], members[2]]
        eps = {r: ("127.0.0.1", m.data_port) for r, m in enumerate(surv)}
        cfg_errs = []

        def cfg(r):
            try:
                surv[r].configure(2, r, 2, eps)
            except BaseException as e:  # noqa: BLE001
                cfg_errs.append((r, e))

        ts = [threading.Thread(target=cfg, args=(r,)) for r in range(2)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(timeout=30)
        assert not cfg_errs, cfg_errs
        out2, errs2 = _chain_step(surv, 0, [vecs[0], vecs[2]], [1, 1])
        assert not errs2, errs2
        want = _star_reference([vecs[0], vecs[2]], [1, 1])
        np.testing.assert_array_equal(out2[0], want)
        np.testing.assert_array_equal(out2[1], want)
    finally:
        for m in members:
            m.close()


def test_should_abort_interrupts_a_stuck_peer_wait():
    """The lease layer learned of a reform while we were blocked on a peer
    that will never answer: should_abort flips and the allreduce raises
    CommsAbortedError instead of waiting out io_timeout."""
    members, _ = _form_chain(2, bucket_mb=4.0)
    try:
        flag = threading.Event()
        flag.set()
        with pytest.raises(CommsAbortedError):
            # rank 0 sends its bucket then blocks on the bcast that rank 1
            # (never calling allreduce) will not produce
            members[0].allreduce(0, np.ones(8, np.float32), 1,
                                 should_abort=flag.is_set)
    finally:
        for m in members:
            m.close()


def test_configure_resets_residual_on_generation_change():
    """Stale-residual fencing regression: error feedback accumulated under
    generation g must be dropped when the chain reconfigures for g+1 — and
    only on an actual generation CHANGE (same-generation reconfigure of a
    world-1 chain keeps it)."""
    c = ChainComms(codec="threshold",
                   codec_opts={"threshold": 1e-2, "capacity_fraction": 0.2})
    try:
        c.configure(1, 0, 1, {})
        c.allreduce(0, np.full(64, 0.5, np.float32), 1)
        assert c.codec_state is not None
        assert np.abs(c.codec_state.residual).sum() > 0
        resets0 = c.codec_state.resets
        c.configure(1, 0, 1, {})                   # same generation: kept
        assert np.abs(c.codec_state.residual).sum() > 0
        assert c.codec_state.resets == resets0
        c.configure(2, 0, 1, {})                   # reform: dropped
        assert not c.codec_state.residual.any()
        assert c.codec_state.resets == resets0 + 1
    finally:
        c.close()


def test_allreduce_emits_comm_metrics():
    from deeplearning4j_tpu.monitor import get_registry
    members, _ = _form_chain(2, bucket_mb=0.001)
    try:
        vecs = [np.ones(600, np.float32), np.ones(600, np.float32)]
        out, errs = _chain_step(members, 0, vecs, [1, 1])
        assert not errs, errs
        text = get_registry().render()
        assert 'dl4jtpu_cluster_comm_bytes_total{' in text
        assert 'direction="sent"' in text and 'direction="recv"' in text
        assert "dl4jtpu_cluster_compression_ratio" in text
        assert "dl4jtpu_cluster_bucket_seconds" in text
        for m in members:
            assert m.bytes_sent > 0 and m.bytes_recv > 0
            assert m.last["wall_s"] > 0
    finally:
        for m in members:
            m.close()
