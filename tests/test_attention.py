"""Attention + ring attention (sequence parallelism) tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.nn.layers.attention import (
    MultiHeadAttention, LayerNormalization, scaled_dot_product_attention,
)
from deeplearning4j_tpu.parallel.sequence_parallel import ring_attention
from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.nn.conf.inputs import InputType


def _qkv(B=2, T=16, H=2, Dh=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, Dh).astype(np.float32))
    return mk(), mk(), mk()


def test_ring_attention_matches_full():
    q, k, v = _qkv()
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    full = scaled_dot_product_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5), \
        np.abs(np.asarray(full) - np.asarray(ring)).max()


def test_ring_attention_causal_matches_full():
    q, k, v = _qkv(T=24, seed=3)
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    full = scaled_dot_product_attention(q, k, v, causal=True)
    ring = ring_attention(q, k, v, mesh, causal=True)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5), \
        np.abs(np.asarray(full) - np.asarray(ring)).max()


def test_ring_attention_two_device_axis():
    q, k, v = _qkv(T=12, seed=5)
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))
    full = scaled_dot_product_attention(q, k, v, causal=True)
    ring = ring_attention(q, k, v, mesh, causal=True)
    assert np.allclose(np.asarray(full), np.asarray(ring), atol=1e-5)


def test_mha_layer_in_network():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(1e-3))
            .list()
            .layer(MultiHeadAttention(n_heads=2, causal=True))
            .layer(LayerNormalization())
            .layer(RnnOutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(8))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.rand(3, 6, 8).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, (3, 6))]
    s0 = net.score(x=x, y=y)
    for _ in range(20):
        net.fit(x, y)
    assert net.score(x=x, y=y) < s0
    out = net.output(x)
    assert out.shape == (3, 6, 5)


def test_mha_gradients():
    from deeplearning4j_tpu.util.gradient_check import gradient_check_network
    conf = (NeuralNetConfiguration.builder()
            .seed(2).updater(Adam(1e-3)).activation("tanh")
            .list()
            .layer(MultiHeadAttention(n_heads=2))
            .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(1)
    x = rng.randn(2, 5, 6)
    y = np.eye(3)[rng.randint(0, 3, (2, 5))]
    fails, checked, worst = gradient_check_network(net, x, y,
                                                   max_checks_per_array=10)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"
