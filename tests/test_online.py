"""Online learning loop: hot swap, promotion gate, rollback, chaos.

The load-bearing claims pinned here:
- ``CheckpointManager.pin`` makes a checkpoint outlive ``keep_last``
  rotation, survives a manifest round-trip, and ``unpin`` re-enters it
  into rotation immediately;
- swapping same-shape weights into a WARMED InferenceEngine or
  DecodeEngine performs ZERO new XLA compiles (``trace_count``
  unchanged), while a shape/dtype/structure-mismatched pytree is
  rejected with a structured ``WeightSwapError`` BEFORE any engine state
  changes (outputs stay bitwise identical);
- a generation in flight across a DecodeEngine swap finishes entirely on
  the OLD weights; the next request runs on the new ones — still one
  compiled program;
- ``POST /admin/swap`` swaps a live server from a checkpoint path (409 on
  incompatible, 400 on torn/missing) and /predict responses carry
  ``x-model-version`` — which the Router forwards;
- the BatchGuard quarantines NaN and loss-spike batches (counted, never
  crashing); a stalled stream degrades /healthz instead of killing the
  service, and recovers;
- the Deployer's promote → rollback restores the pinned incumbent
  BITWISE under a fresh monotonic version, and ``recover()`` converges a
  mid-promotion crash (torn or intact candidate) onto one model;
- slow: ≥3 promotions under live HTTP traffic with zero failed requests
  and zero new compiles, then a forced regression that auto-rolls back;
  and a SIGKILL chaos run (mid-fine-tune + mid-promotion) that resumes
  from the manifest while the serving tier never sees a torn model.
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.data.kafka import InMemoryBroker, NDArrayPublisher, \
    NDArrayPubSubRoute
from deeplearning4j_tpu.data.streaming import StreamingDataSetIterator
from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.online import (BatchGuard, Deployer, DriftingProblem,
                                       EngineTarget, OnlineLearningService,
                                       OnlineTrainer, PromotionGate,
                                       ServerTarget, TrafficMirror)
from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager
from deeplearning4j_tpu.resilience.errors import (StreamStalledError,
                                                  WeightSwapError)
from deeplearning4j_tpu.resilience.faults import SimulatedCrash
from deeplearning4j_tpu.serving import (DecodeEngine, InferenceClient,
                                        InferenceEngine, InferenceServer,
                                        generate_naive)
from deeplearning4j_tpu.serving.replica import build_model
from deeplearning4j_tpu.serving.router import Router
from deeplearning4j_tpu.util import model_serializer

_WORKER = Path(__file__).with_name("_online_worker.py")

PROB = DriftingProblem()


def _mlp(seed=42):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _lstm(seed=7):
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_out=13, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(13))
            .build())
    return MultiLayerNetwork(conf).init()


def _save_at(mgr, net, iteration):
    """Record a checkpoint at a chosen iteration number (the manager names
    and indexes entries by the model's counters)."""
    net.iteration = iteration
    return mgr.save(net)


def _counter_value(name, **labels):
    fam = get_registry()._families.get(name)
    if fam is None:
        return 0.0
    if not fam.labelnames:
        return fam.value
    want = tuple(str(labels[k]) for k in fam.labelnames)
    for key, child in fam.children():
        if key == want:
            return child.value
    return 0.0


X_PROBE = np.arange(20, dtype=np.float32).reshape(5, 4) / 10.0


# -------------------------------------------------------------- pin / unpin

def test_pin_survives_rotation_and_manifest_roundtrip(tmp_path):
    mgr = CheckpointManager(tmp_path, keep_last=2)
    net = _mlp()
    first = _save_at(mgr, net, 1)
    mgr.pin(1)
    for it in range(2, 8):
        _save_at(mgr, net, it)
    # the pinned checkpoint outlived six rotations of a keep_last=2 window
    assert os.path.exists(first)
    live = {c.iteration: c.pinned for c in mgr.checkpoints()}
    assert live[1] is True
    assert set(live) == {1, 6, 7}
    # manifest round-trip: a fresh manager (new process) sees the pin
    mgr2 = CheckpointManager(tmp_path, keep_last=2)
    assert {c.iteration: c.pinned for c in mgr2.checkpoints()}[1] is True
    # unpin → immediately re-enters rotation and is rotated away (it is
    # far outside the keep_last window)
    mgr2.unpin(1)
    assert not os.path.exists(first)
    assert {c.iteration for c in mgr2.checkpoints()} == {6, 7}


def test_pin_unknown_iteration_raises(tmp_path):
    mgr = CheckpointManager(tmp_path, keep_last=2)
    _save_at(mgr, _mlp(), 5)
    with pytest.raises(ValueError, match="live iterations"):
        mgr.pin(99)
    mgr.pin(5)
    mgr.pin(5)          # idempotent


# ------------------------------------------------- zero-compile weight swap

def test_engine_swap_zero_new_compiles_and_versions():
    serving, donor = _mlp(seed=42), _mlp(seed=11)
    eng = InferenceEngine(serving, max_batch=16)
    eng.warmup((4,), max_batch=16)
    warm = eng.trace_count
    before = np.asarray(eng.predict_host(X_PROBE))
    assert eng.model_version == 0

    v = eng.swap_weights(donor.params, donor.state)
    after = np.asarray(eng.predict_host(X_PROBE))
    assert v == 1 and eng.model_version == 1
    assert eng.trace_count == warm, "hot swap must not trace new programs"
    assert not np.array_equal(before, after), "swap must change outputs"
    # donor-derived reference: swapped engine serves the donor's function
    assert np.allclose(after, np.asarray(donor.output(X_PROBE)),
                       atol=0, rtol=0)
    assert eng.stats()["model_version"] == 1


def test_engine_swap_mismatch_rejected_before_state_changes():
    serving = _mlp()
    eng = InferenceEngine(serving, max_batch=16)
    eng.warmup((4,), max_batch=16)
    baseline = np.asarray(eng.predict_host(X_PROBE))
    good = serving.params

    # shape mismatch (a wider hidden layer)
    import jax
    wide = jax.tree_util.tree_map(
        lambda a: np.zeros((a.shape[0], 32), a.dtype)
        if getattr(a, "shape", ())[-1:] == (16,) else np.asarray(a), good)
    with pytest.raises(WeightSwapError, match="expected"):
        eng.swap_weights(wide)

    # dtype mismatch
    halved = jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float16), good)
    with pytest.raises(WeightSwapError, match="float16"):
        eng.swap_weights(halved)

    # structure mismatch (missing layer: params is a per-layer list)
    with pytest.raises(WeightSwapError, match="missing array"):
        eng.swap_weights(list(good)[:-1])

    # the live engine was never touched: same version, bitwise outputs
    assert eng.model_version == 0
    assert np.array_equal(baseline, np.asarray(eng.predict_host(X_PROBE)))


def test_decode_swap_zero_compiles_and_inflight_finishes_on_old_weights():
    old, new = _lstm(seed=7), _lstm(seed=23)
    eng = DecodeEngine(old, slots=2, max_len=48)
    eng.warmup()            # before start(): the loop thread owns the
    eng.start()             # decode state once it runs
    try:
        warm = eng.trace_count
        assert warm == 1, "one program covers every schedule"
        prompt = [1, 2, 3]

        fut = eng.submit(prompt, max_new_tokens=24)
        # wait until the request holds a slot: a swap staged before
        # admission would (correctly) pause admission and the generation
        # would run on the NEW weights — not the scenario under test
        deadline = time.monotonic() + 10
        while not any(r is not None for r in eng._slot_reqs):
            assert time.monotonic() < deadline, "request never admitted"
            time.sleep(0.001)
        v = eng.swap_weights(new.params, new.state)   # blocks until applied
        got = fut.result(timeout=30)

        ref_old = generate_naive(old, prompt, 24, max_len=48)
        assert got["tokens"] == ref_old["tokens"], \
            "in-flight generation must finish on the old weights"
        assert v == 1 and eng.model_version == 1

        got2 = eng.generate(prompt, max_new_tokens=24, timeout=30)
        ref_new = generate_naive(new, prompt, 24, max_len=48)
        assert got2["tokens"] == ref_new["tokens"], \
            "post-swap generation must run on the new weights"
        assert eng.trace_count == warm, "swap must not trace new programs"
    finally:
        eng.stop()


# ------------------------------------------------------- HTTP admin surface

def test_admin_swap_http_and_model_version_header(tmp_path):
    serving, donor = _mlp(seed=42), _mlp(seed=11)
    ck_good = str(tmp_path / "good.zip")
    model_serializer.write_model(donor, ck_good)
    ck_bad = str(tmp_path / "bad.zip")
    model_serializer.write_model(_lstm(), ck_bad)

    srv = InferenceServer(serving, port=0, max_latency_ms=1.0)
    srv.start()
    cli = InferenceClient(f"http://127.0.0.1:{srv.port}", retries=1)
    try:
        srv.engine.warmup((4,), max_batch=srv.engine.max_batch)
        warm = srv.engine.trace_count

        def predict_version():
            body = json.dumps(
                {"ndarray": _b64(X_PROBE)}).encode()
            st, data, hdrs = cli.post_raw("/predict", body)
            assert st == 200, data
            mv = {k.lower(): v for k, v in hdrs.items()}["x-model-version"]
            return int(mv), _from_b64(json.loads(data)["ndarray"])

        v0, out0 = predict_version()
        assert v0 == 0

        st, data, _ = cli.post_raw("/admin/swap", json.dumps(
            {"checkpoint": ck_good}).encode())
        assert st == 200, data
        rep = json.loads(data)
        assert rep["swapped"] and rep["version"] == 1
        assert rep["compiled_programs"] == warm

        v1, out1 = predict_version()
        assert v1 == 1
        assert not np.array_equal(out0, out1)
        assert srv.engine.trace_count == warm

        # incompatible architecture → 409, engine untouched
        st, data, _ = cli.post_raw("/admin/swap", json.dumps(
            {"checkpoint": ck_bad}).encode())
        assert st == 409, data
        assert json.loads(data)["error"]["type"] == "weight_mismatch"
        assert predict_version()[0] == 1

        # missing checkpoint → 400
        st, data, _ = cli.post_raw("/admin/swap", json.dumps(
            {"checkpoint": str(tmp_path / "nope.zip")}).encode())
        assert st == 400, data
        assert json.loads(data)["error"]["type"] == "bad_checkpoint"
    finally:
        cli.close()
        srv.stop()


def _b64(a):
    from deeplearning4j_tpu.clustering.knn_server import ndarray_to_b64
    return ndarray_to_b64(np.asarray(a))


def _from_b64(o):
    from deeplearning4j_tpu.clustering.knn_server import ndarray_from_b64
    return ndarray_from_b64(o)


def test_router_forwards_model_version_header(tmp_path):
    from deeplearning4j_tpu.serving.replica import InProcessReplica
    donor = _mlp(seed=11)
    ck = str(tmp_path / "donor.zip")
    model_serializer.write_model(donor, ck)
    rep = InProcessReplica(model="mlp", chaos=False).start()
    router = Router([rep.url], port=0, probe_interval=None).start()
    cli = InferenceClient(f"http://127.0.0.1:{router.port}", retries=1)
    try:
        rep.srv.swap_checkpoint(ck)
        body = json.dumps({"ndarray": _b64(X_PROBE)}).encode()
        st, data, hdrs = cli.post_raw("/predict", body)
        assert st == 200, data
        low = {k.lower(): v for k, v in hdrs.items()}
        assert low.get("x-model-version") == "1", \
            "router must forward the replica's model-version header"
    finally:
        cli.close()
        router.stop()
        rep.stop()


# -------------------------------------------------------------- guardrails

def test_guard_quarantines_nan_and_loss_spike():
    net = _mlp()
    guard = BatchGuard(net, spike_factor=3.0, warmup=2)
    base = _counter_value("dl4jtpu_online_quarantined_batches_total",
                          reason="non_finite")
    x, y = PROB.batch(16, phase=0, seed=0)

    bad = x.copy()
    bad[3, 1] = np.nan
    assert guard.check(bad, y) == "non_finite"
    assert _counter_value("dl4jtpu_online_quarantined_batches_total",
                          reason="non_finite") == base + 1

    for seed in range(4):                 # establish the EMA baseline
        cx, cy = PROB.batch(16, phase=0, seed=seed)
        assert guard.check(cx, cy) is None

    # saturating features + adversarial labels → loss far above the EMA
    sx, sy = PROB.batch(16, phase=0, seed=50)
    spike_x = sx * 50.0
    spike_y = np.roll(sy, 1, axis=1)
    assert guard.check(spike_x, spike_y) == "loss_spike"

    # quarantine never touched the weights: clean batches still pass
    cx, cy = PROB.batch(16, phase=0, seed=60)
    assert guard.check(cx, cy) is None


def test_stream_stall_degrades_health_then_recovers(tmp_path):
    net = _mlp()
    it = StreamingDataSetIterator(batch_size=16, stall_timeout=0.2)
    trainer = OnlineTrainer(net, it, CheckpointManager(tmp_path),
                            batches_per_round=2)
    srv = InferenceServer(net, port=0, health_hook=trainer.health_info)
    # silent stream → the round ends stalled instead of raising
    assert trainer.run_round() is None
    assert trainer.stalled
    assert srv.health_info() == {"status": "degraded",
                                 "reason": "stream_stalled"}
    # stream comes back → next round trains and health recovers
    x, y = PROB.batch(32, phase=0, seed=1)
    it.push(x, y, batched=True)
    assert trainer.run_round() is not None
    assert not trainer.stalled
    assert srv.health_info()["status"] == "ok"


def test_kafka_route_stall_timeout_passthrough():
    broker = InMemoryBroker()
    route = NDArrayPubSubRoute(broker, "t", batch_size=2, stall_timeout=0.2)
    with pytest.raises(StreamStalledError):
        next(route.iterator)
    # the stalled iterator stays usable once records arrive
    pub = NDArrayPublisher(broker, "t")
    PROB.publish(pub, 2, phase=0, seed=0)
    route.start()
    try:
        ds = next(route.iterator)
        assert ds.features.shape == (2, 4)
    finally:
        route.stop()


# ---------------------------------------------------------------- the gate

def test_promotion_gate_decisions():
    ex, ey = PROB.eval_set(128, phase=0)
    perfect = lambda x: np.eye(3, dtype=np.float32)[  # noqa: E731
        np.argmax(x @ PROB.weights(0), axis=1)]
    rng = np.random.default_rng(0)
    noisy = lambda x: rng.random((x.shape[0], 3))     # noqa: E731

    gate = PromotionGate(ex, ey, min_improvement=0.0,
                         max_shadow_disagreement=0.5)
    # bootstrap: no incumbent → promote
    d = gate.decide(perfect, None)
    assert d.promote and "bootstrap" in d.reason

    # clear winner promotes; clear loser is rejected
    assert gate.decide(perfect, noisy).promote
    d = gate.decide(noisy, perfect)
    assert not d.promote and "quality bar" in d.reason

    # shadow-disagreement ceiling blocks even a quality-equal candidate
    mirror = TrafficMirror()
    mirror.record(PROB.batch(32, phase=0, seed=3)[0])
    flipped = lambda x: np.roll(perfect(x), 1, axis=1)  # noqa: E731
    tight = PromotionGate(ex, ey, min_improvement=-1.0,
                          max_shadow_disagreement=0.1)
    d = tight.decide(flipped, perfect, mirror)
    assert not d.promote and "disagreement" in d.reason
    assert d.shadow_disagreement > 0.9


# ------------------------------------------------------- deploy + rollback

def test_deployer_promote_rollback_bitwise(tmp_path):
    serving = _mlp(seed=42)
    eng = InferenceEngine(serving, max_batch=16)
    eng.warmup((4,), max_batch=16)
    warm = eng.trace_count

    mgr = CheckpointManager(tmp_path, keep_last=2)
    ck_a = _save_at(mgr, _mlp(seed=11), 1)
    ck_b = _save_at(mgr, _mlp(seed=12), 2)
    dep = Deployer(mgr, targets=[EngineTarget(eng)])

    assert dep.promote(ck_a) == 1
    out_a = np.asarray(eng.predict_host(X_PROBE))
    assert dep.promote(ck_b) == 2
    out_b = np.asarray(eng.predict_host(X_PROBE))
    assert not np.array_equal(out_a, out_b)
    pins = {c.iteration: c.pinned for c in mgr.checkpoints()}
    assert pins[1] and pins[2], "current AND rollback target stay pinned"

    v = dep.rollback()
    assert v == 3, "rollback mints a NEW monotonic version"
    assert eng.model_version == 3
    restored = np.asarray(eng.predict_host(X_PROBE))
    assert np.array_equal(restored, out_a), \
        "rollback must restore the incumbent bitwise"
    assert eng.trace_count == warm
    with pytest.raises(RuntimeError, match="no previous"):
        dep.rollback()
    state = json.loads((tmp_path / "deploy.json").read_text())
    assert state["phase"] == "live" and state["version"] == 3


def test_deployer_recovers_mid_promotion_crash(tmp_path):
    net1, net2 = _mlp(seed=42), _mlp(seed=42)
    e1 = InferenceEngine(net1, max_batch=16)
    e2 = InferenceEngine(net2, max_batch=16)
    mgr = CheckpointManager(tmp_path, keep_last=3)
    ck_a = _save_at(mgr, _mlp(seed=11), 1)
    ck_b = _save_at(mgr, _mlp(seed=12), 2)

    targets = [EngineTarget(e1), EngineTarget(e2)]
    dep = Deployer(mgr, targets=targets)
    dep.promote(ck_a)

    def crash():
        raise SimulatedCrash("killed between target swaps")
    dep.chaos_mid_promotion = crash
    with pytest.raises(SimulatedCrash):
        dep.promote(ck_b)
    # split brain: e1 already swapped to B, e2 still serves A
    assert e1.model_version == 2 and e2.model_version == 1

    # "restart": a fresh Deployer reads the promoting intent and, with the
    # candidate zip intact, finishes the promotion on every target
    dep2 = Deployer(mgr, targets=targets)
    assert dep2.recover() == "promoted"
    o1 = np.asarray(e1.predict_host(X_PROBE))
    o2 = np.asarray(e2.predict_host(X_PROBE))
    assert np.array_equal(o1, o2), "recover must converge the tier"
    assert dep2.current["checkpoint"] == ck_b

    # same crash but the candidate zip is TORN → converge back onto the
    # pinned incumbent instead
    ck_c = _save_at(mgr, _mlp(seed=13), 3)
    dep2.chaos_mid_promotion = crash
    with pytest.raises(SimulatedCrash):
        dep2.promote(ck_c)
    with open(ck_c, "r+b") as fh:       # torn zip: truncate mid-archive
        fh.truncate(100)
    dep3 = Deployer(mgr, targets=targets)
    assert dep3.recover() == "reverted"
    o1 = np.asarray(e1.predict_host(X_PROBE))
    o2 = np.asarray(e2.predict_host(X_PROBE))
    assert np.array_equal(o1, o2)
    assert dep3.current["checkpoint"] == ck_b


# ------------------------------------------------------- assembled service

def _stack(tmp_path, engine_targets, batches_per_round=6):
    net, scratch = build_model("mlp"), build_model("mlp")
    it = StreamingDataSetIterator(batch_size=16)
    mgr = CheckpointManager(os.path.join(tmp_path, "ck"), keep_last=3)
    trainer = OnlineTrainer(net, it, mgr, guard=BatchGuard(net),
                            batches_per_round=batches_per_round)
    ex, ey = PROB.eval_set(128, phase=0)
    gate = PromotionGate(ex, ey, min_improvement=0.0)
    mirror = TrafficMirror()
    dep = Deployer(mgr, targets=list(engine_targets))
    svc = OnlineLearningService(trainer, gate, dep, scratch, mirror=mirror,
                                regression_margin=0.05)
    return net, it, trainer, gate, mirror, dep, svc


def _feed(it, phase, seeds):
    for s in seeds:
        x, y = PROB.batch(16, phase=phase, seed=s)
        it.push(x, y, batched=True)


def test_service_trains_promotes_and_improves(tmp_path):
    serving = build_model("mlp")
    eng = InferenceEngine(serving, max_batch=16)
    eng.warmup((4,), max_batch=16)
    warm = eng.trace_count
    net, it, trainer, gate, mirror, dep, svc = _stack(
        str(tmp_path), [EngineTarget(eng)])

    seed, qualities = 0, []
    for _ in range(5):
        _feed(it, 0, range(seed, seed + 6))
        seed += 6
        mirror.record(PROB.batch(8, phase=0, seed=5000 + seed)[0])
        out = svc.step()
        assert out["trained"]
        if out["promoted"]:
            qualities.append(out["decision"]["candidate_quality"])
    assert len(qualities) >= 2, "expected at least two promotions"
    assert qualities[-1] > qualities[0], "quality must improve"
    assert eng.model_version == dep.version >= 2
    assert eng.trace_count == warm, "no swap may compile anything new"


def test_service_forced_regression_rolls_back_bitwise(tmp_path):
    serving = build_model("mlp")
    eng = InferenceEngine(serving, max_batch=16)
    eng.warmup((4,), max_batch=16)
    net, it, trainer, gate, mirror, dep, svc = _stack(
        str(tmp_path), [EngineTarget(eng)])

    _feed(it, 0, range(6))
    out = svc.step()
    assert out["promoted"] and not out["rolled_back"]
    v_good = out["version"]
    incumbent_out = np.asarray(eng.predict_host(X_PROBE))

    # force a bad candidate through the gate: mislabeled training tanks
    # quality, min_improvement=-inf promotes it anyway — the regression
    # watch must catch it and roll back. The BatchGuard would (correctly)
    # quarantine this poison, so it is disabled for the forced run.
    gate.min_improvement = -1e9
    svc.regression_margin = 0.02
    trainer.guard = None
    trainer.batches_per_round = 12
    for s in range(100, 112):
        x, y = PROB.batch(16, phase=0, seed=s)
        it.push(x, np.roll(y, 1, axis=1), batched=True)
    out = svc.step()
    assert out["promoted"] and out["rolled_back"], out
    assert out["version"] == v_good + 2, "promote + rollback, both versioned"
    assert np.array_equal(np.asarray(eng.predict_host(X_PROBE)),
                          incumbent_out), \
        "rollback must restore the incumbent outputs bitwise"


# ------------------------------------------------------------------- slow

@pytest.mark.slow
def test_online_soak_hot_swaps_under_live_traffic(tmp_path):
    """≥3 promotions across a drifting stream while live HTTP /predict
    traffic flows: zero failed requests, zero new compiles per swap,
    monotonic model versions on the wire, and a forced regression at the
    end that rolls back bitwise — all through a real server socket."""
    serving = build_model("mlp")
    mirror = TrafficMirror()
    net, it, trainer, gate, _m, dep, svc = _stack(str(tmp_path), [],
                                                  batches_per_round=8)
    svc.mirror = mirror
    srv = InferenceServer(serving, port=0, max_latency_ms=1.0,
                          health_hook=svc.health_info,
                          request_mirror=mirror.record)
    srv.start()
    dep.targets.append(ServerTarget(srv))
    srv.engine.warmup((4,), max_batch=srv.engine.max_batch)
    warm = srv.engine.trace_count

    phase_box = [0]
    failures, versions = [], []
    stop = threading.Event()

    def traffic():
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}", retries=1)
        rng = np.random.default_rng(99)
        try:
            while not stop.is_set():
                x = PROB.batch(4, phase=phase_box[0],
                               seed=int(rng.integers(1 << 30)))[0]
                body = json.dumps({"ndarray": _b64(x)}).encode()
                try:
                    st, data, hdrs = cli.post_raw("/predict", body)
                except Exception as e:      # noqa: BLE001
                    failures.append(repr(e))
                    continue
                if st != 200:
                    failures.append((st, data[:200]))
                    continue
                low = {k.lower(): v for k, v in hdrs.items()}
                versions.append(int(low["x-model-version"]))
                out = _from_b64(json.loads(data)["ndarray"])
                if not np.all(np.isfinite(out)):
                    failures.append("non-finite prediction")
                time.sleep(0.002)
        finally:
            cli.close()

    th = threading.Thread(target=traffic, daemon=True)
    th.start()
    try:
        promotions, seed = 0, 0
        for rnd in range(9):
            phase = rnd // 3
            if phase != phase_box[0]:
                phase_box[0] = phase
                gate.set_eval_set(*PROB.eval_set(256, phase=phase))
            _feed(it, phase, range(seed, seed + 8))
            seed += 8
            out = svc.step()
            assert out["trained"], out
            assert not out["rolled_back"], out
            if out["promoted"]:
                promotions += 1
                assert srv.engine.trace_count == warm, \
                    "swap under traffic must not compile"
            time.sleep(0.3)     # let live traffic observe this version
        assert promotions >= 3, f"only {promotions} promotions"
        assert dep.version == promotions

        # forced regression over the same live tier (guard off — it would
        # rightly quarantine the poison this block trains on)
        pre = np.asarray(srv.engine.predict_host(X_PROBE))
        gate.min_improvement = -1e9
        svc.regression_margin = 0.02
        trainer.guard = None
        trainer.batches_per_round = 12
        for s in range(5000, 5012):
            x, y = PROB.batch(16, phase=phase_box[0], seed=s)
            it.push(x, np.roll(y, 1, axis=1), batched=True)
        out = svc.step()
        assert out["promoted"] and out["rolled_back"], out
        assert np.array_equal(pre,
                              np.asarray(srv.engine.predict_host(X_PROBE)))
    finally:
        stop.set()
        th.join(timeout=30)
        srv.stop()

    assert not failures, f"{len(failures)} failed requests: {failures[:5]}"
    assert len(versions) > 30, "traffic thread barely ran"
    assert versions == sorted(versions), \
        "model versions on the wire must be monotonic"
    assert versions[-1] >= 3, "traffic never saw the swaps land"
    assert mirror.seen > 0, "live traffic must reach the shadow mirror"


@pytest.mark.slow
def test_online_trainer_sigkill_chaos(tmp_path):
    """SIGKILL the online trainer mid-fine-tune and mid-promotion; each
    relaunch resumes from the manifest and converges the deploy intent;
    the parent's serving server answers correctly throughout."""
    serving = build_model("mlp")
    srv = InferenceServer(serving, port=0, max_latency_ms=1.0)
    srv.start()
    url = f"http://127.0.0.1:{srv.port}"
    cli = InferenceClient(url, retries=1)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (str(_WORKER.parent.parent) + os.pathsep
                         + env.get("PYTHONPATH", ""))

    def probe():
        out = cli.predict(X_PROBE)
        assert out.shape == (5, 3) and np.all(np.isfinite(out))
        return np.asarray(out)

    def run(*extra):
        cmd = [sys.executable, str(_WORKER), "--dir", str(tmp_path),
               "--server-url", url, *extra]
        return subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=600)

    try:
        probe()
        # 1: die right after the 2nd checkpoint save (mid-fine-tune)
        r1 = run("--rounds", "10", "--kill-after-saves", "2")
        assert r1.returncode == -9, (r1.returncode, r1.stdout, r1.stderr)
        assert "WORKER_SELF_KILL after_save" in r1.stdout
        mgr = CheckpointManager(tmp_path / "ck", keep_last=3)
        assert mgr.latest() is not None, "no checkpoint survived the kill"
        probe()

        # 2: die mid-promotion — after the serving target swapped, before
        # the intent file says live
        r2 = run("--rounds", "2", "--kill-at-promotion")
        assert r2.returncode == -9, (r2.returncode, r2.stdout, r2.stderr)
        assert "WORKER_SELF_KILL mid_promotion" in r2.stdout
        state = json.loads((tmp_path / "deploy.json").read_text())
        assert state["phase"] == "promoting"
        probe()     # server still serves (already-swapped weights are fine)

        # 3: clean relaunch resumes from the manifest, converges the
        # promotion, and finishes its rounds
        r3 = run("--rounds", "3")
        assert r3.returncode == 0, (r3.returncode, r3.stdout, r3.stderr)
        assert "WORKER_RESUMED from=" in r3.stdout
        assert "from=None" not in r3.stdout, "must resume, not start fresh"
        assert "WORKER_RECOVERED outcome=promoted" in r3.stdout
        assert "WORKER_DONE" in r3.stdout
        state = json.loads((tmp_path / "deploy.json").read_text())
        assert state["phase"] == "live"
        assert srv.engine.model_version >= 1
        probe()
    finally:
        cli.close()
        srv.stop()
