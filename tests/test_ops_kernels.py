"""Accelerated-kernel equivalence tests.

Pattern parity: deeplearning4j-cuda/src/test ValidateCudnnLSTM.java /
TestConvolution.java — run the same input through the built-in (pure jnp)
path and the accelerated (Pallas) path and assert outputs AND gradients
match (SURVEY.md §4 'accelerator-vs-reference equivalence tests'). On CPU
the Pallas kernels run in interpreter mode.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import ops


@pytest.fixture
def helpers_on():
    ops.set_helpers_enabled(True, interpret=True)
    yield
    ops.set_helpers_enabled(None)


def _lstm_layer(n_in=6, n_out=8):
    from deeplearning4j_tpu.nn.layers.rnn import LSTM
    lyr = LSTM(n_in=n_in, n_out=n_out)
    params = lyr.init(jax.random.PRNGKey(0))
    return lyr, params


class TestFusedLSTM:
    def test_forward_matches_reference(self, helpers_on):
        lyr, params = _lstm_layer()
        x = jnp.asarray(np.random.RandomState(1).randn(4, 10, 6), jnp.float32)

        ops.set_helpers_enabled(False)
        ref, _ = lyr.apply(params, x)
        ops.set_helpers_enabled(True, interpret=True)
        fused, _ = lyr.apply(params, x)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_reference(self, helpers_on):
        lyr, params = _lstm_layer(n_in=5, n_out=7)
        x = jnp.asarray(np.random.RandomState(2).randn(3, 6, 5), jnp.float32)
        tgt = jnp.asarray(np.random.RandomState(3).randn(3, 6, 7), jnp.float32)

        def loss(p, x):
            y, _ = lyr.apply(p, x)
            return jnp.sum((y - tgt) ** 2)

        ops.set_helpers_enabled(False)
        ref_gp, ref_gx = jax.grad(loss, argnums=(0, 1))(params, x)
        ops.set_helpers_enabled(True, interpret=True)
        fu_gp, fu_gx = jax.grad(loss, argnums=(0, 1))(params, x)

        np.testing.assert_allclose(np.asarray(fu_gx), np.asarray(ref_gx),
                                   rtol=1e-4, atol=1e-4)
        for k in ref_gp:
            np.testing.assert_allclose(np.asarray(fu_gp[k]),
                                       np.asarray(ref_gp[k]),
                                       rtol=1e-4, atol=1e-4, err_msg=k)

    def test_carry_states_match(self, helpers_on):
        lyr, params = _lstm_layer()
        x = jnp.asarray(np.random.RandomState(4).randn(2, 5, 6), jnp.float32)
        ops.set_helpers_enabled(False)
        _, (h_ref, c_ref) = lyr.apply_with_carry(params, x)
        ops.set_helpers_enabled(True, interpret=True)
        _, (h_fu, c_fu) = lyr.apply_with_carry(params, x)
        np.testing.assert_allclose(np.asarray(h_fu), np.asarray(h_ref),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c_fu), np.asarray(c_ref),
                                   rtol=1e-5, atol=1e-5)

    def test_graves_falls_back(self, helpers_on):
        """Peephole LSTM is unsupported by the fused kernel — must still work
        (via the reference path), parity with cuDNN helper null-fallback."""
        from deeplearning4j_tpu.nn.layers.rnn import GravesLSTM
        lyr = GravesLSTM(n_in=4, n_out=5)
        params = lyr.init(jax.random.PRNGKey(0))
        x = jnp.ones((2, 3, 4), jnp.float32)
        y, _ = lyr.apply(params, x)
        assert y.shape == (2, 3, 5)
        assert np.all(np.isfinite(np.asarray(y)))


class TestFlashAttention:
    def _ref(self, q, k, v, causal):
        scale = 1.0 / jnp.sqrt(q.shape[-1])
        s = jnp.einsum("btd,bsd->bts", q, k) * scale
        if causal:
            t = q.shape[1]
            m = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(m, s, -jnp.inf)
        return jnp.einsum("bts,bsd->btd", jax.nn.softmax(s, -1), v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward(self, helpers_on, causal):
        rs = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rs.randn(2, 16, 4), jnp.float32)
                   for _ in range(3))
        o = ops.flash_attention(q, k, v, causal, True)
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(self._ref(q, k, v, causal)),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients(self, helpers_on, causal):
        rs = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rs.randn(2, 16, 4), jnp.float32)
                   for _ in range(3))

        def f_fa(q, k, v):
            return jnp.sum(ops.flash_attention(q, k, v, causal, True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(self._ref(q, k, v, causal) ** 2)

        g_fa = jax.grad(f_fa, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_fa, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4, err_msg=name)

    def test_layer_routes_through_flash(self, helpers_on):
        from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention
        lyr = MultiHeadAttention(n_in=16, n_heads=2, causal=True)
        lyr.set_n_in(type("T", (), {"size": 16, "flat_size": lambda s: 16})())
        params = lyr.init(jax.random.PRNGKey(0))
        # Dh = 16/2 = 8 satisfies supported()'s dh % 8 == 0, so this shape
        # actually engages the flash kernel (smaller Dh falls back and the
        # comparison would be vacuous)
        from deeplearning4j_tpu.ops.flash_attention import supported
        assert supported(16, 8)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 16, 16), jnp.float32)
        y_fa, _ = lyr.apply(params, x)
        ops.set_helpers_enabled(False)
        y_ref, _ = lyr.apply(params, x)
        np.testing.assert_allclose(np.asarray(y_fa), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-4)


class TestFusedStackedLSTM:
    """Wavefront 2-layer kernel (ops.fused_lstm2_sequence) must equal two
    sequential fused/scan layers — outputs and every gradient."""

    def _net_2lstm(self, vocab=6, H=8):
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers.rnn import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        conf = (NeuralNetConfiguration.builder().seed(3).list()
                .layer(LSTM(n_out=H, activation="tanh"))
                .layer(LSTM(n_out=H, activation="tanh"))
                .layer(RnnOutputLayer(n_out=vocab, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(vocab))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_pair_matches_sequential_outputs_and_grads(self, helpers_on):
        from deeplearning4j_tpu.nn.layers.rnn import lstm_pair_fusable
        net = self._net_2lstm()
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(4, 10, 6), jnp.float32)
        y = jnp.asarray(np.eye(6, dtype=np.float32)[
            rs.randint(0, 6, (4, 10))])
        assert lstm_pair_fusable(net.layers[0], net.layers[1],
                                 net.params[0], net.params[1], x, None)

        def loss(p, x):
            l, _ = net._loss(p, net.state, x, y, None, None, None)
            return l

        # fused pair (helpers on, interpret)
        l_pair = float(loss(net.params, x))
        g_pair = jax.grad(loss, argnums=(0, 1))(net.params, x)
        # sequential reference (helpers off -> pure scan layers)
        ops.set_helpers_enabled(False)
        l_seq = float(loss(net.params, x))
        g_seq = jax.grad(loss, argnums=(0, 1))(net.params, x)
        ops.set_helpers_enabled(True, interpret=True)

        assert abs(l_pair - l_seq) < 1e-5, (l_pair, l_seq)
        np.testing.assert_allclose(np.asarray(g_pair[1]),
                                   np.asarray(g_seq[1]),
                                   rtol=1e-4, atol=1e-5, err_msg="dx")
        for li, (pp, ps) in enumerate(zip(g_pair[0], g_seq[0])):
            for k in ps:
                np.testing.assert_allclose(
                    np.asarray(pp[k]), np.asarray(ps[k]),
                    rtol=1e-4, atol=1e-5, err_msg=f"layer{li}/{k}")

    def test_pair_inference_matches_sequential(self, helpers_on):
        net = self._net_2lstm()
        rs = np.random.RandomState(7)
        x = np.asarray(rs.randn(3, 8, 6), np.float32)
        out_pair = np.asarray(net.output(x))
        ops.set_helpers_enabled(False)
        net._output_fn = None
        out_seq = np.asarray(net.output(x))
        ops.set_helpers_enabled(True, interpret=True)
        net._output_fn = None
        np.testing.assert_allclose(out_pair, out_seq, rtol=1e-5, atol=1e-5)

    def test_pair_not_fused_with_dropout_between(self, helpers_on):
        """Inter-layer dropout blocks fusion (falls back, still correct)."""
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers.rnn import (LSTM, RnnOutputLayer,
                                                      lstm_pair_fusable)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        conf = (NeuralNetConfiguration.builder().seed(3).list()
                .layer(LSTM(n_out=8))
                .layer(LSTM(n_out=8, dropout=0.5))
                .layer(RnnOutputLayer(n_out=6, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(6))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = jnp.ones((2, 4, 6), jnp.float32)
        assert not lstm_pair_fusable(net.layers[0], net.layers[1],
                                     net.params[0], net.params[1], x, None)
        y = np.eye(6, dtype=np.float32)[np.zeros((2, 4), int)]
        net.fit(np.asarray(x), y)
        assert np.isfinite(net.get_score())
