"""Minor parity items (VERDICT r1 missing #7 + weak #8):
JointParallelDataSetIterator, CnnSentenceDataSetIterator, and
ComputationGraph external epsilons."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    ListDataSetIterator, JointParallelDataSetIterator, InequalityHandling,
)


def _it(n, batch=2, f=3, seed=0):
    rs = np.random.RandomState(seed)
    return ListDataSetIterator(
        DataSet(rs.randn(n, f).astype(np.float32),
                np.eye(2, dtype=np.float32)[rs.randint(0, 2, n)]), batch)


class TestJointParallelIterator:
    def test_per_consumer_feeds(self):
        j = JointParallelDataSetIterator([_it(8), _it(8, seed=1)],
                                         async_prefetch=False)
        j.reset()
        assert j.num_producers == 2
        a = j.next_for(0)
        b = j.next_for(1)
        assert a.features.shape == (2, 3) and b.features.shape == (2, 3)
        assert not np.allclose(a.features, b.features)

    def test_stop_everyone(self):
        j = JointParallelDataSetIterator(
            [_it(2), _it(8)], InequalityHandling.STOP_EVERYONE,
            async_prefetch=False)
        j.reset()
        assert j.has_next_for(0)
        j.next_for(0)
        assert not j.has_next_for(0)     # producer 0 dry → everyone stops
        assert not j.has_next_for(1)
        assert j.next_for(1) is None

    def test_pass_null(self):
        j = JointParallelDataSetIterator(
            [_it(2), _it(6)], InequalityHandling.PASS_NULL,
            async_prefetch=False)
        j.reset()
        j.next_for(0)
        assert j.next_for(0) is None     # dry producer passes null
        assert j.next_for(1) is not None  # others continue

    def test_reset_policy_replays(self):
        j = JointParallelDataSetIterator(
            [_it(2)], InequalityHandling.RESET, async_prefetch=False)
        j.reset()
        seen = [j.next_for(0) for _ in range(4)]   # 1 batch/epoch, replayed
        assert all(s is not None for s in seen)

    def test_relocate_steals(self):
        j = JointParallelDataSetIterator(
            [_it(2), _it(8, seed=1)], InequalityHandling.RELOCATE,
            async_prefetch=False)
        j.reset()
        j.next_for(0)
        stolen = j.next_for(0)           # producer 0 dry → takes from 1
        assert stolen is not None

    def test_round_robin_iteration_covers_all(self):
        j = JointParallelDataSetIterator(
            [_it(4), _it(4, seed=1)], InequalityHandling.PASS_NULL,
            async_prefetch=False)
        batches = list(j)
        assert len(batches) == 4          # 2 per producer, interleaved

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            JointParallelDataSetIterator([])


class _ToyVectors:
    def __init__(self, words, dim=4, seed=0):
        rs = np.random.RandomState(seed)
        self._v = {w: rs.randn(dim).astype(np.float32) for w in words}

    def has_word(self, w):
        return w in self._v

    def word_vector(self, w):
        return self._v[w]


class TestCnnSentenceIterator:
    def _data(self):
        return [("the cat sat", "animal"), ("stocks fell hard today", "money"),
                ("a cat and a dog", "animal"), ("the market rallied", "money")]

    def _wv(self):
        words = {w for s, _ in self._data() for w in s.split()} - {"dog"}
        return _ToyVectors(sorted(words))

    def test_shapes_masks_labels(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        it = CnnSentenceDataSetIterator(self._data(), self._wv(),
                                        batch_size=4)
        ds = next(iter(it))
        B, L, D, C = ds.features.shape
        assert B == 4 and D == 4 and C == 1
        assert ds.features_mask.shape == (B, L)
        # 'dog' unknown → removed: that sentence has 4 known tokens
        assert ds.labels.shape == (4, 2)
        assert set(it.labels) == {"animal", "money"}
        np.testing.assert_allclose(ds.labels.sum(1), 1.0)
        # masked positions are zero
        assert np.all(ds.features[ds.features_mask == 0] == 0)

    def test_unknown_vector_mode_keeps_tokens(self):
        from deeplearning4j_tpu.nlp import (CnnSentenceDataSetIterator,
                                            UnknownWordHandling)
        it_rm = CnnSentenceDataSetIterator(self._data(), self._wv(),
                                           batch_size=4)
        it_uk = CnnSentenceDataSetIterator(
            self._data(), self._wv(), batch_size=4,
            unknown_word_handling=UnknownWordHandling.USE_UNKNOWN_VECTOR)
        n_rm = next(iter(it_rm)).features_mask.sum()
        n_uk = next(iter(it_uk)).features_mask.sum()
        assert n_uk == n_rm + 1           # 'dog' kept as the unknown vector

    def test_load_single_sentence(self):
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        it = CnnSentenceDataSetIterator(self._data(), self._wv())
        arr = it.load_single_sentence("the cat sat")
        assert arr.shape == (1, 3, 4, 1)

    def test_trains_sentence_cnn(self):
        """End-to-end: the emitted batches actually train a conv net."""
        from deeplearning4j_tpu.nlp import CnnSentenceDataSetIterator
        from deeplearning4j_tpu import (NeuralNetConfiguration,
                                        MultiLayerNetwork)
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                                  GlobalPoolingLayer,
                                                  OutputLayer)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.updaters import Adam
        it = CnnSentenceDataSetIterator(self._data() * 4, self._wv(),
                                        batch_size=4,
                                        max_sentence_length=6)
        ds = next(iter(it))
        L = ds.features.shape[1]
        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=(2, 4),
                                        activation="relu"))
                .layer(GlobalPoolingLayer(pooling_type="max"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(L, 4, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(ds.features, ds.labels)
        assert np.isfinite(net.get_score())


class TestCGExternalEpsilons:
    def _cg(self):
        from deeplearning4j_tpu import NeuralNetConfiguration
        from deeplearning4j_tpu.models import ComputationGraph
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        g = (NeuralNetConfiguration.builder().seed(4).updater(Sgd(0.1))
             .weight_init("xavier").l2(1e-3).graph_builder()
             .add_inputs("in").set_input_types(InputType.feed_forward(5))
             .add_layer("h", DenseLayer(n_out=7, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3, activation="identity",
                                           loss="mse"), "h"))
        return ComputationGraph(g.set_outputs("out").build()).init()

    def test_external_epsilons_match_autodiff(self):
        """backprop_external with eps = dL/d(out) must equal jax.grad of the
        same external loss composed through the graph (the
        calcBackpropGradients(externalEpsilons) contract)."""
        cg = self._cg()
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(6, 5), jnp.float32)
        tgt = jnp.asarray(rs.randn(6, 3), jnp.float32)

        out = cg.output(x)
        eps = 2.0 * (out - tgt)                 # d/d(out) of sum((out-t)^2)
        got, _ = cg.backprop_external([x], [eps])

        def external_loss(params):
            acts, _, _ = cg._forward(params, cg.state, [x], train=True,
                                     rng=None)
            reg = sum((cg.conf.nodes[n].layer.reg_loss(p)
                       for n, p in params.items()), 0.0)
            return jnp.sum((acts["out"] - tgt) ** 2) + reg

        want = jax.grad(external_loss)(cg.params)
        for name in want:
            for k in want[name]:
                np.testing.assert_allclose(
                    np.asarray(got[name][k]), np.asarray(want[name][k]),
                    rtol=1e-5, atol=1e-6, err_msg=f"{name}/{k}")

    def test_fit_external_updates_params(self):
        cg = self._cg()
        rs = np.random.RandomState(1)
        x = rs.randn(4, 5).astype(np.float32)
        eps = rs.randn(4, 3).astype(np.float32)
        before = np.asarray(cg.params["h"]["W"]).copy()
        cg.fit_external([x], [eps])
        assert not np.allclose(before, np.asarray(cg.params["h"]["W"]))
        assert cg.iteration == 1
