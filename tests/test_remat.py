"""Backward rematerialization (GlobalConf.remat): identical training math,
different schedule. Remat recomputes activations in the backward instead of
storing them — on TPU this is faster for HBM-bound conv models and is the
bench configuration for ResNet50 (docs/PERF_R05.md); these tests pin that
it changes NOTHING numerically."""

import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (ConvolutionLayer, DenseLayer,
                                          BatchNormalization, OutputLayer)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.updaters import Adam


def _conf(remat):
    b = (NeuralNetConfiguration.builder()
         .seed(7).updater(Adam(1e-2)).weight_init("xavier"))
    if remat:
        b = b.remat(remat)
    return (b.list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3, activation="relu"))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())


def _data(steps=3, b=4):
    rs = np.random.RandomState(0)
    xs = rs.rand(steps, b, 8, 8, 1).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (steps, b))]
    return jnp.asarray(xs), jnp.asarray(ys)


def test_remat_mln_identical_training():
    xs, ys = _data()
    nets = [MultiLayerNetwork(_conf(r)).init()
            for r in (False, True, "save_convs")]
    for net in nets:
        net.fit_scan(xs, ys)
    a = nets[0]
    for b in nets[1:]:
        assert np.allclose(float(a.get_score()), float(b.get_score()),
                           atol=1e-5)
        for pa, pb in zip(a.params, b.params):
            for k in pa:
                np.testing.assert_allclose(np.asarray(pa[k]),
                                           np.asarray(pb[k]), atol=1e-5)


def test_remat_rejects_unknown_mode():
    net = MultiLayerNetwork(_conf(False))
    net.conf.global_conf.remat = "bogus"      # bypasses the eager check
    with pytest.raises(ValueError, match="remat"):
        net.init().fit_scan(*_data(1))


def _small_residual_cg(remat):
    """2-block bottleneck residual CG — the ResNet shape (projection +
    identity shortcuts, ElementWiseVertex add) at a depth that compiles in
    seconds, so the CG remat modes stay pinned in tier-1 while the full
    ResNet50 parity run rides in the slow tier."""
    from deeplearning4j_tpu.models import ComputationGraph
    from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
    from deeplearning4j_tpu.nn.layers import ActivationLayer, GlobalPoolingLayer

    b = (NeuralNetConfiguration.builder()
         .seed(11).updater(Adam(1e-2)).weight_init("relu"))
    if remat:
        b = b.remat(remat)
    g = (b.graph_builder()
         .add_inputs("input")
         .set_input_types(InputType.convolutional(8, 8, 3)))

    def conv_bn(name, inp, n_out, k, stride=1, pad=0, act=True):
        g.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=n_out, kernel_size=k,
                                     stride=stride, padding=pad,
                                     has_bias=False), inp)
        g.add_layer(f"{name}_bn",
                    BatchNormalization(
                        activation="relu" if act else "identity"),
                    f"{name}_conv")
        return f"{name}_bn"

    def block(name, inp, f, project=False):
        x = conv_bn(f"{name}_a", inp, f, 1)
        x = conv_bn(f"{name}_b", x, f, 3, pad=1)
        x = conv_bn(f"{name}_c", x, 2 * f, 1, act=False)
        sc = conv_bn(f"{name}_sc", inp, 2 * f, 1, act=False) if project else inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    x = conv_bn("stem", "input", 8, 3, pad=1)
    x = block("res0", x, 8, project=True)
    x = block("res1", x, 8)
    g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
    g.add_layer("fc", OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent", n_in=16), "avgpool")
    g.set_outputs("fc")
    return ComputationGraph(g.build()).init()


@pytest.mark.slow
def test_remat_cg_small_identical_training():
    rs = np.random.RandomState(1)
    x = rs.rand(4, 8, 8, 3).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 4)]
    xs, ys = jnp.asarray(x[None]), jnp.asarray(y[None])
    cgs = [_small_residual_cg(r) for r in (False, True, "save_convs")]
    for cg in cgs:
        cg.fit_scan(xs, ys)
    scores = [float(c.get_score()) for c in cgs]
    assert np.isfinite(scores[0])
    for s in scores[1:]:
        assert abs(scores[0] - s) < 1e-5, scores


@pytest.mark.slow
def test_remat_cg_identical_training():
    from deeplearning4j_tpu.zoo.resnet import ResNet50Cifar
    rs = np.random.RandomState(1)
    x = rs.rand(4, 32, 32, 3).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 4)]
    xs, ys = jnp.asarray(x[None]), jnp.asarray(y[None])
    cgs = [ResNet50Cifar(num_classes=10, remat=r).init()
           for r in (False, True, "save_convs")]
    for cg in cgs:
        cg.fit_scan(xs, ys)
    scores = [float(c.get_score()) for c in cgs]
    assert np.isfinite(scores[0])
    for s in scores[1:]:
        assert abs(scores[0] - s) < 1e-4, scores


def test_remat_roundtrips_in_conf_json():
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    again = MultiLayerConfiguration.from_json(_conf(True).to_json())
    assert again.global_conf.remat is True
    assert MultiLayerConfiguration.from_json(
        _conf(False).to_json()).global_conf.remat is False
    assert MultiLayerConfiguration.from_json(
        _conf("save_convs").to_json()).global_conf.remat == "save_convs"


def test_remat_builder_rejects_bad_mode_eagerly():
    with pytest.raises(ValueError, match="remat"):
        NeuralNetConfiguration.builder().remat("save_conv")
