"""Worker process for the two-process multi-host test (not a pytest module).

Each process owns 2 virtual CPU devices; the 2-process cluster forms a
4-device global mesh. Validates deeplearning4j_tpu.parallel.distributed
initialize()/pod_mesh()/local_batch_slice() and that a psum actually sums
across process boundaries — the reference's Spark `local[N]`-style
distributed test, but over real process boundaries (SURVEY.md §4).

Prints two markers so the pytest side can assert formation/sharding
unconditionally and gate only the collective on backend support:

    WORKER_<pid>_FORMED global=<n> local=<n>     cluster + mesh + slice OK
    WORKER_<pid>_OK psum=<total|unsupported>     the collective itself

Usage: _dist_worker.py <coordinator_port> <process_id> <num_processes>
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")


def main():
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax
    jax.config.update("jax_platforms", "cpu")

    from deeplearning4j_tpu.parallel import distributed

    distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.process_index() == pid

    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    assert n_global == 2 * nproc, n_global
    assert n_local == 2, n_local

    mesh = distributed.pod_mesh(("data",))
    assert mesh.devices.size == n_global

    # batch sharding: every row owned exactly once, at the offset this
    # process's rank dictates (ragged worlds are covered by test_elastic)
    sl = distributed.local_batch_slice(8)
    assert sl == slice(pid * 4, (pid + 1) * 4), sl

    print(f"WORKER_{pid}_FORMED global={n_global} local={n_local}")

    # psum across the full pod: each device contributes (global_index + 1);
    # every process must see the same whole-cluster total.
    import jax.numpy as jnp  # noqa: F401
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from functools import partial

    vals = np.arange(1, n_global + 1, dtype=np.float32)
    sharding = NamedSharding(mesh, P("data"))
    garr = jax.make_array_from_callback(
        (n_global,), sharding, lambda idx: vals[idx])

    @partial(shard_map, mesh=mesh, in_specs=P("data"), out_specs=P())
    def total(x):
        return jax.lax.psum(x.sum(), "data")[None]

    try:
        got = float(jax.jit(total)(garr).addressable_shards[0].data[0])
    except Exception as e:  # noqa: BLE001 — inspect, then re-raise
        # some jaxlib CPU builds form the cluster but ship no cross-process
        # collective transport; cluster formation above IS validated, so
        # report the environmental gap instead of failing the worker
        if "aren't implemented on the CPU backend" in str(e):
            print(f"WORKER_{pid}_OK psum=unsupported")
            return
        raise
    want = float(vals.sum())
    assert got == want, (got, want)

    print(f"WORKER_{pid}_OK psum={got}")


if __name__ == "__main__":
    main()
