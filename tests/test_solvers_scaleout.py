"""Tests: second-order solvers, gradient compression, cluster SPI.

Parity patterns: reference deeplearning4j-core/src/test optimizer tests
(solvers on small real nets), EncodedGradientsAccumulator tests, and the
Spark `local[N]`-master tests (SURVEY.md §4) — here the 8-device virtual CPU
mesh plays the role of local executors.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.data.dataset import DataSet


def _toy_net(seed=12, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n=64, n_in=4, n_cls=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, n_in).astype(np.float32)
    y_idx = (x.sum(axis=1) > 0).astype(int) + (x[:, 0] > 1).astype(int)
    y = np.eye(n_cls, dtype=np.float32)[y_idx]
    return DataSet(x, y)


class TestSolvers:
    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_full_batch_solvers_reduce_loss(self, algo):
        from deeplearning4j_tpu.optimize.solvers import Solver
        net = _toy_net()
        ds = _toy_data()
        before = net.score(ds)
        Solver(net, algorithm=algo, max_iterations=30).optimize(ds)
        after = net.score(ds)
        assert after < before * 0.7, (algo, before, after)

    def test_lbfgs_converges_faster_than_steepest_descent(self):
        from deeplearning4j_tpu.optimize.solvers import (LBFGS,
                                                         LineGradientDescent)
        ds = _toy_data()
        n1, n2 = _toy_net(), _toy_net()
        LBFGS(max_iterations=25, tolerance=0).optimize(n1, ds)
        LineGradientDescent(max_iterations=25, tolerance=0).optimize(n2, ds)
        assert n1.score(ds) <= n2.score(ds) * 1.05

    def test_line_search_satisfies_armijo(self):
        from deeplearning4j_tpu.optimize.solvers import BackTrackLineSearch
        import jax
        vg = jax.jit(jax.value_and_grad(lambda v: jnp.sum((v - 2.0) ** 2)))
        x = jnp.zeros((5,))
        f0, g0 = vg(x)
        ls = BackTrackLineSearch()
        step, f_new, x_new, _ = ls.optimize(vg, x, float(f0), g0, -g0)
        assert step > 0 and f_new < float(f0)

    def test_unknown_algorithm_raises(self):
        from deeplearning4j_tpu.optimize.solvers import Solver
        with pytest.raises(ValueError, match="unknown algorithm"):
            Solver(_toy_net(), algorithm="newton")


class TestCompression:
    def test_encode_decode_roundtrip(self):
        from deeplearning4j_tpu.parallel.compression import (
            threshold_encode, threshold_decode)
        g = jnp.asarray([0.5, -0.002, 0.0001, -0.8, 0.01])
        idx, vals, count = threshold_encode(g, 0.01, 4)
        assert int(count) == 3          # 0.5, -0.8, 0.01
        dense = threshold_decode(idx, vals, 5)
        # transmitted values are sign * threshold
        np.testing.assert_allclose(np.asarray(dense),
                                   [0.01, 0.0, 0.0, -0.01, 0.01], atol=1e-7)

    def test_residual_carry_preserves_mass(self):
        from deeplearning4j_tpu.parallel.compression import EncodingHandler
        h = EncodingHandler(threshold=0.1, capacity_fraction=0.5)
        g = jnp.asarray([1.0, 0.05, 0.0, 0.0])
        idx, vals, _ = h.encode(g)
        # residual = grad - sent; 1.0 entry sent as 0.1 → residual 0.9
        res = np.asarray(h.residual)
        assert abs(res[0] - 0.9) < 1e-6
        # next encode sends the residual again
        idx2, vals2, c2 = h.encode(jnp.zeros(4))
        assert int(c2) >= 1

    def test_accumulator_all_workers_receive_all_updates(self):
        from deeplearning4j_tpu.parallel.compression import (
            EncodedGradientsAccumulator, threshold_decode)
        acc = EncodedGradientsAccumulator(2, 4, threshold=0.01,
                                          capacity_fraction=1.0)
        acc.store_update(0, jnp.asarray([1.0, 0.0, 0.0, 0.0]))
        acc.store_update(1, jnp.asarray([0.0, -1.0, 0.0, 0.0]))
        u0 = np.asarray(acc.apply_update(0))
        u1 = np.asarray(acc.apply_update(1))
        np.testing.assert_allclose(u0, u1)
        assert u0[0] > 0 and u0[1] < 0
        # queues drained
        assert np.allclose(np.asarray(acc.apply_update(0)), 0.0)


class TestClusterSPI:
    def _batches(self, n_batches=8, bs=8):
        ds = _toy_data(n=n_batches * bs)
        f, l = np.asarray(ds.features), np.asarray(ds.labels)
        return [DataSet(f[i * bs:(i + 1) * bs], l[i * bs:(i + 1) * bs])
                for i in range(n_batches)]

    def test_parameter_averaging_master(self):
        from deeplearning4j_tpu.scaleout import (
            ParameterAveragingTrainingMaster, ClusterMultiLayerNetwork)
        net = _toy_net()
        master = ParameterAveragingTrainingMaster(
            averaging_frequency=2, workers=4).set_collect_training_stats(True)
        cn = ClusterMultiLayerNetwork(net, master)
        batches = self._batches()
        before = net.score(DataSet(
            np.concatenate([b.features for b in batches]),
            np.concatenate([b.labels for b in batches])))
        cn.fit(batches, epochs=3)
        after = cn.score_examples(batches)
        assert np.mean(after) < before
        assert "fit" in master.get_training_stats().timings

    def test_shared_training_master_learns(self):
        from deeplearning4j_tpu.scaleout import (SharedTrainingMaster,
                                                 ClusterMultiLayerNetwork)
        net = _toy_net()
        master = SharedTrainingMaster(threshold=1e-3, workers=2,
                                      learning_rate=0.1)
        cn = ClusterMultiLayerNetwork(net, master)
        batches = self._batches()
        before = np.mean(cn.score_examples(batches))
        cn.fit(batches, epochs=5)
        after = np.mean(cn.score_examples(batches))
        assert after < before

    def test_shared_master_update_magnitude_independent_of_workers(self):
        """Each encoded update must land exactly once per replica — more
        workers must NOT multiply the effective learning rate."""
        from deeplearning4j_tpu.scaleout import (SharedTrainingMaster,
                                                 ClusterMultiLayerNetwork)
        import jax
        from jax.flatten_util import ravel_pytree
        batches = self._batches(n_batches=8)
        deltas = {}
        for workers in (1, 4):
            net = _toy_net()
            v0, _ = ravel_pytree(net.params)
            master = SharedTrainingMaster(threshold=1e-3, workers=workers,
                                          learning_rate=0.05)
            ClusterMultiLayerNetwork(net, master).fit(batches)
            v1, _ = ravel_pytree(net.params)
            deltas[workers] = float(jnp.linalg.norm(v1 - v0))
        ratio = deltas[4] / deltas[1]
        assert 0.5 < ratio < 2.0, deltas

    def test_repartition_preserves_masks(self):
        from deeplearning4j_tpu.scaleout import repartition
        x = np.random.RandomState(0).randn(10, 5, 3).astype(np.float32)
        y = np.zeros((10, 5, 2), np.float32)
        m = (np.arange(5)[None, :] < 3).astype(np.float32).repeat(10, 0)
        ds = DataSet(x, y, m, m)
        out = repartition([ds], 4, seed=2)
        assert all(b.features_mask is not None for b in out)
        assert sum(b.features.shape[0] for b in out) == 10

    def test_repartition(self):
        from deeplearning4j_tpu.scaleout import repartition
        batches = self._batches(n_batches=3, bs=10)   # 30 examples
        out = repartition(batches, 8, seed=1)
        sizes = [b.features.shape[0] for b in out]
        assert sizes == [8, 8, 8, 6]
        total_in = np.sort(np.concatenate(
            [np.asarray(b.features).ravel() for b in batches]))
        total_out = np.sort(np.concatenate(
            [np.asarray(b.features).ravel() for b in out]))
        np.testing.assert_allclose(total_in, total_out)


class TestCollectiveSharedMaster:
    """SharedTrainingMaster with a mesh: the Strom-2015 threshold exchange
    compiled as one shard_map program with psum'd sparse messages (the
    production path; the logical-replica loop is the semantics demo)."""

    def _batches(self, n_batches=8, bs=32):
        ds = _toy_data(n=n_batches * bs)
        f, l = np.asarray(ds.features), np.asarray(ds.labels)
        return [DataSet(f[i * bs:(i + 1) * bs], l[i * bs:(i + 1) * bs])
                for i in range(n_batches)]

    def test_collective_exchange_learns_on_mesh(self):
        from deeplearning4j_tpu.scaleout import (SharedTrainingMaster,
                                                 ClusterMultiLayerNetwork)
        from deeplearning4j_tpu.parallel.wrapper import default_mesh
        mesh = default_mesh()
        assert mesh.devices.size == 8
        net = _toy_net()
        master = SharedTrainingMaster(threshold=1e-3, learning_rate=0.1,
                                      batch_size_per_worker=4, mesh=mesh)
        cn = ClusterMultiLayerNetwork(net, master)
        batches = self._batches()
        before = np.mean(cn.score_examples(batches))
        cn.fit(batches, epochs=5)
        after = np.mean(cn.score_examples(batches))
        assert after < before
        assert net.iteration > 0

    def test_collective_threshold_adapts(self):
        from deeplearning4j_tpu.scaleout import (SharedTrainingMaster,
                                                 ClusterMultiLayerNetwork)
        from deeplearning4j_tpu.parallel.wrapper import default_mesh
        net = _toy_net()
        # huge threshold: nothing clears it, adapt must decay toward min
        master = SharedTrainingMaster(threshold=10.0, min_threshold=1e-5,
                                      threshold_step=0.5, learning_rate=0.05,
                                      batch_size_per_worker=4,
                                      mesh=default_mesh())
        ClusterMultiLayerNetwork(net, master).fit(self._batches())
        assert float(master.threshold) < 10.0
