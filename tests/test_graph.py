"""Graph API + DeepWalk tests (model: reference deeplearning4j-graph/src/test
— TestGraph.java, TestGraphHuffman.java, DeepWalkGradientCheck/TestDeepWalk)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import (Graph, NoEdgeHandling, NoEdgesException,
                                      RandomWalkIterator,
                                      WeightedRandomWalkIterator,
                                      RandomWalkGraphIteratorProvider,
                                      DeepWalk, GraphHuffman)
from deeplearning4j_tpu.graph.walks import generate_walks_batch


def _ring(n=10):
    g = Graph(n)
    for i in range(n):
        g.add_edge(i, (i + 1) % n)
    return g


def test_graph_structure():
    g = _ring(10)
    assert g.num_vertices() == 10
    assert g.get_vertex_degree(0) == 2
    assert sorted(g.neighbors(0)) == [1, 9]
    g2 = Graph(3)
    g2.add_edge(0, 1, directed=True)
    assert g2.neighbors(0) == [1] and g2.neighbors(1) == []


def test_random_walks_stay_on_edges():
    g = _ring(10)
    it = RandomWalkIterator(g, walk_length=8, seed=0)
    walks = list(it)
    assert len(walks) == 10
    for w in walks:
        assert len(w) == 9
        for a, b in zip(w, w[1:]):
            assert b in g.neighbors(a)
    # starts cover every vertex in order
    assert [w[0] for w in walks] == list(range(10))


def test_disconnected_vertex_handling():
    g = Graph(3)
    g.add_edge(0, 1)
    it = RandomWalkIterator(g, walk_length=3, seed=0,
                            mode=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                            first_vertex=2, last_vertex=3)
    assert next(it) == [2, 2, 2, 2]
    it2 = RandomWalkIterator(g, walk_length=3, seed=0,
                             mode=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED,
                             first_vertex=2, last_vertex=3)
    with pytest.raises(NoEdgesException):
        next(it2)


def test_weighted_walks_follow_weights():
    g = Graph(3)
    g.add_edge(0, 1, weight=1e6, directed=True)
    g.add_edge(0, 2, weight=1e-6, directed=True)
    g.add_edge(1, 0, weight=1.0, directed=True)
    g.add_edge(2, 0, weight=1.0, directed=True)
    it = WeightedRandomWalkIterator(g, walk_length=1, seed=1)
    firsts = [next(it)[1]]
    for _ in range(20):
        it.reset()
        firsts.append(next(it)[1])
    assert all(f == 1 for f in firsts)  # ~never picks the 1e-12-prob edge


def test_iterator_provider_partitions():
    g = _ring(10)
    its = RandomWalkGraphIteratorProvider(g, 4).get_graph_walk_iterators(3)
    starts = [w[0] for it in its for w in it]
    assert sorted(starts) == list(range(10))


def test_vectorized_walks_match_graph():
    g = _ring(12)
    rng = np.random.default_rng(0)
    walks = generate_walks_batch(g, np.arange(12), 6, rng)
    assert walks.shape == (12, 7)
    for w in walks:
        for a, b in zip(w, w[1:]):
            assert int(b) in g.neighbors(int(a))


def test_graph_huffman_codes():
    # model: reference TestGraphHuffman.java — 7 vertices with known degrees
    hs = GraphHuffman(7).build_tree([12, 3, 6, 1, 2, 7, 8])
    lens = [hs.get_code_length(v) for v in range(7)]
    # highest-degree vertex gets shortest code; codes are prefix-free
    assert lens[0] == min(lens)
    assert lens[3] == max(lens)
    codes = {(hs.get_code(v), hs.get_code_length(v)) for v in range(7)}
    assert len(codes) == 7
    for v in range(7):
        assert len(hs.get_path_inner_node(v)) == hs.get_code_length(v)
        assert all(0 <= p < 6 for p in hs.get_path_inner_node(v))


def test_deepwalk_learns_community_structure():
    # two dense cliques joined by one bridge edge: embeddings should place
    # same-clique vertices nearer than cross-clique ones.
    n = 12
    g = Graph(n)
    for grp in (range(0, 6), range(6, 12)):
        grp = list(grp)
        for i in grp:
            for j in grp:
                if i < j:
                    g.add_edge(i, j)
    g.add_edge(5, 6)
    dw = (DeepWalk.Builder().vector_size(16).window_size(3)
          .learning_rate(0.1).seed(7).build())
    dw.walks_per_vertex = 5
    dw.fit(g, walk_length=10, epochs=20)
    same = np.mean([dw.similarity(0, j) for j in range(1, 6)])
    cross = np.mean([dw.similarity(0, j) for j in range(6, 12)])
    assert same > cross


def test_deepwalk_save_load_roundtrip(tmp_path):
    g = _ring(8)
    dw = DeepWalk(vector_size=8, seed=3).initialize(g)
    dw.fit(g, walk_length=5)
    p = str(tmp_path / "dw")
    dw.save(p)
    dw2 = DeepWalk.load(p)
    np.testing.assert_allclose(dw2.get_vertex_vector(2),
                               dw.get_vertex_vector(2), rtol=1e-6)
    assert dw2.num_vertices() == 8
    # training continues after load (HS tables restored)
    dw2.fit(g, walk_length=5)
    dw2.fit_walks(np.array([[0, 1, 2, 3]], np.int32))


def test_batch_walks_exception_mode():
    g = Graph(3)
    g.add_edge(0, 1, directed=True)  # vertex 1,2 have no out-edges... 1 has none
    rng = np.random.default_rng(0)
    with pytest.raises(NoEdgesException):
        generate_walks_batch(g, np.array([0]), 3, rng,
                             mode=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)


def test_edge_list_loader(tmp_path):
    p = tmp_path / "edges.txt"
    p.write_text("# comment\n0,1\n1,2,5.0\n2,0\n")
    from deeplearning4j_tpu.graph.api import load_edge_list
    g = load_edge_list(str(p), 3, weighted=True)
    assert g.get_vertex_degree(0) == 2
    assert 5.0 in g.neighbor_weights(1)
