"""Data-parallel training tests on the 8-device virtual CPU mesh
(parity role: ParallelWrapperTest / Spark local[N] tests, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Sgd, Adam
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.parallel import ParallelWrapper, ParallelInference


def _net(seed=5, lr=0.05):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=160, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x.sum(axis=1) * 2).astype(int) % 3]
    return DataSet(x, y)


def test_sync_dp_matches_single_device():
    """Gradient-allreduce DP on 8 devices must equal single-device training on
    the same global batch (the reference's averaging-freq-1 semantics)."""
    ds = _data()
    single = _net()
    for batch in ds.batch_by(32):
        single.fit(batch)

    dp_net = _net()
    pw = ParallelWrapper(dp_net, workers=8, averaging_frequency=1)
    pw.fit(ListDataSetIterator(_data(), 32))

    w1 = np.asarray(single.params[0]["W"])
    w2 = np.asarray(dp_net.params[0]["W"])
    assert np.allclose(w1, w2, atol=1e-5), np.abs(w1 - w2).max()


def test_averaging_mode_trains():
    ds = _data()
    net = _net(lr=0.1)
    pw = ParallelWrapper(net, workers=8, averaging_frequency=4)
    s0 = net.score(ds)
    for _ in range(6):
        pw.fit(ListDataSetIterator(_data(), 64))
    assert net.score(ds) < s0


def test_parallel_inference_matches_model_output():
    net = _net()
    ds = _data(40)
    pi = ParallelInference(net)
    out = pi.output(ds.features)
    ref = np.asarray(net.output(ds.features))
    assert out.shape == ref.shape
    assert np.allclose(out, ref, atol=1e-5)


def test_parallel_inference_batching_async():
    net = _net()
    pi = ParallelInference(net, batch_timeout_ms=5.0).start()
    futs = [pi.submit(np.random.rand(3, 4).astype(np.float32))
            for _ in range(7)]
    outs = [f.result(timeout=30) for f in futs]
    pi.shutdown()
    assert all(o.shape == (3, 3) for o in outs)


def test_uneven_batch_padding():
    net = _net()
    pw = ParallelWrapper(net, workers=8)
    pw.fit(ListDataSetIterator(_data(n=30), 30))  # 30 % 8 != 0
    assert np.isfinite(net.get_score())


# ---------------------------------------------------------------------------
# model-agnostic ParallelWrapper (round 2): ComputationGraph data parallelism
# (parity: reference ParallelWrapper.java:58 takes any Model, not just MLN)
# ---------------------------------------------------------------------------

def _cg_net(seed=5, lr=0.05):
    from deeplearning4j_tpu.models import ComputationGraph
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(lr))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("h1", DenseLayer(n_out=16, activation="tanh"), "in")
            .add_layer("h2", DenseLayer(n_out=16, activation="tanh"), "in")
            .add_vertex("merge",
                        __import__("deeplearning4j_tpu.nn.conf.graph_conf",
                                   fromlist=["MergeVertex"]).MergeVertex(),
                        "h1", "h2")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "merge")
            .set_outputs("out")
            .build())
    return ComputationGraph(conf).init()


def test_sync_dp_cg_matches_single_device():
    """DP ComputationGraph on 8 devices == single-device CG training."""
    ds = _data()
    single = _cg_net()
    for batch in ds.batch_by(32):
        single.fit(batch)

    dp = _cg_net()
    pw = ParallelWrapper(dp, workers=8, averaging_frequency=1)
    pw.fit(ListDataSetIterator(_data(), 32))

    w1 = np.asarray(single.params["h1"]["W"])
    w2 = np.asarray(dp.params["h1"]["W"])
    assert np.allclose(w1, w2, atol=1e-5), np.abs(w1 - w2).max()


def test_cg_averaging_mode_trains():
    ds = _data()
    net = _cg_net(lr=0.1)
    pw = ParallelWrapper(net, workers=8, averaging_frequency=4)
    s0 = net.score(ds.to_multi())
    for _ in range(6):
        pw.fit(ListDataSetIterator(_data(), 64))
    assert net.score(ds.to_multi()) < s0


def test_uneven_batch_padding_gradient_exact():
    """Pad rows must carry ZERO loss weight: one DP step on a 30-row batch
    (padded to 32 over 8 devices) must produce exactly the params of a
    single-device step on the unpadded 30-row batch."""
    ds = _data(n=30)
    single = _net()
    single.fit(ds)

    dp = _net()
    pw = ParallelWrapper(dp, workers=8)
    pw.fit(ListDataSetIterator(_data(n=30), 30))

    for i in (0, 1):
        for k in single.params[i]:
            a = np.asarray(single.params[i][k])
            b = np.asarray(dp.params[i][k])
            assert np.allclose(a, b, atol=1e-6), \
                (i, k, np.abs(a - b).max())


@pytest.mark.slow
def test_resnet50_dp_smoke():
    """The north-star config: ResNet50 (a ComputationGraph) training
    data-parallel on the 8-device mesh (tiny input/batch). Slow tier: the
    50-layer fwd+bwd compile alone takes minutes on a 1-core CI box; the
    CG-through-ParallelWrapper mechanism stays pinned in tier-1 by
    test_sync_dp_cg_matches_single_device and the conv TP x DP tests."""
    from deeplearning4j_tpu.zoo.resnet import ResNet50
    net = ResNet50(num_classes=10, input_shape=(32, 32, 3)).init()
    rng = np.random.RandomState(0)
    x = rng.rand(16, 32, 32, 3).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]
    pw = ParallelWrapper(net, workers=8)
    pw.fit(ListDataSetIterator(DataSet(x, y), 16))
    assert np.isfinite(net.get_score())


def _tp_net():
    conf = (NeuralNetConfiguration.builder().seed(9).updater(Sgd(0.1))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _tp_data(n=32):
    rs = np.random.RandomState(3)
    x = rs.randn(n, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    return x, y


class TestTensorParallel:
    """TP x DP hybrid (2-D mesh) — a TPU-idiomatic extension beyond the
    reference's DP-only capability bar (SURVEY §2 parallelism inventory)."""

    def _net(self):
        return _tp_net()

    def _data(self, n=32):
        return _tp_data(n)

    def test_tp_dp_matches_single_device(self):
        import jax
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        x, y = self._data()
        ref = self._net()
        for i in range(0, 32, 16):
            ref.fit(DataSet(x[i:i + 16], y[i:i + 16]))

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("data", "model"))
        net = self._net()
        pw = ParallelWrapper(net, mesh=mesh)
        assert pw.model_axis == "model" and pw.n_devices == 4
        pw.fit(ListDataSetIterator(DataSet(x, y), 16))

        for p_tp, p_ref in zip(net.params, ref.params):
            for k in p_ref:
                np.testing.assert_allclose(
                    np.asarray(p_tp[k]), np.asarray(p_ref[k]),
                    rtol=1e-4, atol=1e-5, err_msg=k)

    def test_tp_param_placement(self):
        import jax
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "model"))
        net = self._net()
        pw = ParallelWrapper(net, mesh=mesh)
        x, y = self._data(16)
        pw.fit(ListDataSetIterator(DataSet(x, y), 16))
        # the 32-wide hidden kernel must actually be sharded over 'model'
        w0 = net.params[0]["W"]
        assert len(w0.sharding.device_set) == 8
        spec = w0.sharding.spec
        assert spec[-1] == "model", spec

    def test_tp_rejects_averaging(self):
        """Validated at construction, before any model state is touched."""
        import jax, pytest
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        with pytest.raises(ValueError):
            ParallelWrapper(self._net(), mesh=mesh, averaging_frequency=4)


# ---------------------------------------------------------------- fit_scan DP

def test_fit_scan_sync_matches_per_step_fit():
    """Device-resident multi-step DP (one compiled call for all steps) must
    produce bit-for-bit the same params as the per-step sync DP path — and
    therefore the same as single-device training (covered transitively by
    test_sync_dp_matches_single_device)."""
    ds = _data()
    batches = list(ds.batch_by(32))
    xs = np.stack([np.asarray(b.features) for b in batches])
    ys = np.stack([np.asarray(b.labels) for b in batches])

    step_net = _net()
    pw_step = ParallelWrapper(step_net, workers=8, averaging_frequency=1)
    pw_step.fit(ListDataSetIterator(_data(), 32))

    scan_net = _net()
    pw_scan = ParallelWrapper(scan_net, workers=8, averaging_frequency=1)
    pw_scan.fit_scan(xs, ys)

    assert scan_net.iteration == step_net.iteration
    for p1, p2 in zip(step_net.params, scan_net.params):
        for k in p1:
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-6, atol=1e-6, err_msg=k)


def test_fit_scan_averaging_matches_per_chunk():
    """averaging_frequency>1 through fit_scan must equal the per-chunk
    averaging path (divergent local steps + pmean every k steps)."""
    ds = _data(256)
    batches = list(ds.batch_by(32))          # 256/32 = 8 steps, k=4 → 2 rounds
    xs = np.stack([np.asarray(b.features) for b in batches])
    ys = np.stack([np.asarray(b.labels) for b in batches])

    step_net = _net(lr=0.1)
    pw_step = ParallelWrapper(step_net, workers=8, averaging_frequency=4)
    pw_step.fit(ListDataSetIterator(_data(256), 32))

    scan_net = _net(lr=0.1)
    pw_scan = ParallelWrapper(scan_net, workers=8, averaging_frequency=4)
    pw_scan.fit_scan(xs, ys)

    for p1, p2 in zip(step_net.params, scan_net.params):
        for k in p1:
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)


def test_fit_scan_validates_shapes():
    net = _net()
    pw = ParallelWrapper(net, workers=8, averaging_frequency=1)
    x = np.zeros((4, 30, 4), np.float32)     # 30 % 8 != 0
    y = np.zeros((4, 30, 3), np.float32)
    with pytest.raises(ValueError):
        pw.fit_scan(x, y)
    pw4 = ParallelWrapper(_net(), workers=8, averaging_frequency=4)
    x = np.zeros((6, 32, 4), np.float32)     # 6 % 4 != 0
    y = np.zeros((6, 32, 3), np.float32)
    with pytest.raises(ValueError):
        pw4.fit_scan(x, y)


def test_fit_scan_tp_dp_matches_single_device():
    """fit_scan over a 2-D (data, model) mesh — TP params + sharded batch —
    must match single-device training step for step."""
    import jax
    from jax.sharding import Mesh

    x, y = _tp_data()
    ref = _tp_net()
    for i in range(0, 32, 16):
        ref.fit(DataSet(x[i:i + 16], y[i:i + 16]))

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("data", "model"))
    net = _tp_net()
    pw = ParallelWrapper(net, mesh=mesh)
    pw.fit_scan(x.reshape(2, 16, 8), y.reshape(2, 16, 4))

    for p_tp, p_ref in zip(net.params, ref.params):
        for k in p_ref:
            np.testing.assert_allclose(
                np.asarray(p_tp[k]), np.asarray(p_ref[k]),
                rtol=1e-4, atol=1e-5, err_msg=k)


class TestTensorParallelRealModels:
    """TP x DP exactness on realistic models: TinyTransformer (attention
    heads / FFN sharded Megatron-style) and a conv net (output channels
    sharded). GSPMD guarantees semantics regardless of annotation; these
    tests pin that guarantee to 1e-5-level parity against single-device
    training."""

    def _tt(self):
        from deeplearning4j_tpu.zoo.simple import TinyTransformer
        # SGD, not Adam: the K-projection bias is softmax-invariant (its
        # exact gradient is 0), and Adam's 1/sqrt(v) normalization blows
        # pure fp reduction noise on it up to update-sized diffs
        return TinyTransformer(vocab_size=16, n_layers=2, d_model=32,
                               n_heads=4, seed=5, updater=Sgd(0.05)).init()

    @staticmethod
    def _tt_data(n=16, T=12, vocab=16):
        rs = np.random.RandomState(4)
        ids = rs.randint(0, vocab, size=(n, T))
        eye = np.eye(vocab, dtype=np.float32)
        return eye[ids], eye[np.roll(ids, -1, axis=1)]

    def test_tinytransformer_tp_dp_matches_single_device(self):
        import jax
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        x, y = self._tt_data()
        ref = self._tt()
        for i in range(0, 16, 8):
            ref.fit(DataSet(x[i:i + 8], y[i:i + 8]))

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        net = self._tt()
        pw = ParallelWrapper(net, mesh=mesh)
        pw.fit(ListDataSetIterator(DataSet(x, y), 8))

        for name in ref.params:
            for k in ref.params[name]:
                np.testing.assert_allclose(
                    np.asarray(net.params[name][k]),
                    np.asarray(ref.params[name][k]),
                    rtol=1e-4, atol=1e-5, err_msg=f"{name}/{k}")

    def test_tinytransformer_tp_placement(self):
        """Q/K/V kernels shard the head (output) dim; Wo and ff2 shard the
        input dim (row-parallel); LN vectors stay replicated."""
        import jax
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        net = self._tt()
        pw = ParallelWrapper(net, mesh=mesh)
        x, y = self._tt_data()
        pw.fit(ListDataSetIterator(DataSet(x, y), 8))
        p = net.params
        assert p["b0_attn"]["Wq"].sharding.spec[-1] == "model"
        assert p["b0_attn"]["Wo"].sharding.spec[0] == "model"
        assert p["b0_ff2"]["W"].sharding.spec[0] == "model"
        # the placement RULE replicates 1-D vectors (GSPMD may still choose
        # its own layout for outputs after the step — that is its call)
        spec = pw._param_sharding(np.zeros(32), "b0_ln1/gamma").spec
        assert all(s is None for s in spec), spec

    def test_conv_tp_dp_matches_single_device(self):
        import jax
        from jax.sharding import Mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        from __graft_entry__ import _lenet_conf

        rs = np.random.RandomState(1)
        x = rs.rand(16, 16, 16, 1).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 16)]

        ref = MultiLayerNetwork(_lenet_conf(height=16, width=16)).init()
        for i in range(0, 16, 8):
            ref.fit(DataSet(x[i:i + 8], y[i:i + 8]))

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("data", "model"))
        net = MultiLayerNetwork(_lenet_conf(height=16, width=16)).init()
        pw = ParallelWrapper(net, mesh=mesh)
        pw.fit(ListDataSetIterator(DataSet(x, y), 8))

        # conv reductions reorder under sharding; tolerance stays at
        # fp-noise level (worst observed: 1 element at 2.5e-5)
        for p_tp, p_ref in zip(net.params, ref.params):
            for k in p_ref:
                np.testing.assert_allclose(
                    np.asarray(p_tp[k]), np.asarray(p_ref[k]),
                    rtol=2e-4, atol=1e-4, err_msg=k)
