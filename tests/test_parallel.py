"""Data-parallel training tests on the 8-device virtual CPU mesh
(parity role: ParallelWrapperTest / Spark local[N] tests, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Sgd, Adam
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.parallel import ParallelWrapper, ParallelInference


def _net(seed=5, lr=0.05):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(lr))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=160, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[(x.sum(axis=1) * 2).astype(int) % 3]
    return DataSet(x, y)


def test_sync_dp_matches_single_device():
    """Gradient-allreduce DP on 8 devices must equal single-device training on
    the same global batch (the reference's averaging-freq-1 semantics)."""
    ds = _data()
    single = _net()
    for batch in ds.batch_by(32):
        single.fit(batch)

    dp_net = _net()
    pw = ParallelWrapper(dp_net, workers=8, averaging_frequency=1)
    pw.fit(ListDataSetIterator(_data(), 32))

    w1 = np.asarray(single.params[0]["W"])
    w2 = np.asarray(dp_net.params[0]["W"])
    assert np.allclose(w1, w2, atol=1e-5), np.abs(w1 - w2).max()


def test_averaging_mode_trains():
    ds = _data()
    net = _net(lr=0.1)
    pw = ParallelWrapper(net, workers=8, averaging_frequency=4)
    s0 = net.score(ds)
    for _ in range(6):
        pw.fit(ListDataSetIterator(_data(), 64))
    assert net.score(ds) < s0


def test_parallel_inference_matches_model_output():
    net = _net()
    ds = _data(40)
    pi = ParallelInference(net)
    out = pi.output(ds.features)
    ref = np.asarray(net.output(ds.features))
    assert out.shape == ref.shape
    assert np.allclose(out, ref, atol=1e-5)


def test_parallel_inference_batching_async():
    net = _net()
    pi = ParallelInference(net, batch_timeout_ms=5.0).start()
    futs = [pi.submit(np.random.rand(3, 4).astype(np.float32))
            for _ in range(7)]
    outs = [f.result(timeout=30) for f in futs]
    pi.shutdown()
    assert all(o.shape == (3, 3) for o in outs)


def test_uneven_batch_padding():
    net = _net()
    pw = ParallelWrapper(net, workers=8)
    pw.fit(ListDataSetIterator(_data(n=30), 30))  # 30 % 8 != 0
    assert np.isfinite(net.get_score())
