"""Flash decode-step kernel: parity vs the dense reference step.

Claims pinned here (ops/flash_decode.py, docs/DECODING.md):
- the Pallas q-length-1 online-softmax kernel matches a dense masked
  softmax-attention reference within pinned tolerances at f32 AND for
  bf16 inputs (the kernel accumulates in f32 either way);
- the routing seam in MultiHeadAttention.decode_step picks the kernel
  only when helpers are on, the shape is supported and the route says
  pallas — with helpers off (the CPU default) the dense step is
  byte-identical to before, keeping the bitwise decode-parity suite
  meaningful;
- ``decode_attn_route`` honors pin > env > backend ordering;
- transformer generation through DecodeEngine produces the same greedy
  tokens on the flash path as on the dense path.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import ops
from deeplearning4j_tpu.exec import decode_attn_route, set_route
from deeplearning4j_tpu.nn.layers.attention import MultiHeadAttention
from deeplearning4j_tpu.ops.flash_decode import (_pick_block,
                                                 flash_decode_step,
                                                 supported)


def _dense_ref(q, kc, vc, pos):
    B, H, Dh = q.shape
    C = kc.shape[1]
    s = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                   kc.astype(jnp.float32)) / np.sqrt(Dh)
    valid = jnp.arange(C)[None, None, :] <= pos[:, None, None]
    s = jnp.where(valid, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, vc.astype(jnp.float32))


def _rand(shape, seed, dtype=np.float32):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), dtype)


@pytest.fixture
def interpret_helpers():
    ops.set_helpers_enabled(True, interpret=True)
    yield
    ops.set_helpers_enabled(None)


class TestKernel:

    def test_supported_screen(self):
        assert supported(64, 16)
        assert supported(128, 8)
        assert not supported(65, 16)      # capacity not blockable
        assert not supported(64, 12)      # head dim not lane-aligned
        assert _pick_block(96) == 32

    @pytest.mark.parametrize("B,H,Dh,C", [(2, 2, 8, 16), (3, 4, 16, 64),
                                          (1, 2, 32, 128), (4, 1, 8, 96)])
    def test_parity_f32(self, B, H, Dh, C):
        q = _rand((B, H, Dh), 0)
        kc = _rand((B, C, H, Dh), 1)
        vc = _rand((B, C, H, Dh), 2)
        pos = jnp.asarray(
            np.random.default_rng(3).integers(0, C, B), jnp.int32)
        out = flash_decode_step(q, kc, vc, pos, interpret=True)
        ref = _dense_ref(q, kc, vc, pos)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-6

    def test_parity_bf16_inputs(self):
        # bf16 tensors widen to f32 at the kernel boundary; the pinned
        # tolerance is the bf16 input rounding, not kernel error
        B, H, Dh, C = 2, 2, 16, 64
        q = _rand((B, H, Dh), 4, jnp.bfloat16)
        kc = _rand((B, C, H, Dh), 5, jnp.bfloat16)
        vc = _rand((B, C, H, Dh), 6, jnp.bfloat16)
        pos = jnp.asarray([10, 63], jnp.int32)
        out = flash_decode_step(q, kc, vc, pos, interpret=True)
        ref = _dense_ref(q, kc, vc, pos)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-2

    def test_position_zero_and_full_cache(self):
        # pos 0 attends to exactly one key; pos C-1 to the whole cache
        B, H, Dh, C = 2, 1, 8, 32
        q, kc, vc = (_rand((B, H, Dh), 7), _rand((B, C, H, Dh), 8),
                     _rand((B, C, H, Dh), 9))
        pos = jnp.asarray([0, C - 1], jnp.int32)
        out = flash_decode_step(q, kc, vc, pos, interpret=True)
        ref = _dense_ref(q, kc, vc, pos)
        assert float(jnp.max(jnp.abs(out - ref))) < 2e-6
        np.testing.assert_allclose(np.asarray(out[0, 0]),
                                   np.asarray(vc[0, 0, 0]), atol=1e-6)

    def test_unblockable_capacity_raises(self):
        with pytest.raises(ValueError):
            flash_decode_step(_rand((1, 1, 8), 0), _rand((1, 17, 1, 8), 1),
                              _rand((1, 17, 1, 8), 2),
                              jnp.zeros((1,), jnp.int32), interpret=True)


class TestRouting:

    def test_route_orders_pin_env_backend(self, monkeypatch):
        monkeypatch.delenv("DL4JTPU_DECODE_ATTN_ROUTE", raising=False)
        assert decode_attn_route(64, 16) == "pallas"
        assert decode_attn_route(64, 16, backend="cpu") == "scan"
        assert decode_attn_route(64, 16, backend="tpu") == "pallas"
        monkeypatch.setenv("DL4JTPU_DECODE_ATTN_ROUTE", "scan")
        assert decode_attn_route(64, 16, backend="tpu") == "scan"
        set_route("decode_attn", "pallas")
        try:
            assert decode_attn_route(64, 16, backend="cpu") == "pallas"
        finally:
            set_route("decode_attn", None)


class TestAttentionSeam:

    def _layer_and_state(self, C=64, d=32, heads=4, B=3):
        layer = MultiHeadAttention(n_in=d, n_out=d, n_heads=heads,
                                   causal=True)
        p = layer.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        ds = {"k": jnp.asarray(rng.standard_normal((B, C, heads, d // heads)),
                               jnp.float32),
              "v": jnp.asarray(rng.standard_normal((B, C, heads, d // heads)),
                               jnp.float32)}
        x = jnp.asarray(rng.standard_normal((B, 1, d)), jnp.float32)
        pos = jnp.asarray([5, 40, 63], jnp.int32)
        return layer, p, ds, x, pos

    def test_decode_step_flash_matches_dense(self, interpret_helpers):
        layer, p, ds, x, pos = self._layer_and_state()
        ops.set_helpers_enabled(False)
        o_dense, ds1 = layer.decode_step(p, ds, x, pos)
        ops.set_helpers_enabled(True, interpret=True)
        o_flash, ds2 = layer.decode_step(p, ds, x, pos)
        assert float(jnp.max(jnp.abs(o_dense - o_flash))) < 1e-5
        # the KV-cache update is identical either way
        assert jnp.array_equal(ds1["k"], ds2["k"])
        assert jnp.array_equal(ds1["v"], ds2["v"])

    def test_scan_pin_falls_back_to_dense(self, interpret_helpers):
        layer, p, ds, x, pos = self._layer_and_state()
        set_route("decode_attn", "scan")
        try:
            o_pin, _ = layer.decode_step(p, ds, x, pos)
        finally:
            set_route("decode_attn", None)
        ops.set_helpers_enabled(False)
        o_dense, _ = layer.decode_step(p, ds, x, pos)
        assert jnp.array_equal(o_pin, o_dense)

    def test_flash_vs_teacher_forced_tolerance(self, interpret_helpers):
        """Stepping a sequence through decode_step on the FLASH path tracks
        the teacher-forced full forward within a pinned tolerance at every
        position (the dense path's bitwise guarantee relaxes to 1e-5 —
        flash reorders the softmax accumulation)."""
        C, d, heads, B = 32, 32, 4, 2
        layer = MultiHeadAttention(n_in=d, n_out=d, n_heads=heads,
                                   causal=True)
        p = layer.init(jax.random.PRNGKey(3))
        xs = jnp.asarray(
            np.random.default_rng(7).standard_normal((B, C, d)), jnp.float32)
        ops.set_helpers_enabled(False)   # teacher forcing on the dense path
        full, _ = layer.apply(p, xs)
        ops.set_helpers_enabled(True, interpret=True)
        ds = layer.init_decode_state(p, B, C)
        worst = 0.0
        for t in range(C):
            o, ds = layer.decode_step(p, ds, xs[:, t:t + 1], t)
            worst = max(worst, float(jnp.max(jnp.abs(o[:, 0] - full[:, t]))))
        assert worst < 1e-5, worst

    def test_unsupported_shape_falls_back(self, interpret_helpers):
        # head dim 6 is not lane-aligned → dense path even with helpers on
        layer, p, ds, x, pos = self._layer_and_state(C=64, d=24, heads=4)
        o, _ = layer.decode_step(p, ds, x, pos)
        ops.set_helpers_enabled(False)
        o_dense, _ = layer.decode_step(p, ds, x, pos)
        assert jnp.array_equal(o, o_dense)


@pytest.mark.slow
class TestEngineFlashParity:

    def test_transformer_greedy_tokens_match_dense(self, interpret_helpers):
        """DecodeEngine over a transformer stack: greedy generation on the
        flash decode path equals the dense path token-for-token (argmax is
        robust to the kernel's sub-1e-5 numeric delta on this model)."""
        from deeplearning4j_tpu import (NeuralNetConfiguration,
                                        MultiLayerNetwork)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.layers import (PositionalEmbedding,
                                                  RnnOutputLayer)
        from deeplearning4j_tpu.nn.updaters import Adam
        from deeplearning4j_tpu.serving.decode import DecodeEngine
        V = 16
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(PositionalEmbedding(max_len=32))
                .layer(MultiHeadAttention(n_out=V, n_heads=2, causal=True))
                .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(V))
                .build())
        net = MultiLayerNetwork(conf).init()

        def run():
            eng = DecodeEngine(net, slots=2, max_len=32).start()
            try:
                return eng.generate([3, 1, 4], max_new_tokens=8)["tokens"]
            finally:
                eng.stop()

        flash_toks = run()
        ops.set_helpers_enabled(False)
        dense_toks = run()
        assert flash_toks == dense_toks
