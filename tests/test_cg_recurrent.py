"""ComputationGraph recurrent parity: rnn_time_step + truncated BPTT.

Reference: ComputationGraph.java:2362 (rnnTimeStep with stateMap) and
:1617-1629 (doTruncatedBPTT). Oracles: full-sequence output() for streaming
equivalence, the standard train step for single-chunk tBPTT, and the
MultiLayerNetwork tBPTT path (already gradient-checked) for the chunked case.
"""

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.models import ComputationGraph
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import LSTM
from deeplearning4j_tpu.nn.layers.rnn import RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet


F, H, C = 5, 8, 4


def _cg(backprop_type="standard", tbptt=100):
    b = (NeuralNetConfiguration.builder()
         .seed(11)
         .updater(Sgd(0.1))
         .weight_init("xavier")
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(F))
         .add_layer("lstm", LSTM(n_out=H, activation="tanh"), "in")
         .add_layer("out", RnnOutputLayer(n_out=C, activation="softmax",
                                          loss="mcxent"), "lstm"))
    if backprop_type == "tbptt":
        b.backprop_type("tbptt", tbptt, tbptt)
    return ComputationGraph(b.set_outputs("out").build()).init()


def _mln(backprop_type="standard", tbptt=100):
    b = (NeuralNetConfiguration.builder()
         .seed(11)
         .updater(Sgd(0.1))
         .weight_init("xavier")
         .list()
         .layer(LSTM(n_out=H, activation="tanh"))
         .layer(RnnOutputLayer(n_out=C, activation="softmax", loss="mcxent")))
    if backprop_type == "tbptt":
        b.backprop_type("tbptt", tbptt, tbptt)
    return MultiLayerNetwork(
        b.set_input_type(InputType.recurrent(F)).build()).init()


def _seq(b=3, t=12, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(b, t, F).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rs.randint(0, C, (b, t))]
    return x, y


class TestCGRnnTimeStep:
    def test_streaming_matches_full_sequence(self):
        cg = _cg()
        x, _ = _seq()
        full = np.asarray(cg.output(x))
        cg.rnn_clear_previous_state()
        outs = [np.asarray(cg.rnn_time_step(x[:, t])) for t in range(x.shape[1])]
        stream = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(stream, full, rtol=1e-5, atol=1e-6)

    def test_state_persists_and_clears(self):
        cg = _cg()
        x, _ = _seq(t=2)
        first = np.asarray(cg.rnn_time_step(x[:, 0]))
        second = np.asarray(cg.rnn_time_step(x[:, 0]))   # same input, new state
        assert not np.allclose(first, second)
        cg.rnn_clear_previous_state()
        again = np.asarray(cg.rnn_time_step(x[:, 0]))
        np.testing.assert_allclose(again, first, rtol=1e-6)

    def test_matches_mln_stream(self):
        cg, mln = _cg(), _mln()
        x, _ = _seq(seed=4)
        a = np.asarray(cg.rnn_time_step(x[:, :6]))
        b = np.asarray(mln.rnn_time_step(x[:, :6]))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        a2 = np.asarray(cg.rnn_time_step(x[:, 6:]))
        b2 = np.asarray(mln.rnn_time_step(x[:, 6:]))
        np.testing.assert_allclose(a2, b2, rtol=1e-5, atol=1e-6)


class TestCGTbptt:
    def test_single_chunk_equals_standard_step(self):
        """tbptt with L >= T must reproduce the standard full-BPTT update."""
        x, y = _seq()
        std = _cg("standard")
        std.fit(x, y)
        tb = _cg("tbptt", tbptt=100)
        tb.fit(x, y)
        for name in std.params:
            for k in std.params[name]:
                np.testing.assert_allclose(
                    np.asarray(tb.params[name][k]),
                    np.asarray(std.params[name][k]),
                    rtol=1e-5, atol=1e-6, err_msg=f"{name}/{k}")

    def test_chunked_matches_mln_tbptt(self):
        """CG tBPTT must produce the same chunked updates as the (gradient-
        checked) MLN tBPTT on an identical stack."""
        x, y = _seq(b=2, t=12, seed=9)
        cg = _cg("tbptt", tbptt=4)
        mln = _mln("tbptt", tbptt=4)
        cg.fit(x, y)
        mln.fit(DataSet(x, y))
        cg_p = [cg.params["lstm"], cg.params["out"]]
        for got, want in zip(cg_p, mln.params):
            for k in want:
                np.testing.assert_allclose(
                    np.asarray(got[k]), np.asarray(want[k]),
                    rtol=1e-4, atol=1e-5, err_msg=k)
        np.testing.assert_allclose(cg.get_score(), mln.get_score(),
                                   rtol=1e-4)

    def test_chunked_differs_from_full_bptt(self):
        """Truncation must actually truncate (different update than full
        backprop through all T steps)."""
        x, y = _seq(b=2, t=12, seed=2)
        tb = _cg("tbptt", tbptt=4)
        tb.fit(x, y)
        std = _cg("standard")
        std.fit(x, y)
        diffs = [float(np.max(np.abs(np.asarray(tb.params[n][k])
                                     - np.asarray(std.params[n][k]))))
                 for n in std.params for k in std.params[n]]
        assert max(diffs) > 1e-6


class TestTextGeneration:
    def test_zoo_textgenlstm_generates_via_rnn_time_step(self):
        """TextGenerationLSTM streams characters through rnn_time_step
        (the reference zoo model's sampling loop)."""
        from deeplearning4j_tpu.zoo.simple import TextGenerationLSTM
        vocab = 11
        net = TextGenerationLSTM(total_unique_characters=vocab).init()
        rs = np.random.RandomState(0)
        ch = rs.randint(0, vocab)
        generated = []
        for _ in range(8):
            x = np.zeros((1, vocab), np.float32)
            x[0, ch] = 1.0
            probs = np.asarray(net.rnn_time_step(x))[0, -1]
            assert probs.shape == (vocab,)
            assert np.all(np.isfinite(probs))
            np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-4)
            ch = int(np.argmax(probs))
            generated.append(ch)
        assert len(generated) == 8


# ------------------------------------------------- seq2seq graph vertices

def test_seq2seq_encoder_decoder_gradients():
    """The CG seq2seq pattern the reference's graph-rnn vertices exist for:
    GravesLSTM encoder -> LastTimeStepVertex -> DuplicateToTimeSeriesVertex
    -> GravesLSTM decoder -> RnnOutputLayer (parity:
    nn/conf/graph/rnn/LastTimeStepVertex.java,
    rnn/DuplicateToTimeSeriesVertex.java)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        LastTimeStepVertex, DuplicateToTimeSeriesVertex)
    from deeplearning4j_tpu.nn.layers.rnn import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn

    B, T, F, C = 3, 5, 4, 3
    g = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
         .weight_init("xavier").graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(F, T))
         .add_layer("enc", GravesLSTM(n_out=6, activation="tanh"), "in")
         .add_vertex("last", LastTimeStepVertex(mask_input="in"), "enc")
         .add_vertex("dup", DuplicateToTimeSeriesVertex(ref_input="in"),
                     "last")
         .add_layer("dec", GravesLSTM(n_out=6, activation="tanh"), "dup")
         .add_layer("out", RnnOutputLayer(n_out=C, activation="softmax",
                                          loss="mcxent"), "dec")
         .set_outputs("out").build())
    cg = ComputationGraph(g).init()

    rs = np.random.RandomState(2)
    x = rs.randn(B, T, F).astype(np.float32)
    y = np.eye(C, dtype=np.float32)[rs.randint(0, C, (B, T))]

    def loss_fn(params):
        loss, _ = cg._loss(params, cg.state, [jnp.asarray(x)],
                           [jnp.asarray(y)], None)
        return loss

    fails, checked, worst = gradient_check_fn(loss_fn, cg.params,
                                              max_checks_per_array=10)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"
    assert checked > 0

    # forward shape sanity + serde round-trip
    out = cg.output(x)
    assert out.shape == (B, T, C)
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        ComputationGraphConfiguration)
    conf2 = ComputationGraphConfiguration.from_json(g.to_json())
    cg2 = ComputationGraph(conf2).init()
    assert cg2.output(x).shape == (B, T, C)


def test_last_time_step_vertex_masked():
    """With a features mask, LastTimeStepVertex must pick each example's
    true last step, matching a manual gather."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph_conf import LastTimeStepVertex

    v = LastTimeStepVertex(mask_input="in")
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 6, 3).astype(np.float32))
    lengths = np.array([6, 2, 4, 1])
    mask = jnp.asarray((np.arange(6)[None, :] < lengths[:, None])
                       .astype(np.float32))
    got = np.asarray(v.apply([x], mask=mask))
    want = np.stack([np.asarray(x)[i, l - 1] for i, l in enumerate(lengths)])
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # no mask -> plain last step
    np.testing.assert_allclose(np.asarray(v.apply([x])), np.asarray(x)[:, -1],
                               rtol=1e-6)


def test_l2_and_preprocessor_vertices():
    """L2Vertex distance + PreprocessorVertex round-trips
    (parity: nn/conf/graph/L2Vertex.java, PreprocessorVertex.java)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph_conf import (
        L2Vertex, PreprocessorVertex)

    rs = np.random.RandomState(1)
    a = jnp.asarray(rs.randn(5, 7).astype(np.float32))
    b = jnp.asarray(rs.randn(5, 7).astype(np.float32))
    d = np.asarray(L2Vertex().apply([a, b]))
    want = np.linalg.norm(np.asarray(a) - np.asarray(b), axis=1)[:, None]
    np.testing.assert_allclose(d, want, rtol=1e-5)

    img = jnp.asarray(rs.randn(2, 4, 3, 5).astype(np.float32))
    flat = PreprocessorVertex(preprocessor="cnn_to_ff").apply([img])
    assert flat.shape == (2, 60)
    back = PreprocessorVertex(preprocessor="ff_to_cnn", height=4, width=3,
                              channels=5).apply([flat])
    np.testing.assert_allclose(np.asarray(back), np.asarray(img))

    seq = jnp.asarray(rs.randn(3, 4, 6).astype(np.float32))
    ff = PreprocessorVertex(preprocessor="rnn_to_ff").apply([seq])
    assert ff.shape == (12, 6)
    seq2 = PreprocessorVertex(preprocessor="ff_to_rnn", tsteps=4).apply([ff])
    np.testing.assert_allclose(np.asarray(seq2), np.asarray(seq))
