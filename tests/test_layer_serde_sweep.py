"""Config-serde sweep over EVERY registered layer type.

Parity role: the reference pins its Jackson round-trip for every layer
config through the regressiontest + serde suites; here each of the 40+
registered layer classes must survive to_dict → JSON → layer_from_dict with
all dataclass fields intact — a serde gap in any one layer would silently
break checkpoint restore for nets containing it.
"""

import dataclasses
import json

import pytest

from deeplearning4j_tpu.nn.layers.base import LAYER_REGISTRY, layer_from_dict

# representative constructor args for layers whose defaults are not
# self-sufficient (dims that must be set, wrapped inner layers, ...)
SPECIAL = {
    "Bidirectional": lambda cls: cls(
        fwd=LAYER_REGISTRY["LSTM"](n_in=4, n_out=3)),
    "GravesBidirectionalLSTM": lambda cls: cls(n_in=4, n_out=3),
    "LastTimeStep": lambda cls: cls(
        inner=LAYER_REGISTRY["LSTM"](n_in=4, n_out=3)),
    "FrozenLayer": lambda cls: cls(
        inner=LAYER_REGISTRY["DenseLayer"](n_in=4, n_out=3)),
}


def _construct(name, cls):
    if name in SPECIAL:
        try:
            return SPECIAL[name](cls)
        except TypeError:
            pass  # fall through to field-name probing below
    fields = {f.name for f in dataclasses.fields(cls)}
    kwargs = {}
    for dim in ("n_in", "n_out"):
        if dim in fields:
            kwargs[dim] = 4
    for wrapped in ("inner", "fwd", "layer"):
        if wrapped in fields:
            kwargs[wrapped] = LAYER_REGISTRY["DenseLayer"](n_in=4, n_out=3)
    return cls(**kwargs)


@pytest.mark.parametrize("name", sorted(LAYER_REGISTRY))
def test_layer_json_round_trip(name):
    cls = LAYER_REGISTRY[name]
    layer = _construct(name, cls)
    d = layer.to_dict()
    back = layer_from_dict(json.loads(json.dumps(d)))   # through real JSON
    assert type(back) is cls
    for f in dataclasses.fields(cls):
        a, b = getattr(layer, f.name), getattr(back, f.name)
        if dataclasses.is_dataclass(a) and not isinstance(a, type):
            assert type(a) is type(b), f"{name}.{f.name}"
        else:
            assert a == b, f"{name}.{f.name}: {a!r} != {b!r}"
