"""Repo-bundled pretrained artifacts: init_pretrained() must verify the
manifest checksum and reproduce the recorded accuracy end-to-end (parity
role: reference zoo TestInstantiation + ZooModel.initPretrained:40)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.simple import LeNet, SimpleCNN
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


def _manifest():
    p = ZooModel._BUNDLED_DIR / "manifest.json"
    if not p.exists():
        pytest.skip("no bundled pretrained artifacts")
    return json.loads(p.read_text())


def test_lenet_pretrained_reproduces_recorded_accuracy():
    from deeplearning4j_tpu.data.fetchers import load_mnist
    entry = _manifest()["lenet"]
    net = LeNet(num_classes=10).init_pretrained()
    xte, yte = load_mnist(train=False, num_examples=entry["n_test"],
                          flatten=False)
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte.argmax(-1)).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.93


def test_simplecnn_pretrained_reproduces_recorded_accuracy():
    from deeplearning4j_tpu.data.fetchers import _synthetic_images, _one_hot
    entry = _manifest()["simplecnn"]
    net = SimpleCNN(num_classes=entry["n_classes"]).init_pretrained()
    xte, yte_i = _synthetic_images(entry["n_test"], 48, 48, 3,
                                   entry["n_classes"],
                                   seed=entry["test_seed"])
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte_i).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.9


def test_textgenlstm_pretrained_reproduces_recorded_accuracy():
    """Bundled char-LM artifact: held-out next-char top-1 must match the
    manifest (falsifiable: a broken restore scores ~1/vocab)."""
    from deeplearning4j_tpu.zoo.corpus import corpus_windows
    from deeplearning4j_tpu.zoo.simple import TextGenerationLSTM

    mf = _manifest()
    if "textgenlstm" not in mf:
        pytest.skip("textgenlstm artifact not bundled")
    entry = mf["textgenlstm"]
    _, (xte, yte), vocab = corpus_windows(T=entry["seq_len"])
    assert vocab == entry["vocab"]
    assert len(xte) == entry["n_test_windows"]
    net = TextGenerationLSTM(
        total_unique_characters=len(vocab)).init_pretrained()
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte.argmax(-1)).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.2                      # far above chance (~1/vocab)


@pytest.mark.slow
def test_resnet50_cifar_pretrained_reproduces_recorded_accuracy():
    """Bundled ComputationGraph artifact — proves init_pretrained moves CG
    weights (conf + arrays + graph topology) end-to-end."""
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.zoo.resnet import ResNet50Cifar
    from deeplearning4j_tpu.data.fetchers import load_cifar10

    mf = _manifest()
    if "resnet50_cifar10" not in mf:
        pytest.skip("resnet50_cifar10 artifact not bundled")
    entry = mf["resnet50_cifar10"]
    net = ResNet50Cifar(num_classes=10).init_pretrained()
    assert isinstance(net, ComputationGraph)
    xte, yte = load_cifar10(train=False, num_examples=entry["n_test"])
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte.argmax(-1)).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.5


def test_pretrained_checksum_guards_tampering(tmp_path, monkeypatch):
    """A tampered cached zip must be rejected by the manifest check."""
    entry = _manifest()["lenet"]
    cache = tmp_path / "pretrained"
    cache.mkdir()
    src = ZooModel._BUNDLED_DIR / "lenet.zip"
    bad = bytearray(src.read_bytes())
    bad[-1] ^= 0xFF
    (cache / "lenet.zip").write_bytes(bytes(bad))
    (cache / "manifest.json").write_text(json.dumps({"lenet": entry}))
    monkeypatch.setenv("DL4JTPU_DATA_DIR", str(tmp_path))
    with pytest.raises(IOError):
        LeNet(num_classes=10).init_pretrained()
