"""Repo-bundled pretrained artifacts: init_pretrained() must verify the
manifest checksum and reproduce the recorded accuracy end-to-end (parity
role: reference zoo TestInstantiation + ZooModel.initPretrained:40)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.zoo.simple import LeNet, SimpleCNN
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


def _manifest():
    p = ZooModel._BUNDLED_DIR / "manifest.json"
    if not p.exists():
        pytest.skip("no bundled pretrained artifacts")
    return json.loads(p.read_text())


def test_lenet_pretrained_reproduces_recorded_accuracy():
    from deeplearning4j_tpu.data.fetchers import load_mnist
    entry = _manifest()["lenet"]
    net = LeNet(num_classes=10).init_pretrained()
    xte, yte = load_mnist(train=False, num_examples=entry["n_test"],
                          flatten=False)
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte.argmax(-1)).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.95


def test_simplecnn_pretrained_reproduces_recorded_accuracy():
    from deeplearning4j_tpu.data.fetchers import _synthetic_images, _one_hot
    entry = _manifest()["simplecnn"]
    net = SimpleCNN(num_classes=entry["n_classes"]).init_pretrained()
    xte, yte_i = _synthetic_images(entry["n_test"], 48, 48, 3,
                                   entry["n_classes"],
                                   seed=entry["test_seed"])
    pred = np.asarray(net.output(xte))
    acc = float((pred.argmax(-1) == yte_i).mean())
    assert abs(acc - entry["accuracy"]) < 0.02, (acc, entry["accuracy"])
    assert acc > 0.95


def test_pretrained_checksum_guards_tampering(tmp_path, monkeypatch):
    """A tampered cached zip must be rejected by the manifest check."""
    entry = _manifest()["lenet"]
    cache = tmp_path / "pretrained"
    cache.mkdir()
    src = ZooModel._BUNDLED_DIR / "lenet.zip"
    bad = bytearray(src.read_bytes())
    bad[-1] ^= 0xFF
    (cache / "lenet.zip").write_bytes(bytes(bad))
    (cache / "manifest.json").write_text(json.dumps({"lenet": entry}))
    monkeypatch.setenv("DL4JTPU_DATA_DIR", str(tmp_path))
    with pytest.raises(IOError):
        LeNet(num_classes=10).init_pretrained()
