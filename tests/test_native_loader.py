"""Tests: native C++ record readers + async batcher vs Python reference.

Pattern parity: accelerator-vs-reference equivalence (SURVEY.md §4) applied
to the ETL path — the native loaders must produce byte-identical data to
the Python readers."""

import gzip
import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _write_idx(tmp_path, n=40, rows=5, cols=4, n_classes=7, seed=0):
    rs = np.random.RandomState(seed)
    imgs = rs.randint(0, 256, (n, rows, cols)).astype(np.uint8)
    labs = rs.randint(0, n_classes, n).astype(np.uint8)
    ip = tmp_path / "t-images-idx3-ubyte"
    lp = tmp_path / "t-labels-idx1-ubyte"
    with open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 0x0803, n, rows, cols))
        f.write(imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">II", 0x0801, n))
        f.write(labs.tobytes())
    return str(ip), str(lp), imgs, labs


class TestNativeReaders:
    def test_idx_matches_python(self, tmp_path):
        from deeplearning4j_tpu.data.native_loader import load_idx_native
        ip, lp, imgs, labs = _write_idx(tmp_path)
        x, y = load_idx_native(ip, lp, n_classes=7)
        np.testing.assert_allclose(
            x, imgs.reshape(40, -1).astype(np.float32) / 255.0)
        np.testing.assert_allclose(y, np.eye(7, dtype=np.float32)[labs])

    def test_idx_bad_file_raises(self, tmp_path):
        from deeplearning4j_tpu.data.native_loader import load_idx_native
        p = tmp_path / "bogus"
        p.write_bytes(b"not an idx file at all")
        with pytest.raises(ValueError, match="idx_load failed"):
            load_idx_native(str(p), str(p))

    def test_csv_matches_python(self, tmp_path):
        rs = np.random.RandomState(1)
        data = rs.randn(30, 5).astype(np.float32)
        labs = rs.randint(0, 3, 30)
        p = tmp_path / "d.csv"
        with open(p, "w") as f:
            f.write("a,b,c,d,e,label\n")
            for row, lab in zip(data, labs):
                f.write(",".join(f"{v:.6f}" for v in row) + f",{lab}\n")
        from deeplearning4j_tpu.data.native_loader import load_csv_native
        x, y = load_csv_native(str(p), label_col=5, n_classes=3,
                               skip_lines=1)
        np.testing.assert_allclose(x, data, atol=1e-5)
        np.testing.assert_allclose(y, np.eye(3, dtype=np.float32)[labs])

    def test_csv_no_label(self, tmp_path):
        p = tmp_path / "f.csv"
        p.write_text("1.5,2.5\n3.5,4.5\n")
        from deeplearning4j_tpu.data.native_loader import load_csv_native
        x, y = load_csv_native(str(p))
        np.testing.assert_allclose(x, [[1.5, 2.5], [3.5, 4.5]])
        assert y is None


class TestNativeAsyncIterator:
    def test_yields_every_example_once(self):
        from deeplearning4j_tpu.data.native_loader import (
            NativeAsyncDataSetIterator)
        rs = np.random.RandomState(2)
        x = rs.randn(37, 6).astype(np.float32)    # odd size → partial batch
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 37)]
        it = NativeAsyncDataSetIterator(x, y, batch_size=8, shuffle=True,
                                        seed=5)
        got = [ds for ds in it]
        sizes = [d.features.shape[0] for d in got]
        assert sum(sizes) == 37 and sizes[-1] == 5
        xs = np.concatenate([d.features for d in got])
        np.testing.assert_allclose(np.sort(xs.ravel()), np.sort(x.ravel()),
                                   atol=0)
        it.close()

    def test_reset_reshuffles_deterministically(self):
        from deeplearning4j_tpu.data.native_loader import (
            NativeAsyncDataSetIterator)
        x = np.arange(64, dtype=np.float32).reshape(16, 4)
        y = np.eye(2, dtype=np.float32)[np.arange(16) % 2]
        it = NativeAsyncDataSetIterator(x, y, batch_size=4, shuffle=True,
                                        seed=9)
        ep1 = np.concatenate([d.features for d in it])
        it.reset()
        ep2 = np.concatenate([d.features for d in it])
        # different order across epochs (seed+epoch), same multiset
        assert not np.array_equal(ep1, ep2)
        np.testing.assert_allclose(np.sort(ep1.ravel()), np.sort(ep2.ravel()))
        it.close()

    def test_labels_stay_aligned(self):
        from deeplearning4j_tpu.data.native_loader import (
            NativeAsyncDataSetIterator)
        x = np.arange(20, dtype=np.float32).reshape(20, 1)
        y = (x * 10).astype(np.float32)
        it = NativeAsyncDataSetIterator(x, y, batch_size=6, shuffle=True,
                                        seed=1)
        for ds in it:
            np.testing.assert_allclose(ds.labels, ds.features * 10)
        it.close()

    def test_trains_a_net_end_to_end(self):
        from deeplearning4j_tpu.data.native_loader import (
            NativeAsyncDataSetIterator)
        from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
        from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.updaters import Sgd
        rs = np.random.RandomState(3)
        x = rs.randn(128, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        conf = (NeuralNetConfiguration.builder().seed(2).updater(Sgd(0.2))
                .list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="tanh"))
                .layer(OutputLayer(n_in=16, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        it = NativeAsyncDataSetIterator(x, y, batch_size=32, seed=4)
        net.fit(it, epochs=30)
        acc = net.evaluate(x, y).accuracy()
        assert acc > 0.9
        it.close()


# ------------------------------------------- real-format image-tree loaders

def test_tinyimagenet_real_tree(monkeypatch):
    """TinyImageNetDataSetIterator reads the committed class-per-directory
    fixture tree (real-format path), resizes to 64x64x3, labels by sorted
    class order, and records 'real' provenance."""
    import os
    import numpy as np
    from deeplearning4j_tpu.data import fetchers

    root = os.path.join(os.path.dirname(__file__), "resources", "image_tree")
    monkeypatch.setenv("DL4JTPU_DATA_DIR", root)
    it = fetchers.TinyImageNetDataSetIterator(batch_size=6, num_examples=6)
    ds = next(iter(it))
    assert ds.features.shape == (6, 64, 64, 3)
    # default wire format is raw uint8 with a device_side /255 scaler
    # attached (4x less H2D traffic; cast runs on chip)
    assert ds.features.dtype == np.uint8
    assert it.pre_processor is not None and it.pre_processor.device_side
    assert ds.labels.shape[1] == 200
    assert fetchers.data_source("tinyimagenet") == "real"
    # fixture images carry a class-colored channel: class 0 = red saturated
    labels = np.argmax(np.asarray(ds.labels), axis=1)
    for x, l in zip(np.asarray(ds.features), labels):
        assert x[..., int(l)].min() > 0.9 * 255, \
            "class channel must be saturated"
    # uint8_wire=False restores plain float [0,1] features
    it_f = fetchers.TinyImageNetDataSetIterator(batch_size=6, num_examples=6,
                                                uint8_wire=False)
    ds_f = next(iter(it_f))
    assert ds_f.features.dtype == np.float32
    np.testing.assert_allclose(np.asarray(ds_f.features),
                               np.asarray(ds.features) / 255.0,
                               atol=0.5 / 255)

    # absent tree -> synthetic fallback, recorded as such
    monkeypatch.setenv("DL4JTPU_DATA_DIR", root + "/does_not_exist")
    it2 = fetchers.TinyImageNetDataSetIterator(batch_size=4, num_examples=4)
    ds2 = next(iter(it2))
    assert ds2.features.shape == (4, 64, 64, 3)
    assert fetchers.data_source("tinyimagenet") == "synthetic"


def test_lfw_real_tree(monkeypatch):
    import os
    import numpy as np
    from deeplearning4j_tpu.data import fetchers

    root = os.path.join(os.path.dirname(__file__), "resources", "image_tree")
    monkeypatch.setenv("DL4JTPU_DATA_DIR", root)
    it = fetchers.LFWDataSetIterator(batch_size=4, num_examples=4,
                                     num_labels=2, image_shape=(16, 16, 3))
    ds = next(iter(it))
    assert ds.features.shape == (4, 16, 16, 3)
    assert fetchers.data_source("lfw") == "real"
    labels = set(np.argmax(np.asarray(ds.labels), axis=1).tolist())
    assert labels == {0, 1}          # both people present
