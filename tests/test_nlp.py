"""NLP embeddings tests (parity role: deeplearning4j-nlp test corpus tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    Word2Vec, ParagraphVectors, Glove, WordVectorSerializer,
    DefaultTokenizerFactory, CollectionSentenceIterator, VocabConstructor,
)
from deeplearning4j_tpu.nlp.vocab import build_huffman, VocabCache


def _corpus(n_reps=60):
    """Tiny synthetic corpus with two clear topic clusters."""
    a = ["the cat sat on the mat with another cat",
         "a cat and a kitten play with the mat",
         "the kitten chased the cat around the mat"]
    b = ["stocks rose as the market rallied today",
         "the market fell while stocks dropped today",
         "investors sold stocks as the market crashed"]
    return (a + b) * n_reps


def test_vocab_and_huffman():
    sentences = _corpus(2)
    tf = DefaultTokenizerFactory()
    seqs = [tf.create(s).get_tokens() for s in sentences]
    vocab = VocabConstructor(min_word_frequency=2).build_vocab(seqs)
    assert vocab.contains_word("cat")
    assert vocab.word_frequency("the") > vocab.word_frequency("kitten")
    build_huffman(vocab)
    for w in vocab.vocab_words():
        assert len(w.codes) > 0
        assert len(w.codes) == len(w.points)
    # frequent words get shorter codes
    assert len(vocab.word_for("the").codes) <= len(vocab.word_for("kitten").codes)


def test_word2vec_skipgram_clusters():
    w2v = Word2Vec(min_word_frequency=3, layer_size=32, window_size=3,
                   epochs=3, negative=5, seed=7, sentences=_corpus(),
                   subsampling=0)  # tiny corpus: keep all tokens
    w2v.fit()
    # same-topic words closer than cross-topic
    assert w2v.similarity("cat", "kitten") > w2v.similarity("cat", "stocks")
    assert w2v.similarity("market", "stocks") > w2v.similarity("market", "mat")
    near = w2v.words_nearest("cat", 5)
    assert any(w in near for w in ("kitten", "mat"))


def test_word2vec_hierarchical_softmax():
    w2v = Word2Vec(min_word_frequency=3, layer_size=24, window_size=3,
                   epochs=3, use_hierarchic_softmax=True, seed=7,
                   sentences=_corpus(), subsampling=0)
    w2v.fit()
    assert w2v.similarity("cat", "kitten") > w2v.similarity("cat", "market")


def test_word2vec_cbow():
    w2v = Word2Vec(min_word_frequency=3, layer_size=24, window_size=3,
                   epochs=8, seed=7, sentences=_corpus(), subsampling=0,
                   elements_learning_algorithm="cbow")
    w2v.fit()
    # margin, not a hair's breadth: topic structure must be clear
    assert w2v.similarity("stocks", "market") > \
        w2v.similarity("stocks", "kitten") + 0.1


def test_word2vec_serialization(tmp_path):
    w2v = Word2Vec(min_word_frequency=3, layer_size=16, epochs=1, seed=7,
                   sentences=_corpus(10), subsampling=0).fit()
    p = tmp_path / "vectors.txt"
    WordVectorSerializer.write_word_vectors(w2v, p)
    loaded = WordVectorSerializer.read_word_vectors(p)
    assert loaded.has_word("cat")
    v1 = w2v.word_vector("cat")
    v2 = loaded.word_vector("cat")
    assert np.allclose(v1, v2, atol=1e-5)
    assert loaded.words_nearest("cat", 3) == w2v.words_nearest("cat", 3)


def test_paragraph_vectors_dbow():
    docs = _corpus(20)
    labels = [f"cats_{i}" if "cat" in d or "kitten" in d else f"fin_{i}"
              for i, d in enumerate(docs)]
    pv = ParagraphVectors(min_word_frequency=3, layer_size=24, window_size=3,
                          epochs=2, seed=7, sentences=docs, labels=labels,
                          subsampling=0)
    pv.fit()
    dv = pv.doc_vector(labels[0])
    assert dv is not None and dv.shape == (24,)
    inferred = pv.infer_vector("the cat and the kitten on the mat")
    assert inferred.shape == (24,)
    assert np.isfinite(inferred).all()


def test_glove():
    g = Glove(min_word_frequency=3, layer_size=24, window_size=4, epochs=8,
              seed=7, sentences=_corpus(), subsampling=0)
    g.fit()
    assert g.similarity("cat", "kitten") > g.similarity("cat", "stocks")


def test_distributed_word2vec_clusters_and_is_deterministic():
    """DistributedWord2Vec (dl4j-spark-nlp parity: per-partition training +
    periodic table averaging) over the 8-CPU mesh: learns the same topic
    structure as the single-device trainer and is run-to-run deterministic."""
    from deeplearning4j_tpu.nlp import DistributedWord2Vec
    from deeplearning4j_tpu.parallel.wrapper import default_mesh

    mesh = default_mesh()
    assert mesh.devices.size == 8      # conftest forces 8 virtual devices

    def train():
        return DistributedWord2Vec(
            mesh=mesh, averaging_frequency=4, min_word_frequency=3,
            layer_size=24, window_size=3, epochs=3, seed=7,
            sentences=_corpus(), subsampling=0).fit()

    w2v = train()
    assert w2v.similarity("stocks", "market") > w2v.similarity("stocks", "kitten")
    assert w2v.similarity("cat", "kitten") > w2v.similarity("cat", "market")

    again = train()
    np.testing.assert_array_equal(np.asarray(w2v.syn0), np.asarray(again.syn0))


def test_distributed_word2vec_single_device_mesh():
    """n=1 mesh: the pmean is the identity; training still works end-to-end
    (the degenerate local case, like Spark local[1])."""
    import jax
    from jax.sharding import Mesh
    from deeplearning4j_tpu.nlp import DistributedWord2Vec

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    w2v = DistributedWord2Vec(
        mesh=mesh, min_word_frequency=3, layer_size=16, window_size=3,
        epochs=2, seed=7, sentences=_corpus(), subsampling=0).fit()
    assert w2v.similarity("stocks", "market") > w2v.similarity("stocks", "kitten")


def test_distributed_word2vec_rejects_hs():
    from deeplearning4j_tpu.nlp import DistributedWord2Vec
    with pytest.raises(NotImplementedError):
        DistributedWord2Vec(use_hierarchic_softmax=True, sentences=["a b"])


def test_w2v_single_token_corpus_no_crash():
    """A corpus that reduces to <=1 kept token must fit() cleanly (no pairs
    to train on), not crash in pair generation."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    w = Word2Vec(min_word_frequency=1, layer_size=8, subsampling=0,
                 sentences=["hello"], seed=1)
    w.fit()          # no pairs -> tables untouched, no exception
    assert w.syn0 is not None


def test_w2v_token_cache_sees_inplace_mutation():
    """Replacing sentences IN PLACE (same list object) must invalidate the
    token cache — the fingerprint hashes content, not identity."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    sents = ["a b c d e", "b c d e f"] * 10
    w = Word2Vec(min_word_frequency=1, layer_size=8, subsampling=0,
                 sentences=sents, seed=1)
    w.build_vocab()
    flat1, _ = w._encode_tokens()
    sents[0] = "f e d c b"          # in-place mutation, same length
    flat2, _ = w._encode_tokens()
    assert not np.array_equal(flat1[:5], flat2[:5])


def test_cjk_tokenizer_and_chinese_w2v():
    """The CJK bigram tokenizer proves the tokenizer SPI extension point:
    unsegmented Chinese text tokenizes into bigrams and trains a Word2Vec
    whose topic clusters separate (parity role: deeplearning4j-nlp-chinese)."""
    from deeplearning4j_tpu.nlp import CJKTokenizerFactory, Word2Vec

    tf = CJKTokenizerFactory()
    toks = tf.create("我爱机器学习 and jax").get_tokens()
    assert toks == ["我爱", "爱机", "机器", "器学", "学习", "and", "jax"]
    assert tf.create("猫").get_tokens() == ["猫"]        # single char kept

    rs = np.random.RandomState(3)
    animals = "小猫 小狗 宠物 毛皮".split()
    tech = "电脑 程序 代码 芯片".split()
    sentences = []
    for _ in range(300):
        topic = animals if rs.rand() < 0.5 else tech
        sentences.append("".join(rs.choice(topic, size=6)))   # unsegmented!
    w2v = Word2Vec(min_word_frequency=3, layer_size=16, window_size=3,
                   negative=5, epochs=3, seed=2, subsampling=0,
                   sentences=sentences, tokenizer_factory=CJKTokenizerFactory())
    w2v.fit()
    # bigrams fully inside one word surface frequently; cross-topic
    # similarity must be lower than in-topic for a stable pair
    assert w2v.has_word("小猫") or w2v.vocab.num_words() > 4
    vocab_words = [w2v.vocab.word_at_index(i)
                   for i in range(w2v.vocab.num_words())]
    assert any(any(_c in w for _c in "猫狗宠毛") for w in vocab_words)


def test_word2vec_cbow_hierarchical_softmax():
    """CBOW + HS (reference CBOW.java:138 codes/points branch) learns the
    same topic structure as the other three objective combinations."""
    w2v = Word2Vec(min_word_frequency=3, layer_size=24, window_size=3,
                   epochs=8, seed=7, sentences=_corpus(), subsampling=0,
                   use_hierarchic_softmax=True,
                   elements_learning_algorithm="cbow")
    w2v.fit()
    assert w2v.similarity("stocks", "market") > \
        w2v.similarity("stocks", "kitten") + 0.1


@pytest.mark.slow
def test_cbow_hs_batch_matches_autodiff():
    """The hand-written CBOW-HS scatter update equals -lr * d(loss)/d(params)
    of the Huffman-path NLL at the same point (single-occurrence indices, so
    batched scatter == sequential SGD)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nlp.word2vec import _cbow_hs_batch

    rs = np.random.RandomState(3)
    V, D, B, W, L = 12, 8, 2, 3, 4
    syn0 = jnp.asarray(rs.randn(V, D) * 0.3, jnp.float32)
    syn1 = jnp.asarray(rs.randn(V, D) * 0.3, jnp.float32)
    # disjoint context/point indices so scatter-adds don't overlap
    ctx = jnp.asarray([[0, 1, 2], [3, 4, 5]], jnp.int32)
    msk = jnp.asarray([[1, 1, 0], [1, 1, 1]], jnp.float32)
    pts = jnp.asarray([[6, 7, 0, 0], [8, 9, 10, 0]], jnp.int32)
    cds = jnp.asarray([[1, 0, 0, 0], [0, 1, 1, 0]], jnp.float32)
    cmsk = jnp.asarray([[1, 1, 0, 0], [1, 1, 1, 0]], jnp.float32)
    lr = 0.1

    def loss(syn0, syn1):
        h = (syn0[ctx] * msk[..., None]).sum(1) / msk.sum(-1, keepdims=True)
        s = jnp.einsum("bd,bld->bl", h, syn1[pts])
        # -log sigmoid((1-2c)s) summed over the valid path
        return jnp.sum(jax.nn.softplus(-(1.0 - 2.0 * cds) * s) * cmsk)

    g0, g1 = jax.grad(loss, argnums=(0, 1))(syn0, syn1)
    n0, n1 = _cbow_hs_batch(syn0, syn1, ctx, msk, pts, cds, cmsk,
                            jnp.float32(lr))
    np.testing.assert_allclose(np.asarray(n0 - syn0), np.asarray(-lr * g0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(n1 - syn1), np.asarray(-lr * g1),
                               rtol=1e-4, atol=1e-5)
