"""Elastic cluster tests: coordinator state machine + real subprocess runs.

Two layers, mirroring exec/elastic.py's split:

- **Fake-clock matrix** — the ``ElasticCoordinator`` is pure logic with an
  injectable clock, so the whole lease walk (heartbeat miss → suspect →
  evict → rejoin), stale-generation fencing and N-1 degradation run with
  zero sleeps and zero processes.
- **Subprocess runs** — ``ClusterManager`` spawns real
  ``python -m deeplearning4j_tpu.exec.worker`` processes. The fast N=2
  smoke stays in tier-1; the N=4 SIGKILL soak (bitwise kill-and-rejoin
  parity, zero job restarts) and the partition test are ``slow``.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.exec.elastic import (ClusterFullError,
                                             ElasticCoordinator,
                                             EvictedError, FencedError,
                                             LIVE, SUSPECT)


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def _form(n: int, clock: FakeClock = None, **kw):
    """Join + sync n workers through formation; returns (coord, clock)."""
    clock = clock or FakeClock()
    coord = ElasticCoordinator(n, clock=clock, **kw)
    for i in range(n):
        coord.join(f"w{i}")
        clock.advance(0.01)     # distinct joined_at → deterministic ranks
    for i in range(n):
        coord.sync(f"w{i}", 1)
    assert coord.generation == 1 and coord.proposal is None
    assert coord.world == n
    return coord, clock


def _ranks(coord):
    return {wid: m["rank"] for wid, m in coord.state()["members"].items()}


# ---------------------------------------------------------------------------
# fake-clock state machine
# ---------------------------------------------------------------------------

def test_formation_commits_generation_one_with_dense_ranks():
    coord, _ = _form(3)
    assert coord.phase == "running"
    assert sorted(_ranks(coord).values()) == [0, 1, 2]


def test_sync_waits_until_every_member_acks():
    clock = FakeClock()
    coord = ElasticCoordinator(2, clock=clock)
    coord.join("w0")
    assert coord.sync("w0", 1) == {"status": "wait", "proposal": 1}
    coord.join("w1")
    assert coord.sync("w0", 1)["status"] == "wait"   # w1 not acked yet
    view = None
    coord.sync("w1", 1)
    view = coord.sync("w0", 1)
    assert view["status"] == "go" and view["generation"] == 1
    assert view["world"] == 2


def test_join_beyond_world_size_rejected():
    coord, _ = _form(2)
    with pytest.raises(ClusterFullError):
        coord.join("w2")


def test_heartbeat_from_non_member_raises_evicted():
    coord, _ = _form(1)
    with pytest.raises(EvictedError):
        coord.heartbeat("ghost", generation=1)


def test_missed_heartbeats_walk_live_suspect_and_heal():
    coord, clock = _form(2, suspect_after=1.5, evict_after=4.0)
    clock.advance(1.0)
    coord.heartbeat("w0", generation=1)
    clock.advance(0.7)                   # w1 lease age ~1.7 >= 1.5
    coord.tick()
    states = {w: m["state"] for w, m in coord.state()["members"].items()}
    assert states["w1"] == SUSPECT and states["w0"] == LIVE
    coord.heartbeat("w1", generation=1)  # a heartbeat heals suspicion
    states = {w: m["state"] for w, m in coord.state()["members"].items()}
    assert states["w1"] == LIVE


def test_lease_expiry_evicts_and_replacement_recommits_full_world():
    coord, clock = _form(2, suspect_after=1.5, evict_after=4.0,
                         replacement_grace=8.0)
    ranks_before = _ranks(coord)
    clock.advance(2.0)
    coord.heartbeat("w0", generation=1)
    clock.advance(2.0)                   # w1 lease age 4.0 → evicted
    coord.tick()
    assert "w1" not in coord.state()["members"]
    assert coord.proposal == 2           # reform in flight
    evs = [e["type"] for e in coord.events]
    assert "evicted" in evs and "reform_proposed" in evs

    # mid-reform heartbeats carry the rollback directive
    assert coord.heartbeat("w0", generation=1)["directive"] == "rollback"

    joined = coord.join("w1b")           # the supervisor's replacement
    assert joined["proposal"] == 2
    coord.sync("w0", 2)
    assert coord.generation == 1         # replacement not synced yet
    coord.sync("w1b", 2)
    assert coord.generation == 2 and coord.world == 2
    # survivor keeps its rank; the replacement fills the hole — the shard
    # mapping matches an unkilled run (what bitwise parity depends on)
    ranks = _ranks(coord)
    assert ranks["w0"] == ranks_before["w0"]
    assert ranks["w1b"] == ranks_before["w1"]
    assert coord.last_recovery_wall == pytest.approx(
        clock.t - (100.0 + 0.02 + 4.0), abs=1e-6)
    assert coord.heartbeat("w0", generation=2)["directive"] == "none"


def test_stale_generation_contribution_is_fenced():
    coord, clock = _form(2)
    coord.leave("w1")                    # opens proposal 2
    with pytest.raises(FencedError) as ei:
        coord.contribute("w0", generation=1, step=3, rows=16,
                         vec=np.zeros(4, np.float32))
    assert ei.value.proposal == 2
    # after the reform commits, a straggler stamped gen 1 is still fenced
    clock.advance(coord.replacement_grace + 0.1)
    coord.sync("w0", 2)
    coord.tick()
    assert coord.generation == 2
    with pytest.raises(FencedError):
        coord.contribute("w0", generation=1, step=3, rows=16,
                         vec=np.zeros(4, np.float32))


def test_grace_expiry_commits_degraded_n_minus_1():
    coord, clock = _form(3, replacement_grace=5.0)
    coord.leave("w2")
    coord.sync("w0", 2)
    coord.sync("w1", 2)
    assert coord.generation == 1         # grace window still open
    clock.advance(2.6)                   # survivors keep their leases warm
    coord.heartbeat("w0", generation=1)
    coord.heartbeat("w1", generation=1)
    clock.advance(2.6)
    coord.tick()
    assert coord.generation == 2 and coord.world == 2
    assert sorted(_ranks(coord).values()) == [0, 1]   # ranks compacted
    committed = [e for e in coord.events
                 if e["type"] == "generation_committed" and e["world"] == 2]
    assert committed, coord.events


def test_eviction_of_last_nonreporter_completes_the_job():
    """If the only member that has NOT posted a result dies, the eviction
    itself must complete the job — there is no later result() call to
    re-check the condition, and the finished survivors would otherwise
    wait forever in a reform nobody can commit."""
    coord, clock = _form(2, suspect_after=1.5, evict_after=4.0)
    coord.result("w0", {"final_loss": 0.5})
    assert coord.phase == "running"      # w1 still training
    clock.advance(2.0)
    coord.heartbeat("w0", generation=1)  # survivor's lease stays warm
    clock.advance(2.0)                   # w1 lease age 4.0 → evicted
    coord.tick()
    assert "w1" not in coord.state()["members"]
    assert coord.phase == "done"
    assert coord.proposal is None        # no reform holds the finished job


def test_rollback_without_anchor_rebuilds_the_seed_model():
    """A survivor fenced BEFORE the first checkpoint has already applied
    updates; its rollback must rebuild the deterministic seed model, not
    just reset the step counter — otherwise it replays steps 0..k onto
    advanced params while a replacement starts from the fresh build, and
    the members diverge forever."""
    import jax

    from deeplearning4j_tpu.exec.worker import ElasticWorker, params_digest
    from deeplearning4j_tpu.serving.replica import build_model

    w = ElasticWorker("http://127.0.0.1:9", "wX")   # never dials out
    w.cfg = {"model": "mlp"}
    w.net = build_model("mlp")
    w._build_programs()
    seed_digest = params_digest(w.net.params)
    # pretend two steps applied before the eviction reached us
    w.net.params = jax.tree_util.tree_map(lambda a: a + 1.0, w.net.params)
    w.net.iteration = 2
    w.anchor = {"step": 0, "path": None}
    w._restore_anchor()
    assert w.net.iteration == 0
    assert params_digest(w.net.params) == seed_digest


def test_allreduce_rank_order_deterministic_and_idempotent():
    coord, _ = _form(2)
    v0 = np.array([2.0, 4.0], np.float32)     # pre-scaled by rows
    v1 = np.array([6.0, 8.0], np.float32)
    coord.contribute("w0", generation=1, step=0, rows=2, vec=v0)
    coord.contribute("w1", generation=1, step=0, rows=2, vec=v1)
    got = coord.wait_reduced("w0", generation=1, step=0, timeout=1.0)
    np.testing.assert_array_equal(got, np.array([2.0, 3.0], np.float32))
    # a retried POST after the reduction is a no-op, same answer served
    coord.contribute("w0", generation=1, step=0, rows=2, vec=v0)
    again = coord.wait_reduced("w1", generation=1, step=0, timeout=1.0)
    np.testing.assert_array_equal(again, got)
    assert coord.reduced_steps == 1


def test_reduced_cache_evicts_beyond_keep_window():
    """The star path caches reduced vectors so a worker whose HTTP timed
    out can re-read its step — but only the last ``_REDUCED_KEEP`` of
    them, oldest evicted first, or a long run would pin every gradient
    ever reduced in coordinator memory."""
    from deeplearning4j_tpu.exec.elastic import _REDUCED_KEEP
    coord, _ = _form(2)
    v = np.ones(2, np.float32)
    steps = _REDUCED_KEEP + 3
    for s in range(steps):
        coord.contribute("w0", generation=1, step=s, rows=1, vec=v)
        coord.contribute("w1", generation=1, step=s, rows=1, vec=v)
        coord.wait_reduced("w0", generation=1, step=s, timeout=1.0)
    assert len(coord._reduced) == _REDUCED_KEEP
    kept = sorted(k[1] for k in coord._reduced)
    assert kept == list(range(steps - _REDUCED_KEEP, steps))
    # a recent step re-reads fine; an evicted one can never complete again
    got = coord.wait_reduced("w1", generation=1, step=steps - 1, timeout=1.0)
    np.testing.assert_array_equal(got, v)


def test_chain_reduced_steps_advance_from_heartbeat_floor():
    """On the peer-to-peer plane the coordinator sees no gradients;
    ``reduced_steps`` is the min over members' heartbeat-reported steps —
    monotone even when a reformed member reports an anchor-rolled-back
    step."""
    coord, clock = _form(2)
    coord.heartbeat("w0", generation=1, step=3)
    coord.heartbeat("w1", generation=1, step=2)
    assert coord.reduced_steps == 2          # floor, not max
    coord.heartbeat("w1", generation=1, step=5)
    assert coord.reduced_steps == 3
    coord.heartbeat("w0", generation=1, step=0)   # rollback replay: ignored
    assert coord.reduced_steps == 3
    # the final result payload also advances the floor (a worker may
    # finish between heartbeats)
    coord.result("w0", {"steps": 6})
    coord.result("w1", {"steps": 6})
    assert coord.reduced_steps == 6


def test_coord_client_reuses_connection_and_reconnects_once():
    """Control RPCs ride ONE persistent keep-alive connection per thread
    (serving/client.py pattern); a dropped socket reconnects once
    transparently instead of surfacing to the retry loop."""
    from deeplearning4j_tpu.exec.elastic import CoordinatorServer
    from deeplearning4j_tpu.exec.worker import CoordClient
    coord = ElasticCoordinator(1)
    srv = CoordinatorServer(coord)
    srv.start()
    try:
        client = CoordClient(srv.url, "w0")
        client.state()
        conn1 = client._local.conn
        sock1 = conn1.sock
        assert sock1 is not None
        client.state()
        assert client._local.conn is conn1       # same connection reused
        assert conn1.sock is sock1               # ... and the same socket
        conn1.close()                            # server idle-closed it
        client.state()                           # reconnect-once, no error
        assert client._local.conn.sock is not None
        assert client._local.conn.sock is not sock1
        client.close()
    finally:
        srv.stop()


def test_wait_reduced_fenced_when_membership_changes_mid_barrier():
    coord, _ = _form(2)
    coord.contribute("w0", generation=1, step=0, rows=2,
                     vec=np.zeros(2, np.float32))
    coord.leave("w1")                    # barrier can never complete
    with pytest.raises(FencedError):
        coord.wait_reduced("w0", generation=1, step=0, timeout=1.0)


def test_rank_tagged_spill_paths(monkeypatch):
    from deeplearning4j_tpu.monitor.flight import rank_tagged_path
    monkeypatch.delenv("DL4JTPU_RANK", raising=False)
    assert rank_tagged_path("/tmp/x/spill.json") == "/tmp/x/spill.json"
    monkeypatch.setenv("DL4JTPU_RANK", "2")
    assert rank_tagged_path("/tmp/x/spill.json") == "/tmp/x/spill.rank2.json"
    assert rank_tagged_path("/tmp/x/spill.rank2.json") \
        == "/tmp/x/spill.rank2.json"


# ---------------------------------------------------------------------------
# real subprocess clusters
# ---------------------------------------------------------------------------

def _digests(res):
    return {w: r["params_digest"] for w, r in res["results"].items()}


def test_cluster_n2_chain_bitwise_vs_star_vs_single_process(tmp_path):
    """The data-plane parity triangle (docs/ELASTIC_TRAINING.md "Data
    plane"): the default chunk-pipelined chain, the PR 19 star fallback and
    the in-process single-process replay of the same job must all land on
    the SAME final params digest — the chain's rank-ordered accumulation
    is bitwise, not approximately, the star's arithmetic."""
    from deeplearning4j_tpu.exec.cluster import ClusterManager
    from deeplearning4j_tpu.exec.worker import single_process_reference
    ref = single_process_reference(model="mlp", seed=42, total_steps=6,
                                   global_batch=32, world=2)

    mgr = ClusterManager(tmp_path / "chain", workers=2, total_steps=6,
                         global_batch=32, ckpt_every=3, aot=True)
    res2 = mgr.run(timeout=180)
    d2 = _digests(res2)
    assert len(d2) == 2 and len(set(d2.values())) == 1, d2
    assert set(d2.values()) == {ref["params_digest"]}, (d2, ref)
    assert res2["reduced_steps"] == 6    # inferred from heartbeat floor
    assert res2["spawns"] == 2 and res2["replacements"] == 0
    assert res2["generation"] == 1       # membership never changed
    assert res2["checkpoint"] is not None
    # control plane only: no gradient ever passed through the coordinator
    assert not mgr.coord._reduced and not mgr.coord._barriers
    for r in res2["results"].values():
        assert r["comms"]["data_plane"] == "chain"
        assert r["comms"]["bytes_sent"] > 0 and r["comms"]["bytes_recv"] > 0

    res_star = ClusterManager(tmp_path / "star", workers=2, total_steps=6,
                              global_batch=32, ckpt_every=3, aot=True,
                              data_plane="star").run(timeout=180)
    ds = _digests(res_star)
    assert set(ds.values()) == {ref["params_digest"]}, (ds, ref)
    for r in res_star["results"].values():
        assert r["comms"]["data_plane"] == "star"


@pytest.mark.slow
def test_sigkill_and_rejoin_is_bitwise_and_restarts_nothing(tmp_path):
    """The headline soak: N=4, worker 2 SIGKILLs itself mid-run, the
    replacement restores checkpoint + AOT and the final params are
    bitwise identical to an unkilled N=4 run — with zero job restarts."""
    from deeplearning4j_tpu.exec.cluster import ClusterManager
    ref = ClusterManager(tmp_path / "ref", workers=4, total_steps=10,
                         global_batch=32, ckpt_every=4,
                         aot=True).run(timeout=240)
    dr = _digests(ref)
    assert len(set(dr.values())) == 1, dr

    mgr = ClusterManager(tmp_path / "kill", workers=4, total_steps=10,
                         global_batch=32, ckpt_every=4, aot=True,
                         chaos={2: "die_at_step=6"})
    res = mgr.run(timeout=240)
    dk = _digests(res)
    assert len(set(dk.values())) == 1, dk
    assert set(dk.values()) == set(dr.values()), (dr, dk)   # bitwise parity

    # exactly one replacement joined the SAME job — nothing restarted
    assert res["replacements"] == 1 and res["spawns"] == 5
    assert res["generation"] == 2
    assert "w2r1" in res["results"]
    assert res["results"]["w2r1"]["rejoined"]
    assert res["results"]["w2r1"]["aot_restored"] >= 1
    for wid in ("w0", "w1", "w3"):       # survivors ran straight through
        assert mgr.procs[wid].proc.returncode == 0, wid
    assert res["last_recovery_wall"] is not None
    assert 0 < res["last_recovery_wall"] < 60
    evs = [e["type"] for e in res["events"]]
    assert "evicted" in evs and "generation_committed" in evs


@pytest.mark.slow
def test_kill_before_first_checkpoint_recovers_bitwise(tmp_path):
    """Worker death BEFORE any anchor exists: the rollback has no
    checkpoint to restore, so survivors rebuild the seed model and the
    whole cluster replays from step 0 — final params bitwise equal to an
    unkilled run."""
    from deeplearning4j_tpu.exec.cluster import ClusterManager
    ref = ClusterManager(tmp_path / "ref", workers=2, total_steps=6,
                         global_batch=32, ckpt_every=4,
                         aot=False).run(timeout=240)
    dr = _digests(ref)
    assert len(set(dr.values())) == 1, dr

    mgr = ClusterManager(tmp_path / "kill", workers=2, total_steps=6,
                         global_batch=32, ckpt_every=4, aot=False,
                         chaos={1: "die_at_step=1"})
    res = mgr.run(timeout=240)
    dk = _digests(res)
    assert len(set(dk.values())) == 1, dk
    assert set(dk.values()) == set(dr.values()), (dr, dk)   # bitwise parity
    assert res["replacements"] == 1 and res["spawns"] == 3
    assert res["reduced_steps"] == 6


@pytest.mark.slow
def test_threshold_codec_survives_kill_and_resets_residuals(tmp_path):
    """Lossy codec under chaos: a SIGKILL mid-run reforms the chain and the
    job still converges — and every member that lived through the reform
    reports residual_resets >= 1 (stale error feedback fenced out with the
    dead generation), while wire bytes stay well under dense."""
    from deeplearning4j_tpu.exec.cluster import ClusterManager
    mgr = ClusterManager(tmp_path / "thr", workers=3, total_steps=10,
                         global_batch=30, ckpt_every=3, aot=True,
                         model="charlstm", codec="threshold",
                         bucket_mb=0.005, capacity_fraction=0.05,
                         chaos={1: "die_at_step=5"})
    res = mgr.run(timeout=240)
    assert res["replacements"] == 1 and res["spawns"] == 4
    assert res["reduced_steps"] == 10
    digs = _digests(res)
    assert len(set(digs.values())) == 1, digs    # members agree with each
    for wid, r in res["results"].items():        # other (not with dense)
        assert np.isfinite(r["final_loss"])
        assert r["comms"]["codec"] == "threshold"
        assert r["comms"]["compression_ratio"] > 2.0, (wid, r["comms"])
    for wid in ("w0", "w2"):                     # reform survivors
        assert res["results"][wid]["comms"]["residual_resets"] >= 1, wid


@pytest.mark.slow
def test_partition_evicts_and_cluster_continues_degraded(tmp_path):
    """Blackholed coordinator link: the worker process stays alive but its
    heartbeats vanish — lease expiry evicts it and, with no replacement,
    the grace window expires into an N-1 degraded commit that finishes
    the job. Every seat carries slow_ms chaos so the remaining steps
    outlast the eviction window: on the peer-to-peer chain the gradient
    plane does NOT die with the coordinator link, so a fast job would
    otherwise finish through the healthy 3-chain before the lease ever
    expired (the control/data-plane split working as designed, but not
    the path this drill pins)."""
    from deeplearning4j_tpu.exec.cluster import ClusterManager
    mgr = ClusterManager(tmp_path / "part", workers=3, total_steps=10,
                         global_batch=30, ckpt_every=3, aot=False,
                         hb_interval=0.2, suspect_after=0.8,
                         evict_after=2.0, replacement_grace=2.0,
                         replace=False, partition=[2],
                         chaos={i: "slow_ms=700" for i in range(3)})
    mgr.start()
    try:
        deadline = time.monotonic() + 120
        while mgr.coord.reduced_steps < 4:   # train past the first anchor
            if time.monotonic() > deadline:
                raise TimeoutError("cluster never reached step 4")
            time.sleep(0.05)
        assert mgr.procs["w2"].alive()
        mgr.partition_worker("w2")
    except BaseException:
        mgr.stop()
        raise
    res = mgr.run(timeout=180)

    assert res["world"] == 2             # finished degraded, no replacement
    assert set(res["results"]) == {"w0", "w1"}
    digs = {r["params_digest"] for r in res["results"].values()}
    assert len(digs) == 1, res["results"]
    evicted = [e for e in res["events"] if e["type"] == "evicted"]
    assert evicted and evicted[0]["worker_id"] == "w2"
    assert evicted[0]["reason"] == "lease_expired"
    degraded = [e for e in res["events"]
                if e["type"] == "generation_committed" and e["world"] == 2]
    assert degraded, res["events"]
    assert res["reduced_steps"] == 10
