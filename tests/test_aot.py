"""AOT program artifacts (exec/aot.py): serialize/restore roundtrip,
fall-back-to-retrace on every artifact-level key mismatch (a stale
program must NEVER be deserialized), miss accounting, and the artifact
riding checkpoint rotation."""

import json
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.exec import aot


def _miss_count(reason):
    return aot._aot_metrics()["misses"].labels(reason=reason).value


def _restore_count(engine):
    return aot._aot_metrics()["restores"].labels(engine=engine).value


# ---------------------------------------------------------------- bundle io
def test_bundle_roundtrip_bitwise(tmp_path):
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda a, b: a @ b + 1.0)
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    y = np.arange(8, dtype=np.float32).reshape(4, 2)
    want = np.asarray(fn(x, y))

    b = aot.AotBundle("sig-a", "f32")
    b.add_compiled("matmul", aot.export_compiled(fn, (x, y)))
    path = str(tmp_path / "art.aot.zip")
    b.save(path)

    loaded, reason = aot.open_bundle(path, "sig-a", "f32")
    assert reason is None and "matmul" in loaded
    r0 = _restore_count("t")
    prog = loaded.restore("matmul", engine="t")
    assert prog is not None
    assert _restore_count("t") == r0 + 1
    got = np.asarray(prog(jnp.asarray(x), jnp.asarray(y)))
    assert np.array_equal(got, want)


def test_bundle_merge_save_unions_programs(tmp_path):
    import jax

    path = str(tmp_path / "art.aot.zip")
    fn1 = jax.jit(lambda a: a + 1)
    fn2 = jax.jit(lambda a: a * 2)
    x = np.zeros(3, np.float32)

    b1 = aot.AotBundle("sig", "f32")
    b1.add_compiled("p1", aot.export_compiled(fn1, (x,)))
    b1.save(path)
    b2 = aot.AotBundle("sig", "f32")
    b2.add_compiled("p2", aot.export_compiled(fn2, (x,)))
    b2.save(path)

    assert aot.AotBundle.load(path).keys() == {"p1", "p2"}


def test_companion_path():
    assert aot.companion_path("/d/model.zip") == "/d/model.aot.zip"
    assert aot.companion_path("/d/model") == "/d/model.aot.zip"


# ------------------------------------------------- artifact-level mismatches
@pytest.mark.parametrize("field,value,reason", [
    ("backend", "tpu-v9", "backend"),
    ("jaxlib", "0.0.0-stale", "jaxlib"),
    ("model_sig", "deadbeef" * 4, "model_sig"),
    ("precision", "int8", "precision"),
])
def test_open_bundle_rejects_mismatch(tmp_path, field, value, reason):
    """Each envelope gate rejects the WHOLE bundle with its own miss
    reason — the program inside is never offered for deserialization."""
    import jax

    env = aot._env_fingerprint()
    sig = "a" * 32
    kwargs = {"model_sig": sig, "precision": "f32", "env": env}
    b = aot.AotBundle(**kwargs)
    b.add_compiled("p", aot.export_compiled(
        jax.jit(lambda a: a + 1), (np.zeros(2, np.float32),)))
    # tamper ONE envelope field
    if field in ("backend", "jaxlib"):
        b2 = aot.AotBundle(sig, "f32", env=dict(env, **{field: value}))
    elif field == "model_sig":
        b2 = aot.AotBundle(value, "f32", env=env)
    else:
        b2 = aot.AotBundle(sig, value, env=env)
    b2._programs = dict(b._programs)
    path = str(tmp_path / "art.aot.zip")
    b2.save(path)

    m0 = _miss_count(reason)
    got, why = aot.open_bundle(path, sig, "f32")
    assert got is None and why == reason
    assert _miss_count(reason) == m0 + 1


def test_open_bundle_unknown_format_and_corrupt(tmp_path):
    fmt = str(tmp_path / "fmt.aot.zip")
    with zipfile.ZipFile(fmt, "w") as z:
        z.writestr("meta.json", json.dumps({"format": "someone-else/v9"}))
    m0 = _miss_count("format")
    got, why = aot.open_bundle(fmt, "s", "f32")
    assert got is None and why == "format"
    assert _miss_count("format") == m0 + 1

    bad = str(tmp_path / "bad.aot.zip")
    with open(bad, "wb") as f:
        f.write(b"not a zip at all")
    m0 = _miss_count("corrupt")
    got, why = aot.open_bundle(bad, "s", "f32")
    assert got is None and why == "corrupt"
    assert _miss_count("corrupt") == m0 + 1

    m0 = _miss_count("no_artifact")
    got, why = aot.open_bundle(str(tmp_path / "absent.zip"), "s", "f32")
    assert got is None and why == "no_artifact"
    assert _miss_count("no_artifact") == m0 + 1


def test_key_miss_counts_and_returns_none():
    b = aot.AotBundle("s", "f32")
    m0 = _miss_count("key")
    assert b.restore("never-added") is None
    assert _miss_count("key") == m0 + 1


# -------------------------------------------------------- engine-level path
def test_engine_restore_zero_compiles_bitwise(tmp_path):
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.replica import build_model

    art = str(tmp_path / "mlp.aot.zip")
    x = np.linspace(0.0, 1.0, 8, dtype=np.float32).reshape(2, 4)

    e1 = InferenceEngine(build_model("mlp"))
    e1.warmup((4,), max_batch=4, aot=art)      # trace-and-save
    assert e1.trace_count > 0
    want = np.asarray(e1.predict(x))

    e2 = InferenceEngine(build_model("mlp"))
    e2.warmup((4,), max_batch=4, aot=art)      # restore
    assert e2.trace_count == 0
    got = np.asarray(e2.predict(x))
    assert e2.trace_count == 0                 # serving didn't trace either
    assert np.array_equal(got, want)


def test_engine_stale_model_sig_falls_back_to_retrace(tmp_path):
    """An artifact built for a DIFFERENT architecture must be rejected at
    the envelope (miss{model_sig}) and the engine must retrace — never
    deserialize a stale program."""
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.replica import build_model

    art = str(tmp_path / "other.aot.zip")
    e1 = InferenceEngine(build_model("charlstm"))
    e1.warmup((8, 16), max_batch=2, aot=art)   # charlstm-signed artifact

    m0 = _miss_count("model_sig")
    e2 = InferenceEngine(build_model("mlp"))
    e2.warmup((4,), max_batch=2, aot=art)
    assert _miss_count("model_sig") == m0 + 1
    assert e2.trace_count > 0                  # retraced, fresh programs
    out = np.asarray(e2.predict(np.zeros((2, 4), np.float32)))
    assert out.shape == (2, 3)


def test_decode_restore_zero_compiles_token_identical(tmp_path):
    from deeplearning4j_tpu.serving.decode import DecodeEngine
    from deeplearning4j_tpu.serving.replica import build_model

    art = str(tmp_path / "lstm.aot.zip")
    net = build_model("charlstm")
    kw = dict(slots=2, max_len=32)

    d1 = DecodeEngine(net, **kw)
    d1.warmup(aot=art)
    assert d1.trace_count == 1
    d1.start()
    want = d1.generate([1, 2, 3], max_new_tokens=8, seed=3,
                       temperature=0.5, top_k=3)["tokens"]
    d1.stop()

    d2 = DecodeEngine(net, **kw)
    d2.warmup(aot=art)
    assert d2.trace_count == 0
    d2.start()
    got = d2.generate([1, 2, 3], max_new_tokens=8, seed=3,
                      temperature=0.5, top_k=3)["tokens"]
    d2.stop()
    assert got == want


# ------------------------------------------------------- checkpoint rotation
def test_rotation_unlinks_companion_and_latest_aot(tmp_path):
    from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager
    from deeplearning4j_tpu.serving.replica import build_model

    net = build_model("mlp")
    mgr = CheckpointManager(tmp_path, keep_last=2)
    paths = []
    for i in (1, 2):
        net.iteration = i
        paths.append(mgr.save(net))
    for p in paths:
        with open(aot.companion_path(p), "wb") as f:
            f.write(b"artifact-bytes")
    assert mgr.latest_aot() == aot.companion_path(paths[-1])

    net.iteration = 3
    mgr.save(net)                              # rotates iteration 1 away
    assert not os.path.exists(paths[0])
    assert not os.path.exists(aot.companion_path(paths[0]))
    assert os.path.exists(aot.companion_path(paths[1]))
