"""Pipeline parallelism tests (TPU-idiomatic extension; no reference
equivalent — oracle is the sequential application of the stages)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_forward, stack_stage_params, shard_stages, split_microbatches,
    PipelineParallel,
)

S, F = 4, 16


def _mesh():
    return Mesh(np.array(jax.devices()[:S]), ("pipe",))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


def _stages(seed=0):
    rs = np.random.RandomState(seed)
    return [{"W": jnp.asarray(rs.randn(F, F) / np.sqrt(F), jnp.float32),
             "b": jnp.asarray(rs.randn(F) * 0.1, jnp.float32)}
            for _ in range(S)]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


class TestPipelineForward:
    def test_matches_sequential(self):
        mesh = _mesh()
        stages = _stages()
        stacked = shard_stages(stack_stage_params(stages), mesh)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(8, 3, F), jnp.float32)   # 8 microbatches
        out = pipeline_forward(_stage_fn, stacked, x, mesh)
        want = _sequential(stages, x.reshape(24, F)).reshape(8, 3, F)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_fewer_microbatches_than_stages(self):
        mesh = _mesh()
        stages = _stages(2)
        stacked = shard_stages(stack_stage_params(stages), mesh)
        x = jnp.asarray(np.random.RandomState(2).randn(2, 5, F), jnp.float32)
        out = pipeline_forward(_stage_fn, stacked, x, mesh)
        want = _sequential(stages, x.reshape(10, F)).reshape(2, 5, F)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_sequential(self):
        """jax.grad through the schedule (shard_map + ppermute transpose)
        must equal the sequential model's gradients."""
        mesh = _mesh()
        stages = _stages(3)
        stacked_repl = stack_stage_params(stages)
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(4, 3, F), jnp.float32)
        tgt = jnp.asarray(rs.randn(12, F), jnp.float32)

        def loss_pp(params):
            out = pipeline_forward(_stage_fn, params, x, mesh)
            return jnp.mean((out.reshape(12, F) - tgt) ** 2)

        def loss_seq(params):
            y = x.reshape(12, F)
            for i in range(S):
                p = jax.tree_util.tree_map(lambda a: a[i], params)
                y = _stage_fn(p, y)
            return jnp.mean((y - tgt) ** 2)

        g_pp = jax.grad(loss_pp)(shard_stages(stacked_repl, mesh))
        g_seq = jax.grad(loss_seq)(stacked_repl)
        for k in ("W", "b"):
            np.testing.assert_allclose(np.asarray(g_pp[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-4, atol=1e-6, err_msg=k)


class TestPipelineTrainer:
    def test_trains(self):
        mesh = _mesh()
        pp = PipelineParallel(
            _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), _stages(5),
            mesh, learning_rate=0.2, num_microbatches=4)
        rs = np.random.RandomState(6)
        x = rs.randn(16, F).astype(np.float32)
        t = np.tanh(rs.randn(16, F)).astype(np.float32) * 0.5
        losses = [float(pp.fit_batch(x, t)) for _ in range(60)]
        assert losses[-1] < losses[0] * 0.5, losses[::10]
        out = pp.forward(x)
        assert out.shape == (16, F)

    def test_bad_microbatch_split(self):
        with pytest.raises(ValueError):
            split_microbatches(np.zeros((10, 3)), 4)


def test_device_side_preprocessor_matches_host_side():
    """uint8 batches + ImagePreProcessingScaler(device_side=True): the
    containers apply the transform on device after the copy; the result
    must equal host-side scaling exactly (fit(iterator) both ways)."""
    import numpy as np
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.data.normalizers import ImagePreProcessingScaler

    def net():
        conf = (NeuralNetConfiguration.builder().seed(3).list()
                .layer(DenseLayer(n_in=12, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(0)
    raw = rs.randint(0, 256, size=(64, 12)).astype(np.uint8)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 64)]

    n_dev = net()
    it = ListDataSetIterator(DataSet(raw, y), 16)
    it.set_pre_processor(ImagePreProcessingScaler(device_side=True))
    n_dev.fit(it, epochs=2)

    n_host = net()
    it2 = ListDataSetIterator(DataSet(raw, y), 16)
    it2.set_pre_processor(ImagePreProcessingScaler())   # host-side
    n_host.fit(it2, epochs=2)

    for pd, ph in zip(n_dev.params, n_host.params):
        for k in pd:
            np.testing.assert_allclose(np.asarray(pd[k]), np.asarray(ph[k]),
                                       rtol=1e-5, atol=1e-6, err_msg=k)


def test_device_side_standardize_handles_chunked_batches():
    """NormalizerStandardize(device_side=True) must work through the
    chunked fit path (stacked (S,B,F) blocks) and match host-side
    standardization."""
    import numpy as np
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.data import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.data.normalizers import NormalizerStandardize

    def net():
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(2)
    x = (rs.rand(64, 6) * 7 + 3).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 64)]

    norm_dev = NormalizerStandardize(device_side=True)
    norm_dev.fit(DataSet(x, y))
    n_dev = net()
    it = ListDataSetIterator(DataSet(x, y), 16)
    it.set_pre_processor(norm_dev)
    n_dev.fit(it, epochs=2)

    norm_host = NormalizerStandardize()
    norm_host.fit(DataSet(x, y))
    n_host = net()
    it2 = ListDataSetIterator(DataSet(x.copy(), y), 16)
    it2.set_pre_processor(norm_host)
    n_host.fit(it2, epochs=2)

    for pd, ph in zip(n_dev.params, n_host.params):
        for k in pd:
            np.testing.assert_allclose(np.asarray(pd[k]), np.asarray(ph[k]),
                                       rtol=1e-4, atol=1e-5, err_msg=k)
