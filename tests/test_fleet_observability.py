"""Fleet-wide observability (monitor/tracing + collect + slo, exec/programs).

The load-bearing claims pinned here:
- a TraceContext minted at the router rides the ``x-trace-context``
  header into subprocess replicas, and ``collect_fleet_trace`` merges
  the router's and every replica's ring buffer into ONE Perfetto doc
  with spans from >=4 processes reachable from one router trace_id —
  including both attempts of a hedged request and the winner's device
  spans;
- ``Tracer.export`` drops orphan ``E`` events after a ring wrap (an
  unbalanced ``E`` makes Perfetto mis-nest the whole track) while a
  still-open ``B`` is kept;
- argless spans are cached per name and a trace context never leaks
  into the cached args;
- compiled programs land in the XLA program registry with cost/memory
  analysis, served at ``GET /programs`` and exported as
  ``dl4jtpu_program_*`` gauges — without double-counting the callers'
  compile accounting (``_compile_count`` stays 1);
- the burn-rate SLO degrades ``/healthz`` only when BOTH windows burn
  fast, and recovers as soon as the short window clears (fake clock);
- ``POST /admin/profile`` wraps live traffic in a timed jax.profiler
  capture (one session at a time: 409 while running, 400 for junk);
- the metric catalog in docs/OBSERVABILITY.md matches the code exactly
  (tools/lint_metrics.py gates tier-1 through this file).
"""

import importlib.util
import json
import os
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.monitor import get_registry, trace
from deeplearning4j_tpu.monitor.collect import collect_fleet_trace, merge_docs
from deeplearning4j_tpu.monitor.slo import BurnRateSLO
from deeplearning4j_tpu.monitor.tracing import (TraceContext, Tracer,
                                                trace_context)
from deeplearning4j_tpu.exec.programs import get_programs
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (InferenceClient, InProcessReplica,
                                        ReplicaProcess, Router)

X = np.arange(12, dtype=np.float32).reshape(3, 4) / 10.0


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


# ----------------------------------------------------------- trace context

def test_trace_context_header_roundtrip():
    ctx = TraceContext("req-42")
    assert ctx.to_header() == "req-42"
    child = ctx.child("req-42#a1")
    assert child.trace_id == "req-42" and child.parent == "req-42#a1"
    back = TraceContext.from_header(child.to_header())
    assert back.trace_id == "req-42" and back.parent == "req-42#a1"
    assert TraceContext.from_header(None) is None
    assert TraceContext.from_header("") is None
    assert TraceContext.from_header("   ") is None
    # header without a parent half
    solo = TraceContext.from_header("req-7")
    assert solo.trace_id == "req-7" and solo.parent == ""


def test_span_records_context_and_wall_clock_timestamps():
    tr = Tracer(capacity=64, enabled=True)
    with trace_context(TraceContext("req-1", "req-1#a0")):
        with tr.span("work", n=3):
            pass
    b = tr.events()[0]
    assert b["args"]["trace_id"] == "req-1"
    assert b["args"]["parent"] == "req-1#a0"
    assert b["args"]["n"] == 3
    # timestamps are unix-epoch microseconds (mergeable across processes)
    assert abs(b["ts"] / 1e6 - time.time()) < 5.0


def test_argless_span_cached_and_context_never_leaks():
    tr = Tracer(capacity=64, enabled=True)
    s1 = tr.span("hot")
    s2 = tr.span("hot")
    assert s1 is s2                     # one allocation per name, ever
    with trace_context(TraceContext("req-9")):
        with s1:
            pass
    with s1:                            # same cached span, no context now
        pass
    evs = [e for e in tr.events() if e["ph"] == "B"]
    assert evs[0]["args"] == {"trace_id": "req-9"}
    assert "args" not in evs[1]         # the context did not stick


def test_export_drops_orphan_end_events_after_ring_wrap():
    tr = Tracer(capacity=6, enabled=True)
    with tr.span("outer"):
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
    # ring kept: E_s7, B_s8, E_s8, B_s9, E_s9, E_outer — the two E events
    # whose B fell off the ring must not survive export
    kept = [e for e in tr.export()["traceEvents"] if e["ph"] != "M"]
    assert [(e["ph"], e["name"]) for e in kept] == [
        ("B", "s8"), ("E", "s8"), ("B", "s9"), ("E", "s9")]


def test_export_keeps_unmatched_begin_of_open_span():
    tr = Tracer(capacity=16, enabled=True)
    span = tr.span("still-open")
    span.__enter__()                    # never exited: span is in flight
    kept = [e for e in tr.export()["traceEvents"] if e["ph"] != "M"]
    assert [(e["ph"], e["name"]) for e in kept] == [("B", "still-open")]


def test_merge_docs_dedups_metadata_and_rebases():
    a = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "router"}},
        {"ph": "B", "name": "route", "pid": 1, "tid": 1, "ts": 2000.0},
        {"ph": "E", "name": "route", "pid": 1, "tid": 1, "ts": 3000.0}]}
    b = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "router"}},          # duplicate: dropped
        {"ph": "B", "name": "device", "pid": 2, "tid": 1, "ts": 2500.0},
        {"ph": "E", "name": "device", "pid": 2, "tid": 1, "ts": 2600.0}]}
    doc = merge_docs([a, b])
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    evs = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    assert len(meta) == 1
    assert min(e["ts"] for e in evs) == 0.0    # rebased to t=0
    assert [e["name"] for e in evs] == ["route", "device", "device", "route"]


# ------------------------------------------------------- program registry

def _mln(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_fit_scan_registers_program_without_double_counting_compiles():
    net = _mln()
    rs = np.random.RandomState(0)
    k, b = 2, 128
    xs = rs.randn(k, b, 6).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (k, b))]
    net.fit_scan(xs, ys)
    rec = get_programs().get(net._prog_caller, f"fit_scan_k{k}_b{b}")
    assert rec is not None
    assert rec["flops"] and rec["flops"] > 0
    assert rec["memory_bytes"] and rec["memory_bytes"] > 0
    assert rec["compile_seconds"] and rec["compile_seconds"] > 0
    # the registration relower re-traces the scan body; the container's
    # compile accounting must not see it twice
    assert net._compile_count == 1
    net.fit_scan(xs, ys)                # warm call: still one program
    assert net._compile_count == 1
    # the registry exports per-program gauges
    text = get_registry().render()
    assert f'dl4jtpu_program_flops{{caller="{net._prog_caller}"' in text


@pytest.fixture(scope="module")
def mlp_replica():
    rep = InProcessReplica(model="mlp").start()
    yield rep
    rep.stop()


def test_engine_programs_served_over_http(mlp_replica):
    cli = InferenceClient(mlp_replica.url)
    try:
        cli.predict(X)                  # compiles (or reuses) one bucket
    finally:
        cli.close()
    engine_id = mlp_replica.srv.engine.id
    mine = [p for p in get_programs().entries()
            if p["caller"] == engine_id]
    assert mine, "engine compile did not register any program"
    assert any(p["key"].startswith("b") for p in mine)
    st, body = _get_json(f"{mlp_replica.url}/programs")
    assert st == 200
    served = [p for p in body["programs"] if p["caller"] == engine_id]
    assert {p["key"] for p in served} == {p["key"] for p in mine}
    assert all(set(p) >= {"caller", "key", "flops", "bytes",
                          "memory_bytes", "compile_seconds"}
               for p in served)


# ------------------------------------------------------------- SLO engine

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_burn_rate_slo_state_machine_under_fake_clock():
    clk = _Clock()
    counts = {"bad": 0.0, "total": 0.0}
    slo = BurnRateSLO("t", lambda: counts["bad"], lambda: counts["total"],
                      objective=0.99, short_s=300.0, long_s=3600.0,
                      min_events=20, clock=clk, min_tick_s=0.0)
    st = slo.evaluate()                           # idle process
    assert not st.fast_burn and st.budget_remaining == 1.0

    # min_events guard: 5 failures in an idle process must not page
    counts["bad"] += 5
    counts["total"] += 5
    clk.t = 30.0
    st = slo.evaluate()
    assert st.burn_short == 0.0 and not st.fast_burn

    # slow burn: ~5% errors against a 1% budget is visible but not fast
    counts["total"] += 100
    clk.t = 60.0
    st = slo.evaluate()
    assert 0.0 < st.burn_short < slo.fast_threshold
    assert not st.fast_burn

    # storm: error rate >> budget in BOTH windows -> degraded
    counts["bad"] += 80
    counts["total"] += 100
    clk.t = 120.0
    st = slo.evaluate()
    assert st.burn_short > slo.fast_threshold
    assert st.burn_long > slo.fast_threshold
    assert st.fast_burn
    assert st.budget_remaining == 0.0

    # recovery: healthy traffic clears the 5m window while the 1h window
    # is still digesting the storm — the AND rule re-admits immediately
    counts["total"] += 30
    clk.t = 200.0
    slo.tick()
    counts["total"] += 30
    clk.t = 380.0
    slo.tick()
    clk.t = 430.0
    st = slo.evaluate()
    assert st.burn_short == 0.0
    assert st.burn_long > slo.fast_threshold      # long window still hot
    assert not st.fast_burn
    d = st.as_dict()
    assert d["fast_burn"] is False and d["name"] == "t"
    # the state is exported as gauges
    text = get_registry().render()
    assert 'dl4jtpu_slo_burn_rate{slo="t",window="short"}' in text
    assert 'dl4jtpu_slo_budget_remaining{slo="t"}' in text


def test_healthz_degrades_on_fast_burn_and_recovers(mlp_replica):
    srv = mlp_replica.srv
    st, body = _get_json(f"{mlp_replica.url}/healthz")
    assert st == 200 and body == {"status": "ok"}

    clk = _Clock()
    counts = {"bad": 0.0, "total": 0.0}
    orig = srv.slo
    srv.slo = BurnRateSLO(f"availability:{srv.id}",
                          lambda: counts["bad"], lambda: counts["total"],
                          objective=0.99, clock=clk, min_tick_s=0.0)
    try:
        srv.slo.evaluate()                        # baseline snapshot at t=0
        counts["bad"] += 60
        counts["total"] += 100
        clk.t = 60.0
        st, body = _get_json(f"{mlp_replica.url}/healthz")
        assert st == 200                          # degraded, not draining
        assert body["status"] == "degraded"
        assert body["reason"] == "slo_fast_burn"
        assert body["slo"]["fast_burn"] is True
        assert body["slo"]["name"] == f"availability:{srv.id}"
        # short window clears -> healthy again, byte-identical body
        counts["total"] += 40
        clk.t = 200.0
        srv.slo.tick()
        clk.t = 430.0
        st, body = _get_json(f"{mlp_replica.url}/healthz")
        assert st == 200 and body == {"status": "ok"}
    finally:
        srv.slo = orig


# ------------------------------------------------------ on-demand profiling

def test_admin_profile_wraps_live_traffic(mlp_replica, tmp_path):
    from deeplearning4j_tpu.monitor import profiling

    def post(payload):
        c = InferenceClient(mlp_replica.url, retries=1)
        try:
            return c.post_raw("/admin/profile", json.dumps(payload).encode())
        finally:
            c.close()

    # junk is rejected before any profiler state is touched
    st, body, _ = post({})                        # no dir
    assert st == 400, body
    st, body, _ = post({"dir": str(tmp_path / "p"), "seconds": -1})
    assert st == 400, body

    out = str(tmp_path / "capture")
    st, body, _ = post({"dir": out, "seconds": 0.4})
    assert st == 200, body
    assert json.loads(body)["profiling"] == out
    # one session at a time per process
    st, body, _ = post({"dir": out, "seconds": 0.4})
    assert st == 409
    assert json.loads(body)["error"]["type"] == "profile_busy"
    # live traffic lands inside the capture window
    cli = InferenceClient(mlp_replica.url)
    try:
        cli.predict(X)
    finally:
        cli.close()
    deadline = time.monotonic() + 15.0
    while profiling.profile_status()["profiling"]:
        assert time.monotonic() < deadline, "profile session never stopped"
        time.sleep(0.05)
    captured = [os.path.join(r, f)
                for r, _, fs in os.walk(out) for f in fs]
    assert captured, "jax.profiler wrote nothing"


# ----------------------------------------------------------- metric catalog

def test_metric_catalog_matches_code():
    """tools/lint_metrics.py gates tier-1 from here: every dl4jtpu_*
    literal in the package has a docs/OBSERVABILITY.md catalog row and
    vice versa."""
    path = Path(__file__).resolve().parent.parent / "tools" / "lint_metrics.py"
    spec = importlib.util.spec_from_file_location("lint_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    problems = mod.lint()
    assert problems == [], "\n".join(problems)
    assert len(mod.code_metrics()) > 50           # the scan actually scanned


# ------------------------------------------------------- fleet trace merge

def test_fleet_trace_merges_router_and_replica_spans(tmp_path):
    """3 subprocess replicas + the in-process router under a hedged storm:
    the collected doc has spans from >=4 processes, and one router-minted
    trace_id reaches hedged attempt spans AND the winning replica's
    device spans (the ISSUE's fleet-trace acceptance bar)."""
    reps = [ReplicaProcess(str(tmp_path), model="mlp", trace=True,
                           name=f"replica{i}").start()
            for i in range(3)]
    router = None
    cli = None
    try:
        for r in reps:
            r.wait_ready()
        trace.enable(True)
        trace.clear()
        trace.set_process_name("router")
        router = Router([r.url for r in reps], port=0, probe_interval=None,
                        hedge=True, hedge_delay_ms=40.0,
                        upstream_timeout=60.0).start()
        base = f"http://127.0.0.1:{router.port}"
        cli = InferenceClient(base, timeout=60.0)

        # one slow replica: round-robin lands ~1/3 of primaries on it, the
        # 40 ms hedge fires and the fast copy wins
        c = InferenceClient(reps[0].url, retries=1)
        try:
            st, body, _ = c.post_raw(
                "/chaos", json.dumps({"latency_ms": 1500.0}).encode())
            assert st == 200, body
        finally:
            c.close()
        for _ in range(9):
            cli.predict(X)
        time.sleep(0.3)                 # let in-flight E events land

        doc = collect_fleet_trace(base, path=str(tmp_path / "fleet.json"))
        assert len(doc["collectedFrom"]) == 4     # router + 3 replicas
        evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        pids = {e["pid"] for e in evs}
        assert len(pids) >= 4                     # spans from >=4 processes

        # every process announces a swimlane name
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert "router" in names
        assert sum(1 for n in names if n.startswith("replica:mlp@")) == 3

        router_pid = os.getpid()
        attempts = {}                             # trace_id -> {rid, ...}
        for e in evs:
            if e.get("name") == "attempt" and e["ph"] == "B":
                a = e.get("args", {})
                if "trace_id" in a and "rid" in a:
                    attempts.setdefault(a["trace_id"], set()).add(a["rid"])
        assert attempts, "router recorded no attempt spans"
        hedged = {tid: rids for tid, rids in attempts.items()
                  if len(rids) >= 2}
        assert hedged, "no request was hedged — both attempt spans missing"
        tid, rids = next(iter(sorted(hedged.items())))
        assert any(r.endswith("#a0") for r in rids)
        assert any(r.endswith("#a1") for r in rids)

        # the winner's whole replica-side chain carries the same trace_id
        replica_spans = [e for e in evs
                         if e["pid"] != router_pid
                         and e.get("args", {}).get("trace_id") == tid]
        assert replica_spans, f"trace {tid} never reached a replica"
        replica_names = {e["name"] for e in replica_spans}
        assert "http_request" in replica_names
        assert "device" in replica_names          # engine spans joined in

        # the exported file is a loadable Chrome trace-event doc
        with open(tmp_path / "fleet.json") as f:
            on_disk = json.load(f)
        assert on_disk["traceEvents"]
    finally:
        trace.enable(False)
        trace.clear()
        trace.set_process_name("")
        if cli is not None:
            cli.close()
        if router is not None:
            router.stop()
        for r in reps:
            r.stop()
