"""CJK dictionary segmenter behind the TokenizerFactory SPI.

Parity role: the reference's deeplearning4j-nlp-{chinese,japanese,korean}
modules plug dictionary segmenters into the same TokenizerFactory seam the
whitespace tokenizer uses; these tests prove the seam with a real
(bidirectional maximal-matching) segmenter on bundled CJK fixtures — the
segmenter produces WORDS, Word2Vec consumes them unchanged.
"""

import numpy as np

from deeplearning4j_tpu.nlp.segmenters import (
    DictionarySegmenterTokenizerFactory, MaxMatchSegmenter,
    load_bundled_lexicon)


def test_zh_segments_real_words():
    f = DictionarySegmenterTokenizerFactory("zh")
    assert f.create("我们喜欢使用机器学习和自然语言处理").get_tokens() == [
        "我们", "喜欢", "使用", "机器学习", "和", "自然语言处理"]
    # longest match wins: 机器学习 beats 机器+学习
    assert "机器学习" in f.create("机器学习模型").get_tokens()


def test_ja_segments_real_words():
    f = DictionarySegmenterTokenizerFactory("ja")
    assert f.create("私は機械学習が好きです").get_tokens() == [
        "私", "は", "機械学習", "が", "好き", "です"]


def test_mixed_script_keeps_whitespace_semantics():
    f = DictionarySegmenterTokenizerFactory("zh")
    assert f.create("深度学习模型在TPU hardware上训练").get_tokens() == [
        "深度学习", "模型", "在", "TPU", "hardware", "上", "训练"]


def test_oov_falls_back_to_single_chars():
    seg = MaxMatchSegmenter(["机器学习"])
    assert seg.segment("机器学习硬件") == ["机器学习", "硬", "件"]


def test_bidirectional_disambiguation_prefers_fewer_words():
    # forward greedy over 研究生命 with this lexicon yields 研究生+命 (2);
    # backward yields 研究+生命 (2) — tie, equal singles → backward, the
    # linguistically right split here
    seg = MaxMatchSegmenter(["研究", "研究生", "生命"])
    assert seg.segment("研究生命") == ["研究", "生命"]


def test_custom_lexicon_is_swappable():
    seg = DictionarySegmenterTokenizerFactory(lexicon=["深度", "学习"])
    assert seg.create("深度学习").get_tokens() == ["深度", "学习"]


def test_spi_feeds_word2vec_with_real_words():
    """The extension point demonstrated end-to-end: Word2Vec trained through
    the segmenter factory builds its vocab from segmented WORDS."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents = ["我们喜欢机器学习",
             "我们研究自然语言处理",
             "机器学习模型训练数据",
             "自然语言处理使用词向量"] * 12
    w2v = Word2Vec(min_word_frequency=5, layer_size=16, window_size=2,
                   epochs=1, negative=2, seed=3, subsampling=0,
                   sentences=sents,
                   tokenizer_factory=DictionarySegmenterTokenizerFactory("zh"))
    w2v.build_vocab()
    vocab = set(w2v.vocab.words())
    assert {"机器学习", "我们", "自然语言处理", "训练"} <= vocab
    assert not any(len(w) == 1 for w in vocab)   # words, not characters
    w2v.fit()
    assert np.isfinite(np.asarray(w2v.syn0)).all()


def test_bundled_lexicons_load():
    for lang in ("zh", "ja"):
        words = load_bundled_lexicon(lang)
        assert len(words) > 50
        assert all(" " not in w for w in words)
