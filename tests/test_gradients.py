"""Numeric gradient checks — the correctness backbone.

Parity role: reference gradientcheck/ suites (CNNGradientCheckTest,
LSTMGradientCheckTests, BNGradientCheckTest, VaeGradientCheckTests,
LossFunctionGradientCheck, GradientCheckTestsMasking — SURVEY.md §4).
Analytic jax.grad vs central finite differences in float64.
"""

import numpy as np
import pytest
import jax

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, ConvolutionLayer, SubsamplingLayer,
    BatchNormalization, LSTM, GravesLSTM, SimpleRnn, RnnOutputLayer,
    EmbeddingLayer, GlobalPoolingLayer, Bidirectional, AutoEncoder,
    VariationalAutoencoder, LossLayer,
)
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.util.gradient_check import gradient_check_network


def _check(conf, x, y, max_checks=12, tol=1e-3):
    net = MultiLayerNetwork(conf).init(jax.random.PRNGKey(7))
    fails, checked, worst = gradient_check_network(
        net, np.asarray(x), np.asarray(y), max_checks_per_array=max_checks,
        max_rel_error=tol)
    assert fails == 0, f"{fails}/{checked} gradient checks failed (worst rel {worst:.2e})"
    assert checked > 0


def _builder(act="tanh"):
    return (NeuralNetConfiguration.builder().seed(12).updater(Sgd(0.1))
            .activation(act).weight_init("xavier"))


def test_dense_mlp_gradients():
    rng = np.random.RandomState(0)
    x = rng.randn(5, 4)
    y = np.eye(3)[rng.randint(0, 3, 5)]
    conf = (_builder().list()
            .layer(DenseLayer(n_out=6))
            .layer(DenseLayer(n_out=5, activation="sigmoid"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    _check(conf, x, y)


def test_dense_l1_l2_gradients():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 4)
    y = np.eye(3)[rng.randint(0, 3, 4)]
    conf = (_builder().l1(0.01).l2(0.02).list()
            .layer(DenseLayer(n_out=6))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    _check(conf, x, y)


@pytest.mark.parametrize("loss,out_act,ydist", [
    ("mse", "identity", "real"),
    ("l1", "identity", "real"),
    ("xent", "sigmoid", "binary"),
    ("mcxent", "softmax", "onehot"),
    ("hinge", "identity", "pm1"),
    ("poisson", "softplus", "count"),
    ("kl_divergence", "softmax", "simplex"),
])
def test_loss_function_gradients(loss, out_act, ydist):
    rng = np.random.RandomState(3)
    x = rng.randn(4, 3)
    if ydist == "real":
        y = rng.randn(4, 2)
    elif ydist == "binary":
        y = rng.randint(0, 2, (4, 2)).astype(float)
    elif ydist == "onehot":
        y = np.eye(2)[rng.randint(0, 2, 4)]
    elif ydist == "pm1":
        y = rng.choice([-1.0, 1.0], (4, 2))
    elif ydist == "count":
        y = rng.randint(0, 5, (4, 2)).astype(float)
    else:
        y = rng.dirichlet(np.ones(2), 4)
    conf = (_builder().list()
            .layer(DenseLayer(n_out=5))
            .layer(OutputLayer(n_out=2, activation=out_act, loss=loss))
            .set_input_type(InputType.feed_forward(3)).build())
    _check(conf, x, y)


def test_cnn_gradients():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 8, 8, 2)
    y = np.eye(3)[rng.randint(0, 3, 3)]
    conf = (_builder("relu").list()
            .layer(ConvolutionLayer(n_out=3, kernel_size=3, activation="tanh"))
            .layer(SubsamplingLayer(pooling_type="avg", kernel_size=2, stride=2))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(8, 8, 2)).build())
    _check(conf, x, y)


def test_batchnorm_gradients():
    # BN in train mode uses batch stats; check grads through them
    rng = np.random.RandomState(5)
    x = rng.randn(6, 4)
    y = np.eye(2)[rng.randint(0, 2, 6)]
    conf = (_builder().list()
            .layer(DenseLayer(n_out=5))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    _check(conf, x, y)


def test_lstm_gradients():
    rng = np.random.RandomState(6)
    x = rng.randn(3, 5, 4)
    y = np.eye(2)[rng.randint(0, 2, (3, 5))]
    conf = (_builder().list()
            .layer(LSTM(n_out=6))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())
    _check(conf, x, y)


def test_graves_lstm_gradients():
    rng = np.random.RandomState(7)
    x = rng.randn(2, 4, 3)
    y = np.eye(2)[rng.randint(0, 2, (2, 4))]
    conf = (_builder().list()
            .layer(GravesLSTM(n_out=5))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3)).build())
    _check(conf, x, y)


def test_bidirectional_gradients():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 4, 3)
    y = np.eye(2)[rng.randint(0, 2, (2, 4))]
    conf = (_builder().list()
            .layer(Bidirectional(fwd=LSTM(n_out=4), mode="concat"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(3)).build())
    _check(conf, x, y)


def test_simple_rnn_global_pooling_gradients():
    rng = np.random.RandomState(9)
    x = rng.randn(3, 5, 4)
    y = np.eye(3)[rng.randint(0, 3, 3)]
    conf = (_builder().list()
            .layer(SimpleRnn(n_out=5))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())
    _check(conf, x, y)


def test_masking_gradients():
    """RNN loss with a labels mask (parity: GradientCheckTestsMasking)."""
    rng = np.random.RandomState(10)
    x = rng.randn(3, 5, 4)
    y = np.eye(2)[rng.randint(0, 2, (3, 5))]
    mask = np.ones((3, 5))
    mask[0, 3:] = 0
    mask[2, 1:] = 0
    net = MultiLayerNetwork((_builder().list()
            .layer(LSTM(n_out=4))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())).init(jax.random.PRNGKey(3))
    import jax.numpy as jnp
    # NOTE: no f64 cast here — outside the checker's scoped x64 it would
    # silently truncate to f32 (with a warning); gradient_check_fn upcasts
    # float leaves inside the x64 scope and asserts they really are f64

    def loss_fn(params):
        loss, _ = net._loss(params, net.state, jnp.asarray(x), jnp.asarray(y),
                            None, jnp.asarray(mask), jnp.asarray(mask))
        return loss

    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn
    fails, checked, worst = gradient_check_fn(loss_fn, net.params,
                                              max_checks_per_array=10)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"


def test_vae_gradients():
    """VAE -ELBO gradients without sampling noise (deterministic eps=0 path —
    parity: VaeGradientCheckTests uses fixed seeds similarly)."""
    rng = np.random.RandomState(11)
    x = (rng.rand(4, 6) > 0.5).astype(float)
    vae = VariationalAutoencoder(n_in=6, n_out=3, encoder_layer_sizes=(8,),
                                 decoder_layer_sizes=(8,), activation="tanh",
                                 weight_init="xavier")
    params = vae.init(jax.random.PRNGKey(0))
    import jax.numpy as jnp


    def loss_fn(p):
        return vae.compute_score(p, jnp.asarray(x), train=False, rng=None)

    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn
    fails, checked, worst = gradient_check_fn(loss_fn, params,
                                              max_checks_per_array=8)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"


def test_autoencoder_gradients():
    rng = np.random.RandomState(12)
    x = rng.rand(4, 5)
    ae = AutoEncoder(n_in=5, n_out=3, activation="sigmoid",
                     weight_init="xavier", corruption_level=0.0)
    params = ae.init(jax.random.PRNGKey(1))
    import jax.numpy as jnp


    def loss_fn(p):
        return ae.compute_score(p, jnp.asarray(x), train=False, rng=None)

    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn
    fails, checked, worst = gradient_check_fn(loss_fn, params,
                                              max_checks_per_array=10)
    assert fails == 0, f"{fails}/{checked} failed (worst {worst:.2e})"


def test_embedding_gradients():
    rng = np.random.RandomState(13)
    x = rng.randint(0, 7, (6, 1)).astype(np.float64)
    y = np.eye(3)[rng.randint(0, 3, 6)]
    conf = (_builder().list()
            .layer(EmbeddingLayer(n_in=7, n_out=4))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(7)).build())
    _check(conf, x, y)


def test_rnn_gradient_check_f32_inputs():
    """gradient_check_fn upcasts params to f64 internally while the closure
    feeds f32 activations — recurrent scan carries must follow the promoted
    dtype instead of x.dtype (regression: carry type mismatch crash)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.util.gradient_check import gradient_check_fn

    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, 3).astype(np.float32)          # f32 on purpose
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, (2, 5))]
    for layer in (SimpleRnn(n_out=5), LSTM(n_out=5)):
        net = MultiLayerNetwork((_builder().list()
                .layer(layer)
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(3)).build())).init()

        def loss_fn(params):
            loss, _ = net._loss(params, net.state, jnp.asarray(x),
                                jnp.asarray(y), None, None, None)
            return loss

        fails, checked, _ = gradient_check_fn(loss_fn, net.params,
                                              max_checks_per_array=6)
        assert fails == 0 and checked > 0
