"""Zoo model instantiation + forward tests
(parity role: deeplearning4j-zoo TestInstantiation, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    LeNet, SimpleCNN, AlexNet, VGG16, VGG19, Darknet19, TextGenerationLSTM,
    ResNet50, GoogLeNet, InceptionResNetV1, FaceNetNN4Small2,
)


def _fwd_check(model, shape, n_classes):
    net = model.init()
    x = np.random.RandomState(0).rand(2, *shape).astype(np.float32)
    out = net.output(x)
    if isinstance(out, list):
        out = out[0]
    assert out.shape == (2, n_classes)
    assert np.all(np.isfinite(np.asarray(out)))
    return net


def test_lenet():
    net = _fwd_check(LeNet(num_classes=10), (28, 28, 1), 10)
    y = np.eye(10, dtype=np.float32)[np.random.randint(0, 10, 2)]
    x = np.random.rand(2, 28, 28, 1).astype(np.float32)
    net.fit(x, y)
    assert np.isfinite(net.get_score())


def test_simplecnn():
    _fwd_check(SimpleCNN(num_classes=5, input_shape=(48, 48, 3)), (48, 48, 3), 5)


@pytest.mark.slow
def test_alexnet_small():
    # 224 is the reference default; use it (one forward, batch 2)
    _fwd_check(AlexNet(num_classes=7), (224, 224, 3), 7)


@pytest.mark.slow
def test_vgg16_small_input():
    _fwd_check(VGG16(num_classes=10, input_shape=(32, 32, 3)), (32, 32, 3), 10)


def test_vgg19_constructs():
    conf = VGG19(num_classes=10, input_shape=(32, 32, 3)).conf()
    assert len(conf.layers) == 24  # 16 conv + 5 pool + 3 dense/out


@pytest.mark.slow
def test_darknet19_small():
    _fwd_check(Darknet19(num_classes=10, input_shape=(64, 64, 3)), (64, 64, 3), 10)


def test_textgen_lstm():
    m = TextGenerationLSTM(total_unique_characters=30)
    net = m.init()
    x = np.random.rand(2, 6, 30).astype(np.float32)
    out = net.output(x)
    assert out.shape == (2, 6, 30)


@pytest.mark.slow
def test_resnet50():
    net = _fwd_check(ResNet50(num_classes=10, input_shape=(64, 64, 3)),
                     (64, 64, 3), 10)
    assert net.num_params() > 23_000_000  # ~23.6M + fc


@pytest.mark.slow
def test_googlenet():
    _fwd_check(GoogLeNet(num_classes=10, input_shape=(64, 64, 3)), (64, 64, 3), 10)


@pytest.mark.slow
def test_inception_resnet_v1():
    _fwd_check(InceptionResNetV1(num_classes=10, input_shape=(96, 96, 3)),
               (96, 96, 3), 10)


@pytest.mark.slow
def test_facenet():
    _fwd_check(FaceNetNN4Small2(num_classes=10), (96, 96, 3), 10)


def test_tiny_transformer_learns_and_uses_flash_kernel():
    """TinyTransformer (TPU-first extension): causal pre-LN attention blocks
    learn a cyclic sequence; with helpers forced on (interpret mode) the MHA
    layers route through the flash-attention kernel and produce the same
    predictions."""
    import jax
    from deeplearning4j_tpu import ops
    from deeplearning4j_tpu.zoo import TinyTransformer

    V, T, B = 12, 16, 4
    m = TinyTransformer(vocab_size=V, n_layers=1, d_model=32, n_heads=4,
                        seed=3).init()
    ids = np.tile(np.arange(V), 4)[None].repeat(B, 0)[:, :T + 1]
    x = np.eye(V, dtype=np.float32)[ids[:, :-1]]
    y = np.eye(V, dtype=np.float32)[ids[:, 1:]]
    for _ in range(80):
        m.fit(x, y)
    assert m.get_score() < 0.8, m.get_score()
    out_ref = np.asarray(m.output(x[:1]))
    assert (out_ref.argmax(-1) == ids[:1, 1:]).mean() > 0.9

    ops.set_helpers_enabled(True, interpret=True)
    try:
        from deeplearning4j_tpu.ops.flash_attention import supported
        assert supported(T, 32 // 4)       # the kernel actually engages
        m._output_fn = None        # drop the helpers-off jit cache so the
        out_flash = np.asarray(m.output(x[:1]))   # flash path is retraced
    finally:
        ops.set_helpers_enabled(None)
    np.testing.assert_allclose(out_flash, out_ref, rtol=1e-4, atol=1e-5)


def test_tiny_transformer_is_order_sensitive():
    """Positional embedding makes predictions depend on token ORDER, not
    just the prefix multiset (attention alone is permutation-invariant)."""
    from deeplearning4j_tpu.zoo import TinyTransformer
    V = 8
    m = TinyTransformer(vocab_size=V, n_layers=1, d_model=16, n_heads=2,
                        seed=5).init()
    ab = np.eye(V, dtype=np.float32)[[[0, 1, 2]]]
    ba = np.eye(V, dtype=np.float32)[[[1, 0, 2]]]
    out_ab = np.asarray(m.output(ab))[0, -1]
    out_ba = np.asarray(m.output(ba))[0, -1]
    assert not np.allclose(out_ab, out_ba, atol=1e-5), \
        "same prediction for permuted prefix — no positional signal"


def test_pretrained_checksum_verification(tmp_path, monkeypatch):
    """init_pretrained verifies the cache against the SHA-256 manifest:
    intact file loads, corrupted file raises (parity: ZooModel.initPretrained
    checksum verify — the air gap removes the download, not the check)."""
    import json
    import numpy as np
    from deeplearning4j_tpu.zoo.simple import LeNet
    from deeplearning4j_tpu.zoo.zoo_model import ZooModel
    from deeplearning4j_tpu.util.model_serializer import write_model

    monkeypatch.setenv("DL4JTPU_DATA_DIR", str(tmp_path))
    model = LeNet(num_classes=10, input_shape=(28, 28, 1))
    net = model.init()
    p = model.cache_path()   # the WRITE target — never the bundled artifact
    p.parent.mkdir(parents=True, exist_ok=True)
    write_model(net, str(p))
    ZooModel.write_manifest_entry(model.name, p)

    loaded = model.init_pretrained()          # intact: loads fine
    x = np.random.RandomState(0).rand(2, 28, 28, 1).astype(np.float32)
    np.testing.assert_allclose(np.asarray(loaded.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)

    p.write_bytes(p.read_bytes()[:-7] + b"garbage")   # corrupt the cache
    with pytest.raises(IOError, match="Checksum mismatch"):
        model.init_pretrained()


@pytest.mark.slow
def test_zoo_bf16_inference_output():
    """compute_dtype='bfloat16' must work for INFERENCE too: eval-mode BN
    normalizes with f32 running stats against bf16 activations (was: mixed
    dtype promotion crashed the following conv)."""
    import numpy as np
    from deeplearning4j_tpu.zoo.resnet import ResNet50
    cg = ResNet50(num_classes=10, input_shape=(32, 32, 3), seed=7,
                  compute_dtype="bfloat16").init()
    x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
    out = np.asarray(cg.output(x))
    assert out.shape == (4, 10)
    assert np.all(np.isfinite(out))
