"""Zoo model instantiation + forward tests
(parity role: deeplearning4j-zoo TestInstantiation, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    LeNet, SimpleCNN, AlexNet, VGG16, VGG19, Darknet19, TextGenerationLSTM,
    ResNet50, GoogLeNet, InceptionResNetV1, FaceNetNN4Small2,
)


def _fwd_check(model, shape, n_classes):
    net = model.init()
    x = np.random.RandomState(0).rand(2, *shape).astype(np.float32)
    out = net.output(x)
    if isinstance(out, list):
        out = out[0]
    assert out.shape == (2, n_classes)
    assert np.all(np.isfinite(np.asarray(out)))
    return net


def test_lenet():
    net = _fwd_check(LeNet(num_classes=10), (28, 28, 1), 10)
    y = np.eye(10, dtype=np.float32)[np.random.randint(0, 10, 2)]
    x = np.random.rand(2, 28, 28, 1).astype(np.float32)
    net.fit(x, y)
    assert np.isfinite(net.get_score())


def test_simplecnn():
    _fwd_check(SimpleCNN(num_classes=5, input_shape=(48, 48, 3)), (48, 48, 3), 5)


def test_alexnet_small():
    # 224 is the reference default; use it (one forward, batch 2)
    _fwd_check(AlexNet(num_classes=7), (224, 224, 3), 7)


def test_vgg16_small_input():
    _fwd_check(VGG16(num_classes=10, input_shape=(32, 32, 3)), (32, 32, 3), 10)


def test_vgg19_constructs():
    conf = VGG19(num_classes=10, input_shape=(32, 32, 3)).conf()
    assert len(conf.layers) == 24  # 16 conv + 5 pool + 3 dense/out


def test_darknet19_small():
    _fwd_check(Darknet19(num_classes=10, input_shape=(64, 64, 3)), (64, 64, 3), 10)


def test_textgen_lstm():
    m = TextGenerationLSTM(total_unique_characters=30)
    net = m.init()
    x = np.random.rand(2, 6, 30).astype(np.float32)
    out = net.output(x)
    assert out.shape == (2, 6, 30)


def test_resnet50():
    net = _fwd_check(ResNet50(num_classes=10, input_shape=(64, 64, 3)),
                     (64, 64, 3), 10)
    assert net.num_params() > 23_000_000  # ~23.6M + fc


def test_googlenet():
    _fwd_check(GoogLeNet(num_classes=10, input_shape=(64, 64, 3)), (64, 64, 3), 10)


@pytest.mark.slow
def test_inception_resnet_v1():
    _fwd_check(InceptionResNetV1(num_classes=10, input_shape=(96, 96, 3)),
               (96, 96, 3), 10)


def test_facenet():
    _fwd_check(FaceNetNN4Small2(num_classes=10), (96, 96, 3), 10)
