"""Persistence-format regression tests — pinned golden model zips.

Parity role: the reference's regressiontest suite (deeplearning4j-core
src/test regressiontest/RegressionTest050/060/071/080.java loads model zips
written by old releases from src/test/resources to pin the ModelSerializer
format). tests/resources/golden_*_v1.zip were written by the v1 format
(conf JSON + params npz + updater state + normalizer); any
backwards-incompatible serializer change breaks these tests instead of
silently orphaning users' checkpoints.
"""

import json
from pathlib import Path

import numpy as np

RES = Path(__file__).with_name("resources")


def _expected():
    return json.loads((RES / "golden_expected_v1.json").read_text())


class TestGoldenFormat:
    def test_mln_zip_loads_and_reproduces_outputs(self):
        from deeplearning4j_tpu.util.model_serializer import guess_model
        exp = _expected()
        net = guess_model(str(RES / "golden_mln_v1.zip"))
        out = np.asarray(net.output(np.asarray(exp["x_img"], np.float32)))
        # rtol guards the FORMAT (breakage gives O(1) errors); small slack
        # absorbs XLA reduction-order noise across CPU thread partitions
        np.testing.assert_allclose(out, np.asarray(exp["mln_out"]),
                                   rtol=5e-3, atol=1e-5)
        # updater state must round-trip too (it was one Adam step deep)
        import jax
        assert any(
            leaf.size for leaf in jax.tree_util.tree_leaves(net.opt_state)
            if hasattr(leaf, "size"))

    def test_cg_zip_loads_and_reproduces_outputs(self):
        from deeplearning4j_tpu.util.model_serializer import guess_model
        exp = _expected()
        cg = guess_model(str(RES / "golden_cg_v1.zip"))
        out = np.asarray(cg.output(np.asarray(exp["x_seq"], np.float32)))
        np.testing.assert_allclose(out, np.asarray(exp["cg_out"]),
                                   rtol=5e-3, atol=1e-5)

    def test_loaded_mln_continues_training(self):
        """A restored checkpoint must be trainable (conf + params + updater
        state all intact), not just callable."""
        from deeplearning4j_tpu.util.model_serializer import guess_model
        exp = _expected()
        net = guess_model(str(RES / "golden_mln_v1.zip"))
        x = np.asarray(exp["x_img"], np.float32)
        n_classes = len(exp["mln_out"][0])
        rs = np.random.RandomState(0)
        y = np.eye(n_classes, dtype=np.float32)[
            rs.randint(0, n_classes, len(x))]
        net.fit(x, y)
        assert np.isfinite(net.get_score())
