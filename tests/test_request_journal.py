"""Request-lifecycle observability (monitor/reqlog + SLO histograms).

The load-bearing claims pinned here:
- the wide-event ring is bounded: oldest record dropped first, ``total``
  keeps counting so ``dropped = total - len`` stays visible;
- every rejection path — batcher queue-full (429), stopped (503),
  deadline (504), decode queue-full (429) — leaves EXACTLY ONE terminal
  journal record with the right outcome;
- the InferenceServer mints ``x-request-id`` when the client sent none,
  echoes it in the response header, and the journal record joins on it;
- a concurrent /generate storm honors the ring bound and every kept
  record's phase durations (queue/prefill/decode) are non-negative and
  sum to the record's wall, which never exceeds the client's wall;
- /predict wide events carry queue/bucket/pad/device/readback phase
  attribution and the tenant/priority identity headers;
- fleet merge (the ISSUE-18 acceptance bar): a 3-replica router
  /generate storm collected with ``collect_requests`` yields one merged
  entry per request with the router's annotation joined by base rid,
  and a replica's worst ITL bucket exemplar resolves to a concrete
  journal record;
- ``tools/tail_requests.py`` runs clean against the live fleet.
"""

import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.monitor.collect import collect_requests
from deeplearning4j_tpu.monitor.reqlog import RequestLog, new_record
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, DeadlineExceededError, ServerOverloadedError)
from deeplearning4j_tpu.serving import (InferenceClient, InProcessReplica,
                                        Router)
from deeplearning4j_tpu.clustering.knn_server import ndarray_to_b64
from deeplearning4j_tpu.serving.batcher import MicroBatcher

X = np.arange(12, dtype=np.float32).reshape(3, 4) / 10.0


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


# ------------------------------------------------------------- ring buffer

def test_reqlog_ring_oldest_first_drop_and_accounting():
    log = RequestLog(capacity=4)
    for i in range(10):
        log.append(new_record(f"r{i}", "predict", outcome="ok"))
    assert len(log) == 4
    assert log.total == 10
    assert log.dropped == 6
    # oldest dropped first: exactly the newest four survive, oldest-first
    assert [r["request_id"] for r in log.tail(10)] == ["r6", "r7", "r8", "r9"]
    assert [r["request_id"] for r in log.tail(2)] == ["r8", "r9"]
    assert log.tail(0) == []
    assert log.find("r9")["request_id"] == "r9"
    assert log.find("r0") is None                 # dropped off the ring
    snap = log.snapshot(2)
    assert snap["capacity"] == 4 and snap["total"] == 10
    assert snap["dropped"] == 6                   # ring-level, not n-slice
    assert [r["request_id"] for r in snap["records"]] == ["r8", "r9"]
    # identity defaults every writer relies on
    rec = new_record(None, "decode")
    assert rec["tenant"] == "default" and rec["priority"] == "normal"
    assert rec["outcome"] is None and abs(rec["ts"] - time.time()) < 5.0


# --------------------------------------------------- rejection wide events

class _IdentityEngine:
    """Bare predict_host without ``phases=`` — exercises the batcher's
    capability fallback alongside the rejection paths."""

    def predict_host(self, x):
        return np.asarray(x)


def test_batcher_rejections_one_terminal_record_each():
    # queue-full (429): park the worker so nothing drains, fill the queue
    mb = MicroBatcher(_IdentityEngine(), max_queue=1, journal_capacity=8)
    mb._thread = threading.current_thread()       # sentinel: never drains
    mb.submit(X, request_id="fills-queue")
    with pytest.raises(ServerOverloadedError):
        mb.submit(X, block=False, request_id="gets-shed", tenant="acme")
    shed = [r for r in mb.journal.tail() if r["outcome"] == "shed"]
    assert len(shed) == 1
    assert shed[0]["request_id"] == "gets-shed"
    assert shed[0]["tenant"] == "acme" and shed[0]["source"] == "predict"
    assert mb.journal.total == 1                  # the queued one is live

    # stopped (503): a post-stop submit fails fast and journals "error"
    mb2 = MicroBatcher(_IdentityEngine(), journal_capacity=8)
    mb2.start()
    mb2.stop()
    with pytest.raises(BatcherStoppedError):
        mb2.submit(X, request_id="too-late")
    errs = [r for r in mb2.journal.tail() if r["outcome"] == "error"]
    assert len(errs) == 1 and errs[0]["request_id"] == "too-late"

    # deadline (504): expired before dispatch, answered without the device
    mb3 = MicroBatcher(_IdentityEngine(), journal_capacity=8).start()
    try:
        fut = mb3.submit(X, deadline_ms=0.0, request_id="expired")
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=10.0)
        dead = [r for r in mb3.journal.tail() if r["outcome"] == "deadline"]
        assert len(dead) == 1 and dead[0]["request_id"] == "expired"
        # the served path still works after, with its own single record
        ok = mb3.submit(X, request_id="served").result(timeout=10.0)
        assert ok.shape == X.shape
        assert [r["request_id"] for r in mb3.journal.tail()
                if r["outcome"] == "ok"] == ["served"]
        assert mb3.journal.total == 2
    finally:
        mb3.stop()


# ----------------------------------------------------------- HTTP replicas

@pytest.fixture(scope="module")
def mlp_rep():
    rep = InProcessReplica(model="mlp").start()
    yield rep
    rep.stop()


@pytest.fixture(scope="module")
def lstm_rep():
    rep = InProcessReplica(model="charlstm", slots=2, max_len=32).start()
    yield rep
    rep.stop()


def _post(url, path, payload, headers=None):
    c = InferenceClient(url, retries=1)
    try:
        return c.post_raw(path, json.dumps(payload).encode(),
                          headers=headers)
    finally:
        c.close()


def test_server_mints_and_echoes_request_id(lstm_rep):
    gen = {"tokens": [1, 2, 3], "max_new_tokens": 4}
    # no x-request-id from the client: the server mints one and echoes it
    st, _, hdrs = _post(lstm_rep.url, "/generate", gen)
    assert st == 200
    minted = hdrs.get("x-request-id")
    assert minted and minted.startswith("req-")
    # a client-supplied id is echoed verbatim, never re-minted
    st, _, hdrs = _post(lstm_rep.url, "/generate", gen,
                        headers={"x-request-id": "my-rid-7"})
    assert st == 200 and hdrs.get("x-request-id") == "my-rid-7"
    # both land in the journal, joined on the id
    st, body = _get_json(f"{lstm_rep.url}/requests")
    assert st == 200
    by_rid = {r["request_id"]: r for r in body["records"]}
    assert minted in by_rid and "my-rid-7" in by_rid
    assert by_rid["my-rid-7"]["source"] == "decode"
    assert by_rid["my-rid-7"]["outcome"] == "max_new"
    # minted ids are unique per request
    st, _, hdrs = _post(lstm_rep.url, "/generate", gen)
    assert st == 200 and hdrs.get("x-request-id") not in (None, minted)
    # ?n= caps the reply; junk n is a 400, not a crash
    st, body = _get_json(f"{lstm_rep.url}/requests?n=1")
    assert st == 200 and len(body["records"]) == 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{lstm_rep.url}/requests?n=junk", timeout=10)
    assert ei.value.code == 400


def test_predict_wide_event_phases_and_tenant(mlp_rep):
    payload = {"ndarray": ndarray_to_b64(X)}
    st, _, hdrs = _post(mlp_rep.url, "/predict", payload,
                        headers={"x-request-id": "pred-1",
                                 "x-tenant": "acme",
                                 "x-priority": "batch"})
    assert st == 200 and hdrs.get("x-request-id") == "pred-1"
    st, body = _get_json(f"{mlp_rep.url}/requests")
    rec = {r["request_id"]: r for r in body["records"]}["pred-1"]
    assert rec["source"] == "predict" and rec["outcome"] == "ok"
    assert rec["tenant"] == "acme" and rec["priority"] == "batch"
    assert rec["rows"] == 3 and rec["batch"] >= 1
    phases = rec["phases"]
    assert set(phases) >= {"queue", "bucket", "pad", "device", "readback"}
    assert all(v >= 0.0 for v in phases.values())
    # phase attribution can't exceed the request's own wall (the queue
    # phase is per-rider; bucket/pad/device/readback are the merged call)
    assert phases["queue"] <= rec["wall_seconds"] + 1e-3


def test_decode_queue_full_429_leaves_one_shed_record(lstm_rep):
    eng = lstm_rep.srv.decode_engine
    before = eng.journal.total
    saved = eng.max_queue
    eng.max_queue = 0                             # every submit sheds
    try:
        st, body, hdrs = _post(lstm_rep.url, "/generate",
                               {"tokens": [1, 2], "max_new_tokens": 2},
                               headers={"x-request-id": "shed-me"})
    finally:
        eng.max_queue = saved
    assert st == 429, body
    assert hdrs.get("x-request-id") == "shed-me"  # echoed even on errors
    assert eng.journal.total == before + 1        # exactly one record
    rec = eng.journal.find("shed-me")
    assert rec is not None and rec["outcome"] == "shed"
    assert rec["tokens_out"] == 0 and rec["phases"]["queue"] >= 0.0


def test_generate_storm_ring_bound_and_phase_walls():
    cap, n_req = 6, 12
    rep = InProcessReplica(model="charlstm", slots=2, max_len=32,
                           journal_capacity=cap).start()
    try:
        walls, errs, lock = {}, [], threading.Lock()

        def worker(i):
            rid = f"storm-{i:02d}"
            t0 = time.perf_counter()
            try:
                st, body, _ = _post(rep.url, "/generate",
                                    {"tokens": [1 + i % 4, 2],
                                     "max_new_tokens": 4},
                                    headers={"x-request-id": rid})
                assert st == 200, body
            except Exception as e:  # noqa: BLE001 — surfaced below
                with lock:
                    errs.append(e)
                return
            with lock:
                walls[rid] = time.perf_counter() - t0

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_req)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        assert len(walls) == n_req

        st, body = _get_json(f"{rep.url}/requests")
        assert st == 200
        recs = body["records"]
        # ring bound honored under concurrency; the accounting shows it
        assert len(recs) <= cap
        assert body["total"] == n_req
        assert body["dropped"] == n_req - len(recs)
        # newest survive the wrap (oldest-first drop), served oldest-first
        tss = [r["ts"] for r in recs]
        assert tss == sorted(tss)
        for rec in recs:
            assert rec["request_id"] in walls
            assert rec["outcome"] == "max_new"
            assert rec["tokens_out"] == 4
            ph = rec["phases"]
            assert set(ph) >= {"queue", "prefill", "decode"}
            assert all(v >= 0.0 for v in ph.values())   # monotone stamps
            # the phases ARE the wall: queue+prefill+decode partition
            # submit..last-token exactly (verify only rides spec engines)
            core = ph["queue"] + ph["prefill"] + ph["decode"]
            assert abs(core - rec["wall_seconds"]) < 1e-3
            # and the server-side wall fits inside the client's wall
            assert rec["wall_seconds"] <= walls[rec["request_id"]] + 0.05
            assert rec["ttft_seconds"] is not None
            assert rec["ttft_seconds"] <= rec["wall_seconds"] + 1e-6
    finally:
        rep.stop()


# ------------------------------------------------------------- fleet merge

def test_fleet_journal_merge_exemplar_resolution_and_tail_cli(tmp_path):
    """3-replica router /generate storm (the ISSUE's fleet acceptance
    bar): the merged journal has ONE entry per request with the router's
    annotation joined by base rid, a replica's worst ITL bucket exemplar
    resolves to a concrete merged record, and tail_requests.py runs
    clean against the live fleet."""
    reps = [InProcessReplica(model="charlstm", slots=4, max_len=32).start()
            for _ in range(3)]
    router = None
    try:
        # warm each engine directly so the routed storm never waits on an
        # XLA compile (hedges would fire on compile latency, not load)
        for r in reps:
            st, body, _ = _post(r.url, "/generate",
                                {"tokens": [1, 2], "max_new_tokens": 2})
            assert st == 200, body
        router = Router([r.url for r in reps], port=0, probe_interval=None,
                        upstream_timeout=60.0).start()
        base = f"http://127.0.0.1:{router.port}"

        rids = [f"fleet-{i:02d}" for i in range(9)]
        errs, lock = [], threading.Lock()

        def worker(rid, tok):
            try:
                st, body, hdrs = _post(base, "/generate",
                                       {"tokens": [tok, 2],
                                        "max_new_tokens": 6},
                                       headers={"x-request-id": rid})
                assert st == 200, body
                assert hdrs.get("x-request-id") == rid
            except Exception as e:  # noqa: BLE001 — surfaced below
                with lock:
                    errs.append(e)

        ts = [threading.Thread(target=worker, args=(rid, 1 + i % 4))
              for i, rid in enumerate(rids)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs

        out = str(tmp_path / "fleet_requests.json")
        doc = collect_requests(base, path=out)
        assert len(doc["collectedFrom"]) == 4     # router + 3 replicas
        mine = {e["request_id"]: e for e in doc["requests"]
                if e["request_id"] in set(rids)}
        # one merged entry per request, router annotation joined by rid
        assert sorted(mine) == rids
        for rid, entry in mine.items():
            rt = entry["router"]
            assert rt is not None, f"{rid} missing its router annotation"
            assert rt["outcome"] in ("ok", "hedge_win", "failed_over")
            assert rt["status"] == 200
            assert rt["attempts"] >= 1
            assert all(a.split("#", 1)[0] == rid
                       for a in rt["attempt_rids"])
            assert entry["attempts"], f"{rid} has no replica record"
            att = entry["attempts"][0]
            assert att["source"] == "decode"
            assert att["tokens_out"] == 6
        # the worst ITL bucket exemplar names a real, resolvable request
        by_base = {e["request_id"]: e for e in doc["requests"]}
        resolved = 0
        for r in reps:
            exs = InferenceClient(r.url).stats()[
                "decode"]["slo"]["itl"]["exemplars"]
            if not exs:
                continue
            _, ex_rid, ex_val = exs[-1]           # highest populated bucket
            entry = by_base.get(ex_rid.split("#", 1)[0])
            assert entry is not None, f"exemplar {ex_rid} resolves nowhere"
            assert entry["attempts"] and ex_val >= 0.0
            resolved += 1
        assert resolved >= 1, "no replica produced an ITL exemplar"
        # the on-disk doc is loadable and carries the same merge
        with open(out) as f:
            assert len(json.load(f)["requests"]) == len(doc["requests"])

        # tail CLI smoke against the live fleet
        tool = Path(__file__).resolve().parent.parent / "tools"
        r1 = subprocess.run(
            [sys.executable, str(tool / "tail_requests.py"), base,
             "--slowest", "3"],
            capture_output=True, text=True, timeout=120)
        assert r1.returncode == 0, r1.stderr
        assert len(r1.stdout.strip().splitlines()) == 3
        r2 = subprocess.run(
            [sys.executable, str(tool / "tail_requests.py"), base,
             "--outcome", "max_new", "--tenant", "default"],
            capture_output=True, text=True, timeout=120)
        assert r2.returncode == 0, r2.stderr
        assert any(rid in r2.stdout for rid in rids)
        r3 = subprocess.run(
            [sys.executable, str(tool / "collect_requests.py"), base,
             "-o", str(tmp_path / "cli_requests.json")],
            capture_output=True, text=True, timeout=120)
        assert r3.returncode == 0, r3.stderr
        assert json.loads((tmp_path / "cli_requests.json").read_text())[
            "requests"]
    finally:
        if router is not None:
            router.stop()
        for r in reps:
            r.stop()
