"""Execution core (exec/): mesh policy, sharded parity, trace counts,
kernel routing.

The load-bearing claims pinned here:
- the sharding decision is a pure function of argument shapes (same shape
  -> same compiled program), with the measured min-rows-per-shard
  threshold keeping small batches on the exact single-device program;
- on a 1-device mesh ``Executor.jit`` IS ``jax.jit`` — no wrapper, zero
  new XLA programs vs the pre-executor code;
- sharded d=8 execution (the conftest-forced host devices) matches d=1
  within pinned tolerance for fit / predict / decode — f32 reductions
  reorder across shard boundaries, so the pin is a tolerance, not
  bitwise (measured max abs diff ~3e-8 on a conv forward);
- the fused-LSTM forward routes per measured shape (KERNELS_TPU.json),
  overridably.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import exec as ex
from deeplearning4j_tpu.exec.executor import Executor, param_spec
from deeplearning4j_tpu.exec.mesh import _mesh_from_env
from deeplearning4j_tpu.exec import routing
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (DenseLayer, OutputLayer, LSTM,
                                          RnnOutputLayer)
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.data.dataset import DataSet

V = 13


def _mln(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _lstm_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build())
    return MultiLayerNetwork(conf).init()


def _single_exec():
    return Executor(ex.build_mesh(jax.devices()[:1]))


def _batch(b, f=6, c=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(b, f).astype(np.float32)
    y = np.eye(c, dtype=np.float32)[rs.randint(0, c, b)]
    return x, y


# ---------------------------------------------------------------- mesh
class TestMesh:
    @pytest.mark.mesh8
    def test_default_mesh_is_pure_dp_over_all_devices(self):
        mesh = ex.default_mesh()
        assert mesh.shape[ex.DATA_AXIS] == len(jax.devices())
        assert mesh.shape[ex.MODEL_AXIS] == 1

    @pytest.mark.mesh8
    def test_env_spec_parses(self):
        assert _mesh_from_env("off").size == 1
        m = _mesh_from_env("data=4,model=2")
        assert m.shape[ex.DATA_AXIS] == 4 and m.shape[ex.MODEL_AXIS] == 2
        m = _mesh_from_env("model=2")   # data absorbs the rest
        assert m.shape[ex.MODEL_AXIS] == 2
        assert m.size == len(jax.devices())
        with pytest.raises(ValueError):
            _mesh_from_env("data=999")

    def test_model_parallel_must_divide(self):
        with pytest.raises(ValueError):
            ex.build_mesh(jax.devices()[:1], model_parallel=3)

    def test_host_device_env_composes_flag(self):
        env = ex.host_device_env(4, base={"XLA_FLAGS":
                                          "--foo "
                                          "--xla_force_host_platform_device_count=2"})
        assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
        assert "device_count=2" not in env["XLA_FLAGS"]
        assert "--foo" in env["XLA_FLAGS"]
        assert env["JAX_PLATFORMS"] == "cpu"

    def test_mesh8_fixture_is_a_subprocess_env(self, mesh8):
        assert "--xla_force_host_platform_device_count=8" in mesh8["XLA_FLAGS"]

    def test_mesh_gauges_published(self):
        from deeplearning4j_tpu.monitor.metrics import get_registry
        ex.default_mesh()
        text = get_registry().render()
        assert "dl4jtpu_mesh_devices" in text
        assert 'dl4jtpu_mesh_axis_size{axis="data"}' in text


# -------------------------------------------------------------- policy
class TestShardingPolicy:
    @pytest.mark.mesh8
    def test_min_rows_threshold(self):
        e = Executor(ex.build_mesh())          # 8 devices, pure DP
        assert e.shardable_rows(128)           # 16 rows/shard
        assert e.shardable_rows(8 * 16)
        assert not e.shardable_rows(64)        # 8/shard < 16
        assert not e.shardable_rows(127)       # not divisible
        assert e.shardable_rows(8, min_rows=1)

    def test_single_device_never_shards(self):
        e = _single_exec()
        assert not e.shardable_rows(1 << 20)

    def test_param_spec_megatron_rules(self):
        w_col = jnp.zeros((8, 32))     # generic kernel: shard output dim
        assert param_spec("['Wq']", w_col, 2) == P(None, "model")
        w_row = jnp.zeros((32, 8))     # wide->narrow: row-parallel
        assert param_spec("['ff2']['W']", w_row, 2) == P("model", None)
        assert param_spec("['dense']['W']", w_row, 2) == P("model", None)
        bias = jnp.zeros((32,))
        assert param_spec("['b']", bias, 2) == P()
        odd = jnp.zeros((3, 5))        # nothing divides: replicate
        assert param_spec("['W']", odd, 2) == P()
        assert param_spec("['Wq']", w_col, 1) == P()

    @pytest.mark.mesh8
    def test_opt_state_co_shards_with_params(self):
        e = Executor(ex.build_mesh(model_parallel=2))
        params = {"dense": {"W": jnp.zeros((32, 8)), "b": jnp.zeros((8,))}}
        opt = {"m": {"W": jnp.zeros((32, 8)), "b": jnp.zeros((8,))}}
        sh = e._state_shardings(opt, params)
        assert sh["m"]["W"].spec == P("model", None)
        assert sh["m"]["b"].spec == P()


# ----------------------------------------------------- single-device path
class TestSingleDevicePath:
    def test_jit_is_plain_jax_jit(self):
        e = _single_exec()
        f = e.jit(lambda x: x + 1, in_specs=(ex.BATCH,),
                  out_specs=(ex.BATCH,))
        assert not hasattr(f, "_dl4jtpu_exec_wrapper")
        assert hasattr(f, "lower")     # a real jax.jit object

    def test_train_step_compiles_once_per_shape(self):
        net = _mln()
        net._exec = _single_exec()
        x, y = _batch(32)
        net.fit(DataSet(x, y))
        net.fit(DataSet(x, y))
        assert net._compile_count == 1
        step = net._train_step[next(iter(net._train_step))]
        assert not hasattr(step, "_dl4jtpu_exec_wrapper")

    @pytest.mark.mesh8
    def test_small_batches_stay_on_replicated_program(self):
        net = _mln()
        assert net._executor.mesh.size == len(jax.devices())
        x, y = _batch(32)              # 4 rows/shard < 16: replicated
        net.fit(DataSet(x, y))
        net.fit(DataSet(x, y))
        assert net._compile_count == 1
        step = net._train_step[next(iter(net._train_step))]
        assert step._dl4jtpu_exec_wrapper
        assert set(step._exec_cache) == {False}

    @pytest.mark.mesh8
    def test_large_batch_adds_exactly_one_sharded_program(self):
        net = _mln()
        xs, ys = _batch(32)
        net.fit(DataSet(xs, ys))
        xl, yl = _batch(128)
        net.fit(DataSet(xl, yl))
        net.fit(DataSet(xl, yl))
        step = net._train_step[next(iter(net._train_step))]
        assert set(step._exec_cache) == {False, True}
        assert net._compile_count == 2


# ------------------------------------------------------- sharded parity
@pytest.mark.mesh8
class TestShardedParity:
    """d=8 (conftest's forced host devices) vs d=1, pinned tolerance:
    f32 reductions reorder across shard boundaries, so 'parity' is a
    numeric pin, not bitwise equality."""

    FIT_RTOL, FIT_ATOL = 1e-4, 1e-6
    FWD_RTOL, FWD_ATOL = 1e-5, 1e-6

    def test_fit_matches_single_device(self):
        b = 128                        # 16 rows/shard: sharded path
        net1, net8 = _mln(), _mln()
        net1._exec = _single_exec()
        for i in range(3):
            x, y = _batch(b, seed=i)
            net1.fit(DataSet(x, y))
            net8.fit(DataSet(x, y))
        step = net8._train_step[next(iter(net8._train_step))]
        assert set(step._exec_cache) == {True}
        for p1, p8 in zip(net1.params, net8.params):
            for k in p1:
                np.testing.assert_allclose(
                    np.asarray(p1[k]), np.asarray(p8[k]),
                    rtol=self.FIT_RTOL, atol=self.FIT_ATOL, err_msg=k)
        np.testing.assert_allclose(net1.get_score(), net8.get_score(),
                                   rtol=self.FIT_RTOL, atol=self.FIT_ATOL)

    def test_fit_scan_matches_single_device(self):
        k, b = 3, 128
        rs = np.random.RandomState(0)
        xs = rs.randn(k, b, 6).astype(np.float32)
        ys = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (k, b))]
        net1, net8 = _mln(), _mln()
        net1._exec = _single_exec()
        net1.fit_scan(xs, ys)
        net8.fit_scan(xs, ys)
        for p1, p8 in zip(net1.params, net8.params):
            for key in p1:
                np.testing.assert_allclose(
                    np.asarray(p1[key]), np.asarray(p8[key]),
                    rtol=self.FIT_RTOL, atol=self.FIT_ATOL, err_msg=key)

    def test_predict_matches_single_device(self):
        net1, net8 = _mln(), _mln()
        net1._exec = _single_exec()
        x, _ = _batch(128)
        y1 = np.asarray(net1.output(x))            # bucketed serving path
        y8 = np.asarray(net8.output(x))
        np.testing.assert_allclose(y1, y8, rtol=self.FWD_RTOL,
                                   atol=self.FWD_ATOL)
        # the sharded engine really took the sharded program
        eng = net8.serving_engine()
        assert set(eng._fwd._exec_cache) == {True}

    def test_decode_matches_single_device(self):
        from deeplearning4j_tpu.serving import DecodeEngine
        prompt = [3, 1, 4, 1, 5]
        outs = []
        for make_exec in (_single_exec, None):
            net = _lstm_net()
            if make_exec is not None:
                net._exec = make_exec()
            eng = DecodeEngine(net, slots=16, max_len=32).start()
            try:
                r = eng.generate(prompt, max_new_tokens=8, temperature=0.0)
            finally:
                eng.stop()
            outs.append(list(r["tokens"]))
        assert outs[0] == outs[1]


# -------------------------------------------------------------- routing
class TestRouting:
    def test_measured_table_hits(self):
        assert routing.lstm_fwd_route(16, 128, t=64,
                                      dtype="float32") == "scan"
        assert routing.lstm_fwd_route(16, 128, t=64,
                                      dtype="bfloat16") == "pallas"
        assert routing.lstm_fwd_route(32, 256, t=128,
                                      dtype="float32") == "scan"
        assert routing.lstm_fwd_route(32, 256, t=64,
                                      dtype="float32") == "pallas"

    def test_heuristic_between_measured_shapes(self):
        assert routing.lstm_fwd_route(4, 16) == "scan"       # latency-bound
        assert routing.lstm_fwd_route(256, 256) == "pallas"  # bandwidth-bound
        # f32 long-T falls back to scan even above the B*H crossover
        assert routing.lstm_fwd_route(64, 64, t=256,
                                      dtype="float32") == "scan"

    def test_non_tpu_backend_scans(self):
        assert routing.lstm_fwd_route(256, 256, backend="cpu") == "scan"

    def test_set_route_pin_wins(self):
        routing.set_route("fused_lstm", "scan")
        try:
            assert routing.lstm_fwd_route(256, 256) == "scan"
        finally:
            routing.set_route("fused_lstm", None)
        with pytest.raises(ValueError):
            routing.set_route("fused_lstm", "nope")

    def test_env_pin(self, monkeypatch):
        monkeypatch.setenv("DL4JTPU_LSTM_FWD_ROUTE", "pallas")
        assert routing.lstm_fwd_route(1, 1) == "pallas"

    def test_load_measurements_merges_bench_rows(self):
        n = routing.load_measurements([
            {"kernel": "fused_lstm", "B": 2, "T": 2, "H": 2,
             "dtype": "float32", "fwd_speedup": 1.5},
            {"kernel": "other", "B": 2, "T": 2, "H": 2,
             "dtype": "float32", "fwd_speedup": 9.0},
            {"kernel": "fused_lstm", "B": 2, "T": 2, "H": 2,
             "dtype": "float32"},
        ])
        assert n == 1
        try:
            assert routing.lstm_fwd_route(2, 2, t=2,
                                          dtype="float32") == "pallas"
        finally:
            routing._MEASURED.pop(("fused_lstm", 2, 2, 2, "float32"))


class TestMeasurementFileRouting:
    """Regression over the SHIPPED KERNELS_TPU.json: every measured
    fused-LSTM row — bf16 exactly like f32 — routes pallas iff its
    measured forward speedup beat XLA. Guards the bf16 small-shape
    losses (0.03x-0.4x) that the pre-measurement heuristic got wrong."""

    def _rows(self):
        import json
        import os
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "KERNELS_TPU.json")) as f:
            return [r for r in json.load(f)["results"]
                    if r.get("kernel") == "fused_lstm"
                    and r.get("fwd_speedup") is not None]

    def test_every_measured_row_routes_by_its_speedup(self):
        rows = self._rows()
        assert len(rows) >= 10            # the file really shipped data
        n = routing.load_measurements_file()
        assert n >= len(rows)
        for r in rows:
            want = "pallas" if r["fwd_speedup"] > 1 else "scan"
            got = routing.lstm_fwd_route(r["B"], r["H"], t=r["T"],
                                         dtype=r["dtype"])
            assert got == want, (r, got)

    def test_bf16_small_shapes_route_scan(self):
        routing.load_measurements_file()
        # the three bf16 rows that LOSE hardest (0.03x, 0.1x, 0.31x)
        assert routing.lstm_fwd_route(1, 8, t=4, dtype="bfloat16") == "scan"
        assert routing.lstm_fwd_route(4, 8, t=16, dtype="bfloat16") == "scan"
        assert routing.lstm_fwd_route(8, 24, t=16, dtype="bfloat16") == "scan"
        # and the bf16 rows that WIN route pallas
        assert routing.lstm_fwd_route(16, 128, t=64,
                                      dtype="bfloat16") == "pallas"
        assert routing.lstm_fwd_route(32, 256, t=128,
                                      dtype="bfloat16") == "pallas"

    def test_file_load_is_idempotent(self):
        a = routing.load_measurements_file()
        b = routing.load_measurements_file()
        assert a == b >= 1
