"""UI component library + t-SNE module tests (SURVEY §2 #33/#34 parity:
deeplearning4j-ui-components, TsneModule)."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.ui import components as cmp
from deeplearning4j_tpu.ui import UIServer


class TestComponents:
    def test_chart_line_json_round_trip(self):
        c = (cmp.ChartLine("loss", cmp.Style(width=300))
             .add_series("train", [0, 1, 2], [3.0, 2.0, 1.0])
             .add_series("val", [0, 1, 2], [3.5, 2.5, 1.5]))
        back = cmp.Component.from_json(c.to_json())
        assert isinstance(back, cmp.ChartLine)
        assert back.title == "loss" and len(back.series) == 2
        assert back.series[0]["y"] == [3.0, 2.0, 1.0]
        assert back.style.width == 300

    def test_series_length_mismatch_raises(self):
        import pytest
        with pytest.raises(ValueError):
            cmp.ChartLine("bad").add_series("s", [1, 2], [1.0])

    def test_histogram_and_scatter(self):
        h = cmp.ChartHistogram("resid")
        h.add_bin(0.0, 0.1, 5).add_bin(0.1, 0.2, 3)
        back = cmp.Component.from_json(h.to_json())
        assert back.series[0]["lower"] == 0.0 and back.series[1]["y"] == 3
        s = cmp.ChartScatter("emb").add_series("pts", [1, 2], [3, 4])
        assert "canvas" in s.render_html()

    def test_table_text_div(self):
        t = cmp.ComponentTable(["layer", "params"], [["conv", 500]])
        d = cmp.ComponentDiv(t, cmp.ComponentText("hello"))
        back = cmp.Component.from_json(d.to_json())
        assert isinstance(back, cmp.ComponentDiv)
        assert isinstance(back.children[0], cmp.ComponentTable)
        assert back.children[0].rows == [["conv", "500"]]
        html = back.render_html()
        assert "conv" in html and "hello" in html

    def test_stacked_area_and_bars(self):
        sa = (cmp.ChartStackedArea("mem").add_series("a", [0, 1], [1, 1])
              .add_series("b", [0, 1], [2, 2]))
        assert "canvas" in sa.render_html()
        hb = cmp.ChartHorizontalBar("counts").add_value("x", 3).add_value("y", 5)
        assert cmp.Component.from_json(hb.to_json()).series[1]["value"] == 5
        tl = cmp.ChartTimeline("phases").add_lane("etl", [(0, 1, "load")])
        assert "load" in tl.render_html()

    def test_render_page_is_standalone(self):
        page = cmp.render_page(
            cmp.ChartLine("l").add_series("s", [0, 1], [1, 2]),
            cmp.ComponentText("done"))
        assert page.startswith("<!DOCTYPE html>")
        assert "done" in page


class TestTsneModule:
    def test_upload_and_serve(self):
        ui = UIServer(port=0)
        try:
            rs = np.random.RandomState(0)
            coords = rs.randn(20, 2)
            ui.upload_tsne("emb1", coords, labels=[f"w{i}" for i in range(20)])
            base = f"http://127.0.0.1:{ui.port}"
            page = urllib.request.urlopen(base + "/tsne", timeout=5).read()
            assert b"t-SNE" in page
            sids = json.loads(urllib.request.urlopen(
                base + "/tsne/sessions", timeout=5).read())
            assert sids == ["emb1"]
            d = json.loads(urllib.request.urlopen(
                base + "/tsne/coords?sid=emb1", timeout=5).read())
            assert len(d["coords"]) == 20 and d["labels"][3] == "w3"
            # remote upload route (the reference TsneModule /tsne/upload)
            body = json.dumps({"sessionId": "emb2",
                               "coords": [[0, 1], [1, 0]],
                               "labels": ["a", "b"]}).encode()
            req = urllib.request.Request(base + "/tsne/upload", data=body,
                                         method="POST")
            assert json.loads(urllib.request.urlopen(
                req, timeout=5).read())["status"] == "ok"
            d2 = json.loads(urllib.request.urlopen(
                base + "/tsne/coords?sid=emb2", timeout=5).read())
            assert d2["coords"] == [[0, 1], [1, 0]]
        finally:
            ui.stop()

    def test_tsne_pipeline_to_ui(self):
        """plot/tsne output feeds the module (the BarnesHutTsne → UI flow)."""
        from deeplearning4j_tpu.plot.tsne import BarnesHutTsne
        ui = UIServer(port=0)
        try:
            rs = np.random.RandomState(1)
            x = np.concatenate([rs.randn(10, 8) + 4, rs.randn(10, 8) - 4])
            emb = BarnesHutTsne(max_iter=30, perplexity=5).fit_transform(x)
            ui.upload_tsne("words", emb)
            d = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{ui.port}/tsne/coords?sid=words",
                timeout=5).read())
            assert len(d["coords"]) == 20
        finally:
            ui.stop()
