"""End-to-end smoke tests: build, fit, eval, serialize tiny networks."""

import numpy as np
import pytest

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.layers import (
    DenseLayer, OutputLayer, ConvolutionLayer, SubsamplingLayer,
    BatchNormalization, LSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.fetchers import load_iris


def iris_net():
    return (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(1e-2))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_mlp_learns_iris():
    x, y = load_iris()
    net = MultiLayerNetwork(iris_net()).init()
    ds = DataSet(x, y)
    s0 = net.score(ds)
    for _ in range(120):
        net.fit(ds)
    assert net.score(ds) < s0 * 0.5
    ev = net.evaluate(ds)
    assert ev.accuracy() > 0.9


def test_conv_net_trains():
    conf = (NeuralNetConfiguration.builder()
            .seed(7)
            .updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=3, activation="relu"))
            .layer(SubsamplingLayer(kernel_size=2, stride=2))
            .layer(BatchNormalization())
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.rand(8, 12, 12, 1).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 8)]
    s0 = net.score(x=x, y=y)
    for _ in range(30):
        net.fit(x, y)
    assert np.isfinite(net.get_score())
    assert net.score(x=x, y=y) < s0


def test_lstm_sequence_classification():
    conf = (NeuralNetConfiguration.builder()
            .seed(3)
            .updater(Adam(5e-3))
            .list()
            .layer(LSTM(n_out=12, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.recurrent(5))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.rand(6, 7, 5).astype(np.float32)
    y = np.zeros((6, 7, 2), np.float32)
    y[:, :, 0] = 1
    s0 = net.score(x=x, y=y)
    for _ in range(25):
        net.fit(x, y)
    assert net.score(x=x, y=y) < s0


def test_output_shapes():
    net = MultiLayerNetwork(iris_net()).init()
    out = net.output(np.random.rand(10, 4).astype(np.float32))
    assert out.shape == (10, 3)
    assert np.allclose(np.asarray(out).sum(axis=1), 1.0, atol=1e-5)


def test_summary_and_params():
    net = MultiLayerNetwork(iris_net()).init()
    assert net.num_params() == 4 * 16 + 16 + 16 * 3 + 3
    assert "DenseLayer" in net.summary()


def test_serialization_roundtrip(tmp_path):
    x, y = load_iris()
    net = MultiLayerNetwork(iris_net()).init()
    net.fit(DataSet(x, y))
    p = tmp_path / "model.zip"
    net.save(str(p))
    net2 = MultiLayerNetwork.load(str(p))
    out1 = np.asarray(net.output(x[:5]))
    out2 = np.asarray(net2.output(x[:5]))
    assert np.allclose(out1, out2, atol=1e-6)
    # resumes training identically (updater state round-trip)
    net.fit(DataSet(x, y))
    net2.fit(DataSet(x, y))
    assert np.allclose(np.asarray(net.output(x[:5])),
                       np.asarray(net2.output(x[:5])), atol=1e-5)


def test_config_json_roundtrip():
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    conf = iris_net()
    j = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(j)
    assert conf2.to_json() == j
    net = MultiLayerNetwork(conf2).init()
    assert net.num_params() > 0
