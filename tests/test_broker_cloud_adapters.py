"""Broker/cloud adapter shims (parity: dl4j-streaming kafka route +
deeplearning4j-aws S3 reader/uploader), contract-tested against the
in-process fakes — the optional real backends (kafka-python, boto3) share
the exact same protocol surface."""

import importlib.util
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.data.kafka import (
    InMemoryBroker, NDArrayPublisher, NDArrayPubSubRoute, default_client)
from deeplearning4j_tpu.scaleout.s3 import (
    LocalFileStore, S3Downloader, S3Uploader)

_HAS_KAFKA = importlib.util.find_spec("kafka") is not None


def test_kafka_route_end_to_end_records_to_datasets():
    broker = InMemoryBroker()
    pub = NDArrayPublisher(broker, "train-topic")
    route = NDArrayPubSubRoute(broker, "train-topic", batch_size=4).start()
    rs = np.random.RandomState(0)
    sent = [(rs.rand(3).astype(np.float32),
             np.eye(2, dtype=np.float32)[i % 2]) for i in range(8)]
    for f, l in sent:
        pub.publish(f, l)
    ds1 = next(route.iterator)
    ds2 = next(route.iterator)
    route.stop()
    got_f = np.concatenate([ds1.features, ds2.features])
    np.testing.assert_allclose(got_f, np.stack([f for f, _ in sent]),
                               rtol=1e-6)
    with pytest.raises(StopIteration):
        next(route.iterator)               # stream ended cleanly


def test_kafka_route_trains_a_net():
    """The route feeds MultiLayerNetwork.fit like any other iterator."""
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Sgd

    broker = InMemoryBroker()
    pub = NDArrayPublisher(broker, "t")
    route = NDArrayPubSubRoute(broker, "t", batch_size=8).start()
    rs = np.random.RandomState(1)
    for _ in range(16):
        x = rs.randn(4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[int(x.sum() > 0)]
        pub.publish(x, y)
    import time
    deadline = time.monotonic() + 5.0
    while broker.pending("t") and time.monotonic() < deadline:
        time.sleep(0.01)            # wait for the pump to drain the topic
    route.stop()                    # joins the pump, then ends the stream
    conf = (NeuralNetConfiguration.builder().seed(2).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(route.iterator)
    route.stop(end_stream=False)
    assert np.isfinite(net.get_score())


@pytest.mark.skipif(_HAS_KAFKA, reason="kafka-python installed: "
                    "default_client would attempt a real broker connection")
def test_default_client_names_optional_dependency():
    with pytest.raises(ImportError, match="kafka-python"):
        default_client()


@pytest.mark.skipif(not _HAS_KAFKA, reason="needs kafka-python")
def test_default_client_wraps_broker_connection_errors():
    """Package present but no broker: the error must stay actionable (name
    the servers tried and the InMemoryBroker escape hatch), not surface as
    a bare NoBrokersAvailable from kafka internals."""
    with pytest.raises(ConnectionError, match="InMemoryBroker"):
        default_client("127.0.0.1:1")       # nothing listens on port 1


def test_s3_contract_roundtrip(tmp_path):
    store = LocalFileStore(tmp_path / "store")
    src = tmp_path / "model.bin"
    src.write_bytes(b"\x01\x02\x03")
    up = S3Uploader(store)
    up.upload_file(src, "models", "v1/model.bin")
    assert store.list_objects("models") == ["v1/model.bin"]
    assert store.list_objects("models", prefix="v1/") == ["v1/model.bin"]
    dst = S3Downloader(store).download("models", "v1/model.bin",
                                       tmp_path / "out" / "model.bin")
    assert dst.read_bytes() == b"\x01\x02\x03"
    store.delete("models", "v1/model.bin")
    assert store.list_objects("models") == []


def test_s3_upload_dir_and_prefix_download(tmp_path):
    d = tmp_path / "bundle"
    (d / "sub").mkdir(parents=True)
    (d / "a.txt").write_text("a")
    (d / "sub" / "b.txt").write_text("b")
    store = LocalFileStore(tmp_path / "store")
    n = S3Uploader(store).upload_dir(d, "bk", prefix="data")
    assert n == 2
    got = S3Downloader(store).download_prefix("bk", "data",
                                              tmp_path / "fetched")
    assert sorted(p.name for p in got) == ["a.txt", "b.txt"]
    assert (tmp_path / "fetched" / "sub" / "b.txt").read_text() == "b"


def test_s3_download_prefix_strips_only_at_slash_boundary(tmp_path):
    """Regression: prefix ``data`` also char-matches key ``database/x.txt``;
    that key must keep its full relative path, not be mangled to
    ``base/x.txt``."""
    store = LocalFileStore(tmp_path / "store")
    for key, text in (("data/a.txt", "a"), ("database/x.txt", "x")):
        src = tmp_path / Path(key).name
        src.write_text(text)
        S3Uploader(store).upload_file(src, "bk", key)
    got = S3Downloader(store).download_prefix("bk", "data",
                                              tmp_path / "fetched")
    assert sorted(p.relative_to(tmp_path / "fetched").as_posix()
                  for p in got) == ["a.txt", "database/x.txt"]
    assert (tmp_path / "fetched" / "database" / "x.txt").read_text() == "x"


def test_s3_download_dataset_lands_in_fetcher_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4JTPU_DATA_DIR", str(tmp_path / "cache"))
    store = LocalFileStore(tmp_path / "store")
    src = tmp_path / "iris.csv"
    src.write_text("5.1,3.5,1.4,0.2,0\n")
    S3Uploader(store).upload_file(src, "datasets", "iris/iris.csv")
    S3Downloader(store).download_dataset("datasets", "iris", "iris")
    from deeplearning4j_tpu.data.fetchers import data_dir
    assert (data_dir() / "iris" / "iris.csv").exists()


def test_s3_store_gates_optional_dependency():
    from deeplearning4j_tpu.scaleout.s3 import S3ObjectStore
    with pytest.raises(ImportError, match="boto3"):
        S3ObjectStore()
