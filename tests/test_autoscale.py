"""Fleet autoscaling (serving/autoscale.py) and the router's runtime
replica-set edges (add/remove_upstream, scale-to-zero hold + wake).

The decision logic is driven through ``evaluate_once()`` with a fake
clock — no sleeping out grace periods; only the scale-to-zero test runs
the real loop thread, because the held request genuinely waits on it."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.serving import (Autoscaler, InferenceClient,
                                        InProcessReplica, Router)


def _mlp():
    return InProcessReplica(model="mlp", chaos=False)


@pytest.fixture
def tier():
    """One started mlp replica + router; caller-extended fleet is torn
    down by each test."""
    rep = _mlp().start()
    router = Router([rep.url], port=0, probe_interval=0.2).start()
    try:
        yield rep, router
    finally:
        router.stop()
        rep.stop()


# ------------------------------------------------------------------- router
def test_add_remove_upstream(tier):
    rep, router = tier
    extra = _mlp().start()
    try:
        router.add_upstream(extra.url)
        assert set(router.replicas) == {rep.url, extra.url}
        assert extra.url in router.stats()["replicas"]
        assert router.remove_upstream(extra.url) is True
        assert set(router.replicas) == {rep.url}
        assert router.remove_upstream("http://127.0.0.1:9") is False
    finally:
        extra.stop()


def test_router_requires_upstreams_unless_holding():
    with pytest.raises(ValueError):
        Router([])
    r = Router([], hold_for_capacity_s=1.0)     # scale-to-zero config
    assert r.replicas == {}


# ------------------------------------------------------------ scale up/down
def test_scale_up_on_outstanding_then_drain_on_idle(tier):
    rep, router = tier
    now = [0.0]
    sc = Autoscaler(router, _mlp, min_replicas=1, max_replicas=3,
                    scale_up_outstanding=2.0, scale_down_outstanding=0.5,
                    idle_grace_s=10.0, cooldown_s=5.0,
                    clock=lambda: now[0])
    sc.adopt(rep)
    try:
        router.replicas[rep.url].outstanding = 6        # fake load
        assert sc.evaluate_once() == "up"
        assert sc.replica_count == 2 and len(router.replicas) == 2

        # cooldown gates an immediate second grow
        router.replicas[rep.url].outstanding = 20
        assert sc.evaluate_once() is None
        now[0] += 6.0
        assert sc.evaluate_once() == "up"
        assert sc.replica_count == 3

        # load vanishes: idle grace must elapse BEFORE any drain
        for r in router.replicas.values():
            r.outstanding = 0
        now[0] += 6.0
        assert sc.evaluate_once() is None               # grace starts
        now[0] += 5.0
        assert sc.evaluate_once() is None               # grace not over
        now[0] += 6.0
        assert sc.evaluate_once() == "down"
        assert sc.replica_count == 2
        now[0] += 11.0                                  # grace restarts
        assert sc.evaluate_once() is None
        now[0] += 11.0
        assert sc.evaluate_once() == "down"
        assert sc.replica_count == 1                    # at min: stays
        now[0] += 50.0
        assert sc.evaluate_once() is None
        assert rep.url in router.replicas               # original survives
    finally:
        sc.stop(stop_fleet=True)


def test_failed_warmup_probe_blocks_admission(tier):
    rep, router = tier
    sc = Autoscaler(router, _mlp, min_replicas=1, max_replicas=3,
                    scale_up_outstanding=2.0,
                    warmup_probe=lambda h: False)
    sc.adopt(rep)
    try:
        router.replicas[rep.url].outstanding = 6
        assert sc.evaluate_once() is None       # probe rejected the replica
        assert sc.replica_count == 1
        assert set(router.replicas) == {rep.url}
    finally:
        sc.stop(stop_fleet=False)


def test_signals_shape(tier):
    rep, router = tier
    sc = Autoscaler(router, _mlp)
    sc.adopt(rep)
    sig = sc.signals()
    assert set(sig) >= {"replicas", "routable", "outstanding_total",
                        "outstanding_per_replica", "fast_burn",
                        "compile_cost_s"}
    assert sig["replicas"] == 1 and sig["fast_burn"] is False


# ------------------------------------------------------------- scale-to-zero
def test_scale_to_zero_hold_and_wake():
    holder = {}

    def wake():
        holder["sc"].wake()

    router = Router([], port=0, hold_for_capacity_s=20.0, wake_hook=wake,
                    probe_interval=0.2)
    sc = Autoscaler(router, _mlp, min_replicas=0, max_replicas=1,
                    interval_s=0.05, cooldown_s=0.2)
    holder["sc"] = sc
    router.start()
    sc.start()
    cli = InferenceClient(f"http://127.0.0.1:{router.port}", timeout=60.0)
    try:
        out = cli.predict(np.zeros((1, 4), np.float32))
        assert np.asarray(out).shape[-1] == 3
        assert sc.replica_count == 1            # woken from zero
        from deeplearning4j_tpu.monitor import get_registry
        reg = get_registry()
        held = reg.counter(
            "dl4jtpu_router_capacity_holds_total", "", ("router", "outcome")
        ).labels(router=router.id, outcome="served").value
        assert held >= 1
    finally:
        cli.close()
        sc.stop(stop_fleet=True)
        router.stop()
