"""Fault tolerance end-to-end (docs/FAULT_TOLERANCE.md).

The load-bearing claims pinned here:

- ``write_model`` is ATOMIC: a crash before the final rename leaves the
  previous checkpoint intact and no torn zip or temp litter behind;
- a truncated/damaged checkpoint surfaces as one ``CorruptCheckpointError``
  naming the unreadable member, not a bare ``KeyError``/``BadZipFile``;
- ``CheckpointManager`` keeps the newest ``keep_last`` unpinned saves plus
  every ``keep_every``-th pinned one, and rebuilds its ledger from the
  directory when the manifest is damaged out-of-band;
- a run killed mid-epoch and resumed via ``fit(resume_from=...)`` is
  BITWISE-identical (params, updater state, counters) to the uninterrupted
  run — in-process with ``SimulatedCrash`` (fast, tier-1) and with a real
  SIGKILL over a process boundary (slow soak);
- the shared retry primitive backs off with bounded decorrelated jitter,
  respects deadlines (never sleeps past the budget), honours ``give_up``,
  raises fatal errors immediately, and lands every attempt in
  ``dl4jtpu_retry_attempts_total`` on GET /metrics;
- the serving stack under overload: queue-full requests shed FAST with
  HTTP 429, expired deadlines are answered without ever riding a device
  call (504), drain flips /healthz to 503 draining, and ``stop()`` settles
  every Future — including submits racing the stop.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zipfile
from pathlib import Path

import numpy as np
import pytest

from _crash_worker import build_data, build_net

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)
from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.resilience import (
    BatcherStoppedError, Checkpoint, CheckpointListener, CheckpointManager,
    CorruptCheckpointError, DeadlineExceededError, FatalError, RetryPolicy,
    RetriesExhaustedError, ServerOverloadedError, StreamStalledError,
    TransientError, default_classifier, latest_checkpoint, retry_call)
from deeplearning4j_tpu.resilience.faults import (
    CrashAfter, FlakyBroker, FlakyEngine, SimulatedCrash)
from deeplearning4j_tpu.serving import InferenceServer, MicroBatcher
from deeplearning4j_tpu.util.model_serializer import (
    read_meta, restore_into, write_model)

_WORKER = Path(__file__).with_name("_crash_worker.py")


def _leaves(net):
    import jax
    return [np.asarray(l) for l in
            jax.tree_util.tree_leaves((net.params, net.state, net.opt_state))]


def _assert_bitwise_equal(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        assert np.array_equal(x, y), f"leaf {i} diverged"


def _wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.002)
    pytest.fail(f"timed out waiting for {what}")


# ------------------------------------------------------------- atomic writes

def test_write_model_crash_before_rename_keeps_old_checkpoint(
        tmp_path, monkeypatch):
    net = build_net()
    target = tmp_path / "model.zip"
    write_model(net, str(target))
    original = target.read_bytes()

    def boom(src, dst):
        raise OSError("simulated crash before rename")

    monkeypatch.setattr(os, "replace", boom)
    net.fit(np.ones((3, 4), np.float32), np.eye(3, dtype=np.float32))
    with pytest.raises(OSError, match="simulated crash"):
        write_model(net, str(target))
    # old checkpoint intact, no temp litter, and still loadable
    assert target.read_bytes() == original
    assert sorted(p.name for p in tmp_path.iterdir()) == ["model.zip"]
    monkeypatch.undo()
    assert read_meta(str(target))["kind"] == "MultiLayerNetwork"


def test_write_model_crash_on_fresh_path_leaves_nothing(tmp_path, monkeypatch):
    def boom(src, dst):
        raise OSError("simulated crash before rename")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        write_model(build_net(), str(tmp_path / "fresh.zip"))
    assert list(tmp_path.iterdir()) == []


# -------------------------------------------------------- corrupt checkpoints

def test_truncated_checkpoint_raises_corrupt_error(tmp_path):
    p = tmp_path / "m.zip"
    write_model(build_net(), str(p))
    data = p.read_bytes()
    p.write_bytes(data[:len(data) // 2])
    with pytest.raises(CorruptCheckpointError):
        restore_into(build_net(), str(p))
    assert issubclass(CorruptCheckpointError, ValueError)


def test_missing_member_named_in_corrupt_error(tmp_path):
    p = tmp_path / "m.zip"
    write_model(build_net(), str(p))
    with zipfile.ZipFile(p) as z:
        members = {n: z.read(n) for n in z.namelist()}
    gutted = tmp_path / "gutted.zip"
    with zipfile.ZipFile(gutted, "w") as z:
        for name, blob in members.items():
            if name != "coefficients.npz":
                z.writestr(name, blob)
    with pytest.raises(CorruptCheckpointError, match="coefficients"):
        restore_into(build_net(), str(gutted))


# ----------------------------------------------------- manager: keep policies

def test_keep_last_rotation_and_keep_every_pinning(tmp_path):
    net = build_net()
    mgr = CheckpointManager(tmp_path, keep_last=2, keep_every=3)
    for i in range(1, 8):                       # 7 saves at iterations 1..7
        net.iteration = i
        mgr.save(net)
    # pinned: saves #1, #4, #7; unpinned survivors: the newest 2 (5, 6)
    live = sorted(c.iteration for c in mgr.checkpoints())
    assert live == [1, 4, 5, 6, 7]
    assert sorted(c.iteration for c in mgr.checkpoints() if c.pinned) \
        == [1, 4, 7]
    on_disk = sorted(p.name for p in tmp_path.glob("checkpoint_*.zip"))
    assert len(on_disk) == 5
    assert latest_checkpoint(tmp_path).endswith(
        "checkpoint_iter0000000007_epoch0000.zip")


def test_anchor_pin_survives_manager_restart(tmp_path):
    net = build_net()
    mgr = CheckpointManager(tmp_path, keep_last=2)
    for i in (4, 8):
        net.iteration = i
        mgr.save(net)
        mgr.set_anchor(i)
    assert mgr.anchor == 8
    # advancing the anchor releases the previous pin
    assert [c.iteration for c in mgr.checkpoints() if c.pinned] == [8]
    # a replacement rank 0 opens the same directory with a FRESH manager;
    # the anchor persisted in the manifest, so advancing it must unpin the
    # dead predecessor's anchor instead of leaking the pin forever
    fresh = CheckpointManager(tmp_path, keep_last=2)
    assert fresh.anchor == 8
    net.iteration = 12
    fresh.save(net)
    fresh.set_anchor(12)
    assert [c.iteration for c in fresh.checkpoints() if c.pinned] == [12]


def test_manager_recovers_from_damaged_manifest(tmp_path):
    net = build_net()
    mgr = CheckpointManager(tmp_path, keep_last=5)
    for i in (3, 9):
        net.iteration = i
        mgr.save(net)
    (tmp_path / "manifest.json").write_text("{torn garbage")
    recovered = CheckpointManager(tmp_path, keep_last=5)
    assert sorted(c.iteration for c in recovered.checkpoints()) == [3, 9]
    # a zip deleted out-of-band drops out of the ledger instead of 404ing
    os.unlink(latest_checkpoint(tmp_path))
    again = CheckpointManager(tmp_path, keep_last=5)
    assert [c.iteration for c in again.checkpoints()] == [3]
    assert latest_checkpoint(tmp_path).endswith("iter0000000003_epoch0000.zip")


def test_checkpoint_listener_requires_a_cadence(tmp_path):
    with pytest.raises(ValueError):
        CheckpointListener(tmp_path)


# -------------------------------------------------------- kill-and-resume fit

def test_fit_checkpoint_directory_saves_every_epoch(tmp_path):
    net = build_net(chunk_steps=64)
    net.fit(build_data(), epochs=2, checkpoint=str(tmp_path))
    names = sorted(p.name for p in tmp_path.glob("checkpoint_*.zip"))
    assert names == ["checkpoint_iter0000000006_epoch0001.zip",
                     "checkpoint_iter0000000012_epoch0002.zip"]
    assert read_meta(latest_checkpoint(tmp_path))["iteration"] == 12


def test_resume_guards():
    net = build_net()
    with pytest.raises(ValueError, match="resettable"):
        net.fit(np.ones((4, 4), np.float32),
                np.eye(3, dtype=np.float32)[[0, 1, 2, 0]],
                resume_from="/nonexistent")


def test_resume_from_empty_directory_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        build_net().fit(build_data(), epochs=1, resume_from=str(tmp_path))


def test_crash_mid_epoch_resume_is_bitwise_identical(tmp_path):
    """The tier-1 kill-and-resume: crash inside epoch 2 (iteration 12 of
    18), resume from the iteration-10 checkpoint — the resumed run must
    replay epoch 1 through the shuffling iterator, skip the 4 already
    trained batches of epoch 2, and finish bitwise-equal to the
    uninterrupted run (params AND Adam state AND counters)."""
    ref = build_net()
    ref.fit(build_data(), epochs=3)
    assert ref.iteration == 18 and ref.epoch == 3

    ckpt_dir = tmp_path / "ckpts"
    victim = build_net()
    crash = CrashAfter(at_iteration=11)
    victim.listeners.append(crash)          # fires BEFORE the ckpt listener
    listener = CheckpointListener(str(ckpt_dir), every_n_iterations=2)
    with pytest.raises(SimulatedCrash):
        victim.fit(build_data(), epochs=3, checkpoint=listener)
    assert crash.fired
    # chunked fit (4+2 steps/epoch): the delta trigger fires at the first
    # chunk boundary ≥ 2 past its anchor — iterations 6 and 10, not 12
    # (the crash beats the listener to iteration 12)
    assert sorted(c.iteration for c in listener.manager.checkpoints()) \
        == [6, 10]
    meta = read_meta(latest_checkpoint(ckpt_dir))
    assert (meta["iteration"], meta["epoch"], meta["epoch_batch"]) \
        == (10, 1, 4)

    resumed = build_net()
    resumed.fit(build_data(), epochs=3, resume_from=str(ckpt_dir))
    assert resumed.iteration == ref.iteration and resumed.epoch == ref.epoch
    _assert_bitwise_equal(ref, resumed)


@pytest.mark.slow
def test_sigkill_soak_resume_is_bitwise_identical(tmp_path):
    """The real thing: a subprocess training with checkpoints is SIGKILLed
    mid-run; whatever the kill left in the checkpoint directory must be
    loadable and resume to the uninterrupted result bitwise."""
    ckpt_dir = tmp_path / "ckpts"
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(_WORKER.parents[1])
                         + os.pathsep + env.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, str(_WORKER), str(ckpt_dir), "3", "40"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True)
    try:
        # kill only after real progress: two checkpoints on disk
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            zips = (sorted(ckpt_dir.glob("checkpoint_*.zip"))
                    if ckpt_dir.is_dir() else [])
            if len(zips) >= 2:
                break
            if proc.poll() is not None:
                pytest.fail("worker exited before the kill:\n"
                            + proc.stdout.read())
            time.sleep(0.02)
        else:
            pytest.fail("worker made no checkpoint progress in 240s")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
        assert proc.returncode != 0
    finally:
        if proc.poll() is None:
            proc.kill()

    found = latest_checkpoint(ckpt_dir)
    assert found is not None
    meta = read_meta(found)
    assert 0 < meta["iteration"] < 18       # genuinely killed mid-run

    ref = build_net()
    ref.fit(build_data(), epochs=3)
    resumed = build_net()
    resumed.fit(build_data(), epochs=3, resume_from=str(ckpt_dir))
    assert resumed.iteration == ref.iteration == 18
    _assert_bitwise_equal(ref, resumed)


# -------------------------------------------------------------- retry/backoff

class _FakeTime:
    """Injectable clock+sleeper: no real sleeping in tier-1."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def test_retry_succeeds_after_transient_failures():
    import random
    ft = _FakeTime()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientError("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.05, max_delay=2.0)
    assert retry_call(flaky, policy=policy, component="test_ok",
                      sleep=ft.sleep, clock=ft.clock,
                      rng=random.Random(0)) == "ok"
    assert calls["n"] == 3
    # two backoffs, decorrelated-jitter bounded: [base, prev*3] ∩ [0, max]
    assert len(ft.sleeps) == 2
    assert 0.05 <= ft.sleeps[0] <= 0.15
    assert 0.05 <= ft.sleeps[1] <= min(2.0, ft.sleeps[0] * 3)


def test_retry_exhausts_attempts():
    import random
    ft = _FakeTime()

    def always():
        raise TransientError("down")

    with pytest.raises(RetriesExhaustedError) as ei:
        retry_call(always, policy=RetryPolicy(max_attempts=4, base_delay=0.1,
                                              max_delay=0.5),
                   component="test_exhaust", sleep=ft.sleep, clock=ft.clock,
                   rng=random.Random(1))
    assert ei.value.attempts == 4
    assert len(ft.sleeps) == 3
    assert all(0.1 <= s <= 0.5 for s in ft.sleeps)
    assert isinstance(ei.value.__cause__, TransientError)


def test_retry_deadline_never_sleeps_past_budget():
    import random
    ft = _FakeTime()

    def slow_fail():
        ft.t += 0.2                         # each attempt costs 200ms
        raise TransientError("down")

    policy = RetryPolicy(max_attempts=None, base_delay=0.4, max_delay=10.0,
                         deadline=1.0)
    with pytest.raises(RetriesExhaustedError, match="deadline"):
        retry_call(slow_fail, policy=policy, component="test_deadline",
                   sleep=ft.sleep, clock=ft.clock, rng=random.Random(2))
    # total fake time ≤ deadline + one attempt's cost: the backoff was
    # capped to the remaining budget instead of sleeping through it
    assert ft.t <= 1.0 + 0.2 + 1e-6


def test_retry_give_up_aborts_promptly():
    ft = _FakeTime()
    flag = {"stop": False}

    def failing():
        flag["stop"] = True                 # shutdown begins mid-call
        raise TransientError("down")

    with pytest.raises(RetriesExhaustedError, match="give_up"):
        retry_call(failing, policy=RetryPolicy(max_attempts=None),
                   component="test_giveup", give_up=lambda: flag["stop"],
                   sleep=ft.sleep, clock=ft.clock)
    assert ft.sleeps == []                  # no backoff after the abort flag


def test_retry_fatal_raises_immediately():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        retry_call(fatal, component="test_fatal",
                   sleep=lambda s: pytest.fail("slept on a fatal error"))
    assert calls["n"] == 1


def test_default_classifier():
    retryable = [TransientError("x"), ServerOverloadedError("x"),
                 ConnectionError("x"), TimeoutError("x"), BrokenPipeError(),
                 urllib.error.URLError("refused"),
                 urllib.error.HTTPError("http://x", 429, "too many", {},
                                        None),
                 urllib.error.HTTPError("http://x", 503, "unavail", {},
                                        None)]
    fatal = [FatalError("x"), DeadlineExceededError("x"), ValueError("x"),
             KeyError("x"), FileNotFoundError("x"),
             urllib.error.HTTPError("http://x", 404, "nope", {}, None),
             urllib.error.HTTPError("http://x", 400, "bad", {}, None)]
    assert all(default_classifier(e) for e in retryable)
    assert not any(default_classifier(e) for e in fatal)


def test_retry_metrics_visible_on_metrics_endpoint():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 2:
            raise TransientError("blip")
        return 1

    retry_call(flaky, policy=RetryPolicy(base_delay=0.0, max_delay=0.0),
               component="metrics_probe", sleep=lambda s: None)
    srv = InferenceServer(build_net(), port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=30) as r:
            body = r.read().decode()
    finally:
        srv.stop()
    assert "dl4jtpu_retry_attempts_total" in body
    assert 'component="metrics_probe"' in body
    assert 'outcome="error"' in body and 'outcome="success"' in body


# ------------------------------------------------------------ streaming/kafka

def test_streaming_iterator_detects_stalled_producer():
    from deeplearning4j_tpu.data.streaming import StreamingDataSetIterator
    it = StreamingDataSetIterator(2, stall_timeout=0.15)
    it.push(np.zeros(4, np.float32), np.zeros(3, np.float32))
    with pytest.raises(StreamStalledError):
        next(iter(it))


def test_kafka_pump_retries_polls_and_skips_corrupt_records():
    from deeplearning4j_tpu.data.kafka import (
        InMemoryBroker, NDArrayPublisher, NDArrayPubSubRoute)
    base = InMemoryBroker()
    topic = "resilience_topic"
    # first poll fails with a transient connection reset → pump retries
    broker = FlakyBroker(base, fail_polls={0: ConnectionError("reset")})
    pub = NDArrayPublisher(broker, topic)
    for i in range(4):
        pub.publish(np.full(4, float(i), np.float32),
                    np.eye(3, dtype=np.float32)[i % 3])
    base.send(topic, b"!!not a record!!")    # poison message
    route = NDArrayPubSubRoute(broker, topic, batch_size=2)
    route.start()
    try:
        it = iter(route.iterator)
        ds1, ds2 = next(it), next(it)
    finally:
        route.stop()
    assert broker.poll_calls >= 2            # the failed poll was retried
    got = np.concatenate([ds1.features, ds2.features])[:, 0].tolist()
    assert got == [0.0, 1.0, 2.0, 3.0]       # order preserved, none lost
    corrupt = get_registry().counter(
        "dl4jtpu_stream_corrupt_records_total",
        "Undecodable records skipped by streaming consumers.",
        ("topic",)).labels(topic=topic)
    assert corrupt.value >= 1


# ----------------------------------------------------------- serving overload

def _post_raw(url, payload, timeout=60):
    data = (payload if isinstance(payload, bytes)
            else json.dumps(payload).encode())
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        try:
            return e.code, json.loads(raw)
        except json.JSONDecodeError:
            return e.code, {"raw": raw}


def _get_raw(url, timeout=60):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _predict_payload(n_rows, deadline_ms=None):
    payload = {"ndarray": ndarray_to_b64(np.ones((n_rows, 4), np.float32))}
    if deadline_ms is not None:
        payload["deadline_ms"] = deadline_ms
    return payload


def test_http_storm_429_deadline_and_drain():
    net = build_net()
    base = net.serving_engine()
    base.warmup((4,), max_batch=8)
    gate = threading.Event()                 # holds the "device" busy
    eng = FlakyEngine(base, gate=gate)
    srv = InferenceServer(net, port=0, engine=eng, max_queue=2,
                          max_latency_ms=1.0).start()
    url = f"http://127.0.0.1:{srv.port}"
    results = {}

    def post(name, n_rows, deadline_ms=None):
        results[name] = _post_raw(url + "/predict",
                                  _predict_payload(n_rows, deadline_ms))

    threads = []
    try:
        t = threading.Thread(target=post, args=("r1", 2))
        t.start()
        threads.append(t)
        _wait_for(lambda: eng.calls >= 1, what="r1 riding the gated call")
        for name, rows, dl in (("r2", 3, 80.0), ("r3", 1, None)):
            t = threading.Thread(target=post, args=(name, rows, dl))
            t.start()
            threads.append(t)
        _wait_for(lambda: srv.batcher.stats()["queue_depth"] == 2,
                  what="queue to fill")
        # queue full: shed FAST with 429 — the handler never blocks
        t0 = time.perf_counter()
        code, body = _post_raw(url + "/predict", _predict_payload(1))
        assert (code, body["error"]["type"]) == (429, "overloaded")
        assert time.perf_counter() - t0 < 2.0
        code, body = _get_raw(url + "/healthz")
        assert (code, body["status"]) == (200, "degraded")
        time.sleep(0.12)                     # r2's 80ms deadline expires
        gate.set()
        for t in threads:
            t.join(timeout=60)
        assert results["r1"][0] == 200
        assert results["r3"][0] == 200
        assert ndarray_from_b64(results["r3"][1]["ndarray"]).shape == (1, 3)
        assert results["r2"][0] == 504
        assert results["r2"][1]["error"]["type"] == "deadline_exceeded"
        # the expired request never rode a device call: the engine saw
        # exactly r1's 2 rows + r3's 1 row, never r2's 3
        assert eng.rows_seen == 3
        rej = srv.batcher.stats()["rejected"]
        assert rej["queue_full"] >= 1 and rej["deadline"] >= 1
        # drain: healthz flips to 503 draining, predicts get fast 503s
        srv.batcher.stop()
        code, body = _get_raw(url + "/healthz")
        assert (code, body["status"]) == (503, "draining")
        code, body = _post_raw(url + "/predict", _predict_payload(1))
        assert (code, body["error"]["type"]) == (503, "draining")
    finally:
        gate.set()
        srv.stop()


def test_http_bad_request_vs_engine_fault_classification():
    net = build_net()
    eng = FlakyEngine(net.serving_engine(),
                      fail_calls={0: RuntimeError("injected device fault")})
    srv = InferenceServer(net, port=0, engine=eng,
                          max_latency_ms=1.0).start()
    url = f"http://127.0.0.1:{srv.port}"
    try:
        # --- 400s: client problems, never 500 ---
        code, body = _post_raw(url + "/predict", {})
        assert (code, body["error"]["type"]) == (400, "bad_request")
        assert "ndarray" in body["error"]["message"]
        code, body = _post_raw(url + "/predict",
                               {"ndarray": {"shape": [2], "data": "!"}})
        assert (code, body["error"]["type"]) == (400, "bad_request")
        code, body = _post_raw(url + "/predict", b"{not json")
        assert (code, body["error"]["type"]) == (400, "bad_request")
        wrong_width = {"ndarray": ndarray_to_b64(
            np.ones((2, 5), np.float32))}   # model wants 4 features
        code, body = _post_raw(url + "/predict", wrong_width)
        assert (code, body["error"]["type"]) == (400, "bad_request")
        assert "(2, 5)" in body["error"]["message"]
        payload = _predict_payload(1)
        payload["deadline_ms"] = "soon"
        code, body = _post_raw(url + "/predict", payload)
        assert (code, body["error"]["type"]) == (400, "bad_request")
        code, body = _post_raw(url + "/nope", {})
        assert (code, body["error"]["type"]) == (404, "not_found")
        assert eng.calls == 0               # none of the above hit the engine
        # --- 500: a genuine engine fault, reported then recovered ---
        code, body = _post_raw(url + "/predict", _predict_payload(2))
        assert (code, body["error"]["type"]) == (500, "internal")
        assert "injected device fault" in body["error"]["message"]
        assert "injected device fault" in srv.last_error
        code, body = _post_raw(url + "/predict", _predict_payload(2))
        assert code == 200                  # fault was one-shot; recovered
        assert _get_raw(url + "/healthz")[1]["status"] == "ok"
    finally:
        srv.stop()


def test_batcher_stop_race_settles_every_future():
    net = build_net()
    base = net.serving_engine()
    base.warmup((4,), max_batch=16)
    gate = threading.Event()
    eng = FlakyEngine(base, gate=gate)
    mb = MicroBatcher(eng, max_batch=16, max_latency_ms=1.0).start()
    x = np.zeros((1, 4), np.float32)
    futs = [mb.submit(x) for _ in range(6)]  # first rides, the rest queue
    racing = []

    def spam():
        for _ in range(200):
            try:
                racing.append(mb.submit(x))
            except BatcherStoppedError:
                return

    spammer = threading.Thread(target=spam)
    stopper = threading.Thread(target=mb.stop)
    spammer.start()
    stopper.start()
    time.sleep(0.05)
    gate.set()
    stopper.join(timeout=60)
    spammer.join(timeout=60)
    assert not stopper.is_alive() and not spammer.is_alive()
    # every Future settled: flushed with a result, or rejected — never hung
    for f in futs + racing:
        assert f.done()
        exc = f.exception()
        assert exc is None or isinstance(exc, BatcherStoppedError)
    assert all(f.exception() is None for f in futs)  # pre-stop work flushed
    with pytest.raises(BatcherStoppedError):
        mb.submit(x)


# -------------------------------------------------------------- earlystopping

def test_early_stopping_trainer_accepts_tuple_iterator():
    from deeplearning4j_tpu.earlystopping.early_stopping import (
        EarlyStoppingConfiguration, EarlyStoppingTrainer,
        MaxEpochsTerminationCondition)
    net = build_net()
    rs = np.random.RandomState(3)
    data = [(rs.rand(8, 4).astype(np.float32),
             np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)])
            for _ in range(2)]
    cfg = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(2)])
    result = EarlyStoppingTrainer(cfg, net, data).fit()
    assert result.total_epochs == 2
    assert net.iteration == 4               # 2 epochs × 2 tuple batches
    assert result.best_model is net
