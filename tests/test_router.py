"""Replicated serving tier (serving/router.py + serving/replica.py).

The load-bearing claims pinned here:
- the router balances /predict over replicas with BITWISE parity to a
  direct replica call, and stamps every response with an x-request-id;
- a replica answering 5xx is failed over transparently, then ejected
  after consecutive failures (healthy → suspect → ejected), with the
  ejection and the failover both visible in /metrics;
- the shared retry budget bounds failover: once spent, the client gets a
  FAST 503 ``retry_budget_exhausted`` instead of a retry storm — over
  real sockets, with a fake clock keeping the health model frozen;
- an ejected replica is re-admitted through backoff-spaced probes
  (ejected → recovering → healthy), driven deterministically by a fake
  clock;
- a hedged /predict sends a second copy after the hedge delay and the
  first answer wins (hedges fired AND won observed);
- per-tenant quotas and priority shedding answer 429 at the router
  before any upstream attempt;
- a rolling restart under live traffic completes with ZERO failed
  requests (drain → restart → health-gate → re-admit);
- (slow) the chaos soak: 3 subprocess replicas under a mixed
  /predict+/generate storm, one SIGKILLed and one rolling-restarted
  mid-storm — zero failed in-deadline requests, ejection + failover +
  re-admission all observed via /metrics;
- prefix-affinity routing sends shared-prefix /generate traffic to the
  replica advertising the prompt's KV chain heads, NEVER overrides the
  health state machine, forgets a replica's digest after a weight-swap
  cache clear, and role-aware placement steers fresh prefills away from
  decode-dedicated replicas (docs/SERVING_TIER.md "Disaggregation").
"""

import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.serving import (InferenceClient, InProcessReplica,
                                        ReplicaProcess, RetryBudget, Router)


class _FakeTime:
    """Injectable clock+sleeper for the router's HEALTH model: probe
    cadence and ejection backoff advance without real waiting."""

    def __init__(self):
        self.t = 0.0

    def clock(self):
        return self.t

    def sleep(self, s):
        self.t += s


def _counter_value(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    want = tuple(str(labels[k]) for k in fam.labelnames)
    for key, child in fam.children():
        if key == want:
            return child.value
    return 0.0


def _mk_tier(n=2, model="mlp", **router_kw):
    reps = [InProcessReplica(model=model).start() for _ in range(n)]
    router_kw.setdefault("probe_interval", None)
    router = Router([r.url for r in reps], port=0, **router_kw).start()
    cli = InferenceClient(f"http://127.0.0.1:{router.port}")
    return reps, router, cli


def _teardown(reps, router, cli):
    cli.close()
    router.stop()
    for r in reps:
        r.stop()


def _set_chaos(rep, **cfg):
    """Reconfigure a replica's fault injector over its own /chaos endpoint
    (the same remote surface the subprocess soak uses)."""
    c = InferenceClient(rep.url, retries=1)
    try:
        st, body, _ = c.post_raw("/chaos", json.dumps(cfg).encode())
        assert st == 200, body
    finally:
        c.close()


X = np.arange(12, dtype=np.float32).reshape(3, 4) / 10.0


# ------------------------------------------------------- routing + request ids

def test_router_balances_with_parity_and_request_ids():
    reps, router, cli = _mk_tier(n=2)
    try:
        direct = InferenceClient(reps[0].url)
        want = direct.predict(X)
        direct.close()
        for _ in range(6):
            out = cli.predict(X)
            assert np.array_equal(out, want)      # replicas share the seed
        # both replicas actually served (least-outstanding + round-robin)
        for r in reps:
            assert _counter_value("dl4jtpu_router_upstream_attempts_total",
                                  router=router.id, replica=r.url) > 0
        # x-request-id: minted by the router, echoed by the replica
        st, body, hdrs = cli.post_raw(
            "/predict", json.dumps({"ndarray": None}).encode())
        assert st == 400                          # replica-side validation
        rid = hdrs.get("x-request-id")
        assert rid and rid.startswith("req-")
        assert json.loads(body)["error"]["request_id"].startswith(rid)
        # a caller-supplied id is preserved end to end
        st, body, hdrs = cli.post_raw(
            "/predict", json.dumps({"ndarray": None}).encode(),
            headers={"x-request-id": "trace-me-7"})
        assert hdrs.get("x-request-id") == "trace-me-7"
    finally:
        _teardown(reps, router, cli)


# ------------------------------------------------------------------- failover

def test_failover_on_replica_5xx_then_ejection():
    reps, router, cli = _mk_tier(n=2, hedge=False)
    try:
        _set_chaos(reps[0], fail_next=100)        # replica 0 browns out
        for _ in range(8):
            out = cli.predict(X)                  # every request still served
            assert out.shape == (3, 3)
        states = {u: r["state"]
                  for u, r in cli.stats()["replicas"].items()}
        assert states[reps[0].url] == "ejected"
        assert states[reps[1].url] == "healthy"
        assert _counter_value("dl4jtpu_router_ejections_total",
                              router=router.id, replica=reps[0].url) >= 1
        assert _counter_value("dl4jtpu_router_upstream_failures_total",
                              router=router.id, replica=reps[0].url,
                              kind="5xx") >= 1
        # once ejected, traffic stops reaching replica 0 entirely
        before = _counter_value("dl4jtpu_router_upstream_attempts_total",
                                router=router.id, replica=reps[0].url)
        for _ in range(4):
            cli.predict(X)
        after = _counter_value("dl4jtpu_router_upstream_attempts_total",
                               router=router.id, replica=reps[0].url)
        assert after == before
    finally:
        _teardown(reps, router, cli)


def test_retry_budget_exhaustion_fails_fast(  # satellite: budget semantics
        ):
    ft = _FakeTime()
    reps, router, cli = _mk_tier(
        n=2, hedge=False, clock=ft.clock, sleep=ft.sleep,
        eject_after=1000,       # keep both replicas in rotation: every
                                # request exercises failover, not ejection
        retry_budget=RetryBudget(ratio=0.0, initial=2.0, cap=2.0))
    try:
        for r in reps:
            _set_chaos(r, fail_next=1000)         # full brownout
        # requests 1..2: failover runs (and also fails) — one token each
        for _ in range(2):
            st, body, _ = cli.post_raw(
                "/predict", json.dumps({"ndarray": None}).encode())
            assert st == 502
            assert json.loads(body)["error"]["type"] == "upstream_failed"
        assert router.budget.balance == 0.0
        # request 3: budget spent → fast 503, exactly ONE upstream attempt
        before = sum(_counter_value(
            "dl4jtpu_router_upstream_attempts_total",
            router=router.id, replica=r.url) for r in reps)
        t0 = time.perf_counter()
        st, body, _ = cli.post_raw(
            "/predict", json.dumps({"ndarray": None}).encode())
        elapsed = time.perf_counter() - t0
        assert st == 503
        assert json.loads(body)["error"]["type"] == "retry_budget_exhausted"
        assert elapsed < 1.0                      # fast-fail, no backoff
        after = sum(_counter_value(
            "dl4jtpu_router_upstream_attempts_total",
            router=router.id, replica=r.url) for r in reps)
        assert after - before == 1
        # healthy traffic replenishes the bucket: deposits resume failover
        router.budget.ratio = 1.0
        _set_chaos(reps[1], fail_next=0)
        out = cli.predict(X)
        assert out.shape == (3, 3)
    finally:
        _teardown(reps, router, cli)


# ------------------------------------------------------- ejection → recovery

def test_ejected_replica_recovers_through_probes():
    ft = _FakeTime()
    reps, router, cli = _mk_tier(n=2, hedge=False, eject_after=2,
                                 clock=ft.clock, sleep=ft.sleep,
                                 probe_backoff_base=4.0)
    try:
        rep0 = router.replicas[reps[0].url]
        _set_chaos(reps[0], fail_next=1000)
        for _ in range(6):
            cli.predict(X)
        assert rep0.state == "ejected"
        # probe during backoff: skipped, replica stays out
        router.probe_once()
        assert rep0.state == "ejected"
        # backoff expires but the replica is still sick: re-ejected with a
        # DOUBLED backoff window
        ft.t = rep0.ejected_until + 0.01
        first_backoff = rep0.backoff
        router.probe_once()       # healthz passes (chaos gates only the
        assert rep0.state == "recovering"         # data paths) → provisional
        for _ in range(2):        # round-robin guarantees rep0 gets traffic
            cli.predict(X)                        # ...which still fails
        assert rep0.state == "ejected"
        assert rep0.backoff == 2 * first_backoff
        # now it actually heals: probe re-admits, real success completes it
        _set_chaos(reps[0], fail_next=0)
        ft.t = rep0.ejected_until + 0.01
        router.probe_once()
        assert rep0.state == "recovering"
        assert _counter_value("dl4jtpu_router_readmissions_total",
                              router=router.id, replica=reps[0].url) >= 1
        for _ in range(4):
            cli.predict(X)
        assert rep0.state == "healthy"
        assert rep0.backoff == 0.0
    finally:
        _teardown(reps, router, cli)


# -------------------------------------------------------------------- hedging

def test_hedged_predict_first_answer_wins():
    reps, router, cli = _mk_tier(n=2, hedge=True, hedge_delay_ms=40.0)
    try:
        direct = InferenceClient(reps[0].url)
        want = direct.predict(X)
        direct.close()
        _set_chaos(reps[0], latency_ms=1500.0)    # one slow replica
        t0 = time.perf_counter()
        for _ in range(4):                        # round-robin: ~half the
            out = cli.predict(X)                  # primaries land slow
            assert np.array_equal(out, want)
        elapsed = time.perf_counter() - t0
        fired = _counter_value("dl4jtpu_router_hedges_total",
                               router=router.id, outcome="fired")
        won = _counter_value("dl4jtpu_router_hedges_total",
                             router=router.id, outcome="won")
        assert fired >= 1
        assert won >= 1
        # the hedge rescued the p99: nothing waited out the full 1.5s
        assert elapsed < 0.5 * 1.5 * 4
    finally:
        _teardown(reps, router, cli)


# ------------------------------------------------------------ quotas + sheds

def test_tenant_quota_and_priority_shedding():
    reps, router, cli = _mk_tier(n=1, hedge=False, tenant_quota=2,
                                 max_outstanding=8)
    try:
        body = json.dumps({"ndarray": None}).encode()
        # tenant at quota → 429 tenant_quota before any upstream attempt
        router._tenant_outstanding["acme"] = 2
        before = _counter_value("dl4jtpu_router_upstream_attempts_total",
                                router=router.id, replica=reps[0].url)
        st, out, _ = cli.post_raw("/predict", body,
                                  headers={"x-tenant": "acme"})
        assert st == 429
        assert json.loads(out)["error"]["type"] == "tenant_quota"
        assert _counter_value("dl4jtpu_router_upstream_attempts_total",
                              router=router.id,
                              replica=reps[0].url) == before
        # other tenants are unaffected
        st, _, _ = cli.post_raw("/predict", body,
                                headers={"x-tenant": "other"})
        assert st == 400                          # reached the replica
        router._tenant_outstanding["acme"] = 0
        # priority shedding: at capacity, low and normal shed, high rides
        # the overflow band
        router._total_outstanding = 8
        st, out, _ = cli.post_raw("/predict", body,
                                  headers={"x-priority": "low"})
        assert st == 429
        st, out, _ = cli.post_raw("/predict", body)
        assert st == 429
        assert json.loads(out)["error"]["type"] == "overloaded"
        st, _, _ = cli.post_raw("/predict", body,
                                headers={"x-priority": "high"})
        assert st == 400                          # admitted → replica 400
        router._total_outstanding = 0
        assert _counter_value("dl4jtpu_router_sheds_total",
                              router=router.id, reason="tenant_quota") >= 1
        assert _counter_value("dl4jtpu_router_sheds_total",
                              router=router.id, reason="priority") >= 2
    finally:
        _teardown(reps, router, cli)


# ------------------------------------------------------------ rolling restart

def test_rolling_restart_zero_downtime():
    reps, router, cli = _mk_tier(n=2, hedge=False, probe_interval=0.2)
    try:
        by_url = {r.url: r for r in reps}
        stop = threading.Event()
        failures, served = [], [0]

        def storm():
            c = InferenceClient(f"http://127.0.0.1:{router.port}",
                                retries=1)
            while not stop.is_set():
                try:
                    c.predict(X)
                    served[0] += 1
                except Exception as e:   # noqa: BLE001 — any failure counts
                    failures.append(repr(e))
            c.close()

        threads = [threading.Thread(target=storm) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            while served[0] < 5:                  # traffic is flowing
                time.sleep(0.01)
            router.rolling_restart(
                lambda url: by_url[url].restart(),
                warmup_shape=(4,), ready_timeout=60.0)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not failures, failures[:3]
        assert served[0] > 10
        states = {u: r["state"]
                  for u, r in router.stats()["replicas"].items()}
        assert all(s == "healthy" for s in states.values())
        for r in reps:
            assert _counter_value("dl4jtpu_router_readmissions_total",
                                  router=router.id, replica=r.url) >= 1
    finally:
        _teardown(reps, router, cli)


# ----------------------------------------------------------------- chaos soak

@pytest.mark.slow
def test_chaos_soak_kill_and_roll_replicas_mid_storm(tmp_path):
    """3 subprocess replicas; mid-storm one is SIGKILLed (then restarted)
    and another rolling-restarted. Every in-deadline request must succeed,
    and /metrics must show ejection, failover, and re-admission."""
    reps = [ReplicaProcess(str(tmp_path), model="charlstm",
                           name=f"replica{i}").start()
            for i in range(3)]
    for r in reps:
        r.wait_ready()
    router = Router([r.url for r in reps], port=0, probe_interval=0.25,
                    hedge=True, hedge_delay_ms=250.0,
                    upstream_timeout=60.0).start()
    base = f"http://127.0.0.1:{router.port}"
    by_url = {r.url: r for r in reps}
    stop = threading.Event()
    failures, served = [], [0]
    count_lock = threading.Lock()

    def storm(seed):
        rs = np.random.RandomState(seed)
        c = InferenceClient(base, retries=1, timeout=60.0)
        while not stop.is_set():
            try:
                if rs.rand() < 0.5:
                    x = np.zeros((2, 6, 16), np.float32)
                    x[:, np.arange(6), rs.randint(0, 16, 6)] = 1.0
                    c.predict(x)
                else:
                    c.generate(rs.randint(0, 16, 3).tolist(),
                               max_new_tokens=6, seed=int(seed))
                with count_lock:
                    served[0] += 1
            except Exception as e:   # noqa: BLE001 — every failure counts:
                failures.append(repr(e))   # the soak's claim is ZERO failed
        c.close()

    threads = [threading.Thread(target=storm, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    try:
        while served[0] < 20:                     # storm is established
            time.sleep(0.05)
        reps[0].kill()                            # crash: no drain, no FIN
        while served[0] < 60:                     # tier absorbs the crash
            time.sleep(0.05)
        reps[0].start().wait_ready()              # ...and the replacement
        router.rolling_restart(                   # roll another mid-storm
            lambda url: (by_url[url].stop(), by_url[url].start(),
                         by_url[url].wait_ready()),
            warmup_shape=None, ready_timeout=120.0)
        while served[0] < 100:
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join()
    try:
        assert not failures, failures[:5]
        assert served[0] >= 100
        import urllib.request
        text = urllib.request.urlopen(f"{base}/metrics").read().decode()

        def total(name):
            return sum(float(line.rsplit(" ", 1)[1])
                       for line in text.splitlines()
                       if line.startswith(name + "{"))
        assert total("dl4jtpu_router_ejections_total") >= 1
        assert total("dl4jtpu_router_readmissions_total") >= 1
        assert total("dl4jtpu_router_upstream_failures_total") >= 1
        states = {u: r["state"]
                  for u, r in router.stats()["replicas"].items()}
        assert all(s == "healthy" for s in states.values()), states
    finally:
        router.stop()
        for r in reps:
            r.stop()


# --------------------------------------------------------- prefix affinity

def _mk_kv_tier(roles=("mixed", "mixed"), **router_kw):
    """Paged tinyattn fleet: the replica kind whose decode state the
    prefix cache / migration / affinity machinery can actually share."""
    kw = dict(chaos=False, kv="paged", kv_block_size=8, kv_blocks=32,
              prefix_cache=True, chunk_tokens=8, max_len=64, slots=2)
    reps = [InProcessReplica(model="tinyattn", role=r, **kw).start()
            for r in roles]
    router_kw.setdefault("probe_interval", None)
    router_kw.setdefault("hedge", False)
    router = Router([r.url for r in reps], port=0, **router_kw).start()
    cli = InferenceClient(f"http://127.0.0.1:{router.port}")
    return reps, router, cli


def test_prefix_affinity_routes_to_chain_holder_never_over_health():
    from deeplearning4j_tpu.serving.router import ReplicaState
    reps, router, cli = _mk_kv_tier()
    a, b = reps
    try:
        rng = np.random.default_rng(3)
        prompt = [int(t) for t in rng.integers(0, 16, size=20)]
        ca = InferenceClient(a.url)
        try:
            ref = ca.generate(prompt, max_new_tokens=4)
        finally:
            ca.close()
        router.refresh_affinity()
        assert router.replicas[a.url].kv_block_size == 8
        assert len(router.replicas[a.url].chain_heads) == 2
        # the shared-prefix request lands on the chain holder: its prefix
        # cache takes the hit and the router counts an affinity hit
        out = cli.generate(prompt, max_new_tokens=4)
        assert out["tokens"] == ref["tokens"]
        assert a.srv.decode_engine.stats()["kv"]["prefix_hits"] >= 1
        assert _counter_value("dl4jtpu_router_affinity_requests_total",
                              router=router.id, outcome="hit") >= 1
        # affinity NEVER overrides health: with the chain holder ejected
        # the same prompt serves (cold) from the other replica
        router.replicas[a.url].state = ReplicaState.EJECTED
        out2 = cli.generate(prompt, max_new_tokens=4)
        assert out2["tokens"] == ref["tokens"]
        assert b.srv.decode_engine.stats()["kv"]["prefill_tokens"] > 0
        router.replicas[a.url].state = ReplicaState.HEALTHY
        # swap-then-affinity regression: a weight-swap cache clear must
        # erase the advertised digest at the next refresh — a router
        # still steering by the stale digest would fan stale-KV risk
        # fleet-wide
        a.srv.decode_engine._prefix.clear()
        router.refresh_affinity()
        assert len(router.replicas[a.url].chain_heads) == 0
        hint = router._affinity_hint(
            "/generate", json.dumps({"tokens": prompt}).encode())
        assert not hint or a.url not in hint
    finally:
        _teardown(reps, router, cli)


def test_role_preference_steers_fresh_prefill():
    reps, router, cli = _mk_kv_tier(roles=("decode", "prefill"))
    dec_rep, pre_rep = reps
    try:
        router.refresh_affinity()
        assert router.replicas[pre_rep.url].role == "prefill"
        rng = np.random.default_rng(9)
        # fresh prompts (no chain anywhere): every primary pick should
        # prefer the prefill-role replica over the decode-dedicated one
        for _ in range(3):
            prompt = [int(t) for t in rng.integers(0, 16, size=20)]
            cli.generate(prompt, max_new_tokens=2)
        pre = pre_rep.srv.decode_engine.stats()["kv"]["prefill_tokens"]
        dec = dec_rep.srv.decode_engine.stats()["kv"]["prefill_tokens"]
        assert pre > 0 and dec == 0, (pre, dec)
        # ...but a decode-role replica is still a full server: with the
        # prefill replica gone it takes the work (preference, not policy)
        router.replicas[pre_rep.url].admin_down = True
        prompt = [int(t) for t in rng.integers(0, 16, size=20)]
        out = cli.generate(prompt, max_new_tokens=2)
        assert len(out["tokens"]) == 2
        assert dec_rep.srv.decode_engine.stats()["kv"]["prefill_tokens"] > 0
    finally:
        _teardown(reps, router, cli)
