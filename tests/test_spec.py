"""Speculative decoding subsystem (serving/spec/).

The load-bearing claims pinned here:

- LOSSLESS: speculative output is token-for-token the non-speculative
  engine's — greedy AND seeded temperature sampling (the fixed-seed
  trace form of rejection sampling: draft, verify and the plain step
  share one oracle) — for the charRNN (recurrent carries → snapshot
  rewind) and the causal transformer (positional KV → causal-mask
  rewind), over dense and paged KV, for LINEAR drafts and branching
  TREES (a linear draft is the (1,)*k tree — one code path);
- COMPILE PINS: one step, one verify, one draft program per engine
  regardless of k, tree shape, arrival schedule, prompt lengths or
  slot mix;
- REWIND REGRESSION: a slot whose draft proposals are ALL rejected
  (every tree node, every tick) emits exactly the oracle's correction
  tokens and continues bitwise — paged KV, prefix cache on and off
  (garbage KV written for rejected positions is never read and never
  published, including by a SECOND request re-claiming the garbage
  writer's published prefix blocks);
- SELF-drafting (spec/selfdraft.py): the target as its own int8 draft
  and as an early-exit truncated stack, both still lossless;
- acceptance rule semantics (leading match + correction token) and the
  tree walk's static tables;
- the acceptance-rate stat and gauge are 0.0 (not NaN) while nothing
  has been drafted;
- ``generate_naive`` and the engine share the sampling oracle at
  temperature > 0, not just under greedy argmax.
"""

import threading

import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import DecodeEngine, generate_naive
from deeplearning4j_tpu.serving.spec import (SpecConfig, TreeSpec,
                                             accept_length, parse_kvec)
from deeplearning4j_tpu.zoo.simple import TinyTransformer

V = 13


def _lstm_net(seed=7, width=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=width, activation="tanh"))
            .layer(LSTM(n_out=width, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build())
    return MultiLayerNetwork(conf).init()


def _transformer(seed=7):
    return TinyTransformer(vocab_size=V, n_layers=2, d_model=32, n_heads=4,
                           max_len=64, seed=seed).init()


def _draft_transformer():
    return TinyTransformer(vocab_size=V, n_layers=1, d_model=16, n_heads=2,
                           max_len=64, seed=3).init()


CASES = [([1, 2, 3], 0.0, 0, 0),        # greedy
         ([5], 0.0, 0, 0),              # one-token prompt: verify wipes
         ([0, 4, 2, 9, 7], 0.9, 123, 0),  # seeded sampling
         ([3, 3], 0.7, 7, 5)]           # sampling + top-k filter


def _run_cases(eng, max_new=18):
    return [eng.generate(p, max_new_tokens=max_new, seed=s, temperature=t,
                         top_k=k, timeout=120)["tokens"]
            for p, t, s, k in CASES]


def _assert_spec_pins(eng, step_programs=1):
    st = eng.stats()
    assert st["compiled_programs"] == step_programs, st
    assert st["spec"]["verify_programs"] == 1, st
    assert st["spec"]["draft_programs"] == 1, st
    assert st["spec"]["drafted_tokens"] > 0


# ------------------------------------------------------- acceptance rule

def test_accept_length_leading_match_plus_correction():
    oracle = jnp.array([[5, 6, 7, 8], [5, 6, 7, 8], [5, 6, 7, 8],
                        [5, 6, 7, 8]])
    draft = jnp.array([[5, 6, 9, 8],    # match, match, miss, (match)
                       [5, 6, 7, 8],    # full match
                       [9, 6, 7, 8],    # first-token miss
                       [5, 6, 7, 8]])
    n_in = jnp.array([4, 4, 4, 2])      # last row: short window
    a, e = accept_length(oracle, draft, n_in)
    # a trailing match AFTER a miss must not count (cumprod, not sum)
    assert a.tolist() == [2, 4, 0, 2]
    # emitted = accepted + correction token, capped at the window
    assert e.tolist() == [3, 4, 1, 2]
    a0, e0 = accept_length(oracle, draft, jnp.array([0, 0, 0, 0]))
    assert a0.tolist() == [0, 0, 0, 0] and e0.tolist() == [0, 0, 0, 0]


# ------------------------------------------------------- tree tables

def test_tree_spec_tables():
    tr = TreeSpec((3, 2))
    # node 0 = root; depth-1 group = {1, 2, 3} (spine child 1);
    # depth-2 group = {4, 5} hanging off node 1 (the spine)
    assert tr.n_nodes == 6 and tr.d == 2
    assert tr.parent.tolist() == [-1, 0, 0, 0, 1, 1]
    assert tr.depth.tolist() == [0, 1, 1, 1, 2, 2]
    assert tr.spine.tolist() == [0, 1, 4]
    assert tr.first.tolist() == [1, 4]
    # row n of anc_at_depth is node n's root-path (side nodes saturate)
    assert tr.anc_at_depth[5].tolist() == [0, 1, 5]
    assert tr.anc_at_depth[2].tolist() == [0, 2, 2]
    anc = tr.ancestor_matrix()
    assert anc[5].tolist() == [True, True, False, False, False, True]
    # the linear chain is the degenerate tree
    lin = TreeSpec((1, 1, 1))
    assert lin.n_nodes == 4 and lin.spine.tolist() == [0, 1, 2, 3]
    assert parse_kvec("3,2,2") == (3, 2, 2)
    with pytest.raises(ValueError):
        TreeSpec((2, 0))
    with pytest.raises(ValueError):
        parse_kvec("")


def test_tree_walk_accepts_side_branches():
    """The walk follows oracle matches across branches: a spine miss
    that a SIBLING covers still advances (and ends the path — side
    nodes are leaves), and ``spine_acc`` reports only the prefix that
    followed the draft's own spine."""
    tr = TreeSpec((2, 2))             # nodes: 0 | 1 2 | 3 4 (off node 1)
    #            root  d1: spine,side  d2: spine,side
    toks = jnp.array([[7, 5, 6, 8, 9],     # spine all the way
                      [7, 5, 6, 8, 9],     # side hit at depth 1
                      [7, 5, 6, 8, 9],     # spine d1, side d2
                      [7, 5, 6, 8, 9]])    # total miss
    # oracle[n] = what the target emits AFTER node n's path
    oracle = jnp.array([[5, 8, 0, 1, 2],   # wants 5 then 8: spine+spine
                        [6, 8, 0, 1, 2],   # wants 6: side node 2, leaf
                        [5, 9, 0, 1, 2],   # wants 5 then 9: spine+side
                        [4, 8, 0, 1, 2]])  # wants 4: nothing matches
    n_in = jnp.array([3, 3, 3, 3])
    a, emitted, spine_acc, path = tr.walk(toks, oracle, n_in)
    assert a.tolist() == [2, 1, 2, 0]
    assert emitted.tolist() == [3, 2, 3, 1]
    # row 1 accepted via the side branch; row 2's depth-2 hit was a side
    # node — neither extends the spine-consistent prefix
    assert spine_acc.tolist() == [2, 0, 1, 0]
    assert path[0].tolist() == [0, 1, 3]
    assert path[1].tolist() == [0, 2, 2]      # leaf: path saturates
    assert path[3].tolist() == [0, 0, 0]
    # emit budget cap: n_in = 1 accepts nothing beyond the correction
    a1, e1, _, _ = tr.walk(toks, oracle, jnp.array([1, 1, 1, 1]))
    assert a1.tolist() == [0, 0, 0, 0] and e1.tolist() == [1, 1, 1, 1]
    a0, e0, _, _ = tr.walk(toks, oracle, jnp.array([0, 0, 0, 0]))
    assert e0.tolist() == [0, 0, 0, 0]


# ------------------------------------------------- lossless: charRNN

@pytest.mark.parametrize("k", [2, 4])
def test_spec_matches_plain_charlstm(k):
    net = _lstm_net()
    draft = _lstm_net(seed=11, width=8)
    base = DecodeEngine(net, slots=4, max_len=48).start()
    spec = DecodeEngine(net, slots=4, max_len=48,
                        spec=SpecConfig(draft, k=k)).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        assert base.stats()["compiled_programs"] == 1
        _assert_spec_pins(spec)
    finally:
        base.stop()
        spec.stop()


# -------------------------------------------- lossless: transformer

@pytest.mark.parametrize("kv_kw", [
    dict(kv="dense"),
    dict(kv="paged", kv_block_size=16, prefix_cache=False),
    dict(kv="paged", kv_block_size=16, prefix_cache=True),
], ids=["dense", "paged", "paged-prefix"])
def test_spec_matches_plain_transformer(kv_kw):
    net = _transformer()
    draft = _draft_transformer()
    base = DecodeEngine(net, slots=4, max_len=64, **kv_kw).start()
    spec = DecodeEngine(net, slots=4, max_len=64,
                        spec=SpecConfig(draft, k=4), **kv_kw).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        _assert_spec_pins(spec)
    finally:
        base.stop()
        spec.stop()


# ------------------------------------------------ lossless: token trees

def test_spec_tree_matches_plain_charlstm():
    """Branching caterpillar tree over recurrent carries: side-branch
    acceptance forces the draft-resync path (its snapshots follow its
    own spine), and the stream stays bitwise the plain engine's."""
    net = _lstm_net()
    draft = _lstm_net(seed=11, width=8)
    base = DecodeEngine(net, slots=4, max_len=48).start()
    spec = DecodeEngine(net, slots=4, max_len=48,
                        spec=SpecConfig(draft, tree=(3, 2))).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        _assert_spec_pins(spec)
        assert spec.stats()["spec"]["tree"] == [3, 2]
        assert spec.stats()["spec"]["tree_nodes"] == 6
    finally:
        base.stop()
        spec.stop()


@pytest.mark.parametrize("kv_kw", [
    dict(kv="dense"),
    dict(kv="paged", kv_block_size=16, prefix_cache=True),
], ids=["dense", "paged-prefix"])
def test_spec_tree_matches_plain_transformer(kv_kw):
    net = _transformer()
    draft = _draft_transformer()
    base = DecodeEngine(net, slots=4, max_len=64, **kv_kw).start()
    spec = DecodeEngine(net, slots=4, max_len=64,
                        spec=SpecConfig(draft, tree=(3, 2, 2)),
                        **kv_kw).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        _assert_spec_pins(spec)
    finally:
        base.stop()
        spec.stop()


# --------------------------------------------- lossless: self-drafting

@pytest.mark.parametrize("mode", ["int8", "early_exit:1"])
def test_self_draft_matches_plain_charlstm(mode):
    """The target as its own draft (no separate checkpoint): quantized
    self-drafting and the early-exit truncated stack both stay bitwise
    the plain engine's."""
    net = _lstm_net()
    base = DecodeEngine(net, slots=4, max_len=48).start()
    spec = DecodeEngine(net, slots=4, max_len=48,
                        spec=SpecConfig(k=3, self_draft=mode)).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        _assert_spec_pins(spec)
        assert spec.stats()["spec"]["self_draft"] == mode
    finally:
        base.stop()
        spec.stop()


def test_self_draft_int8_acceptance_near_one():
    """A quantized self-draft almost always agrees with its own f32
    oracle — the acceptance rate should be near the ceiling, which is
    the entire dispatch-amortization case for self_draft."""
    net = _lstm_net()
    eng = DecodeEngine(net, slots=4, max_len=48,
                       spec=SpecConfig(k=3, self_draft="int8")).start()
    try:
        _run_cases(eng)
        st = eng.stats()["spec"]
        assert st["draft_precision"] == "int8"
        assert st["acceptance_rate"] >= 0.8, st
    finally:
        eng.stop()


# ------------------------------------------------- stats guard

def test_acceptance_rate_zero_before_any_draft():
    """Regression: with nothing drafted yet (fresh engine — the warmup
    tick is all-inert) the rate stat and gauge must read 0.0, not NaN."""
    from deeplearning4j_tpu.monitor.metrics import get_registry
    net = _lstm_net()
    eng = DecodeEngine(net, slots=2, max_len=48,
                       spec=SpecConfig(_lstm_net(seed=11, width=8),
                                       k=3)).start()
    try:
        st = eng.stats()["spec"]
        assert st["drafted_tokens"] == 0
        assert st["acceptance_rate"] == 0.0
        assert st["mean_accepted_depth"] == 0.0
        rate = eng._m_spec_rate.value
        assert rate == 0.0 and rate == rate     # not NaN
    finally:
        eng.stop()


def test_spec_with_chunked_prefill_matches_plain():
    """Chunked prefill + speculation compose: the chunk program consumes
    the prompt, the draft catches up in parallel, verify emits. The plain
    step program never even runs in this configuration (0 traces)."""
    net = _transformer()
    kv_kw = dict(kv="paged", kv_block_size=16, prefix_cache=True,
                 chunk_tokens=4)
    base = DecodeEngine(net, slots=4, max_len=64, **kv_kw).start()
    spec = DecodeEngine(net, slots=4, max_len=64,
                        spec=SpecConfig(_draft_transformer(), k=4),
                        **kv_kw).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        st = spec.stats()
        assert st["compiled_programs"] <= 1
        assert st["spec"]["verify_programs"] == 1
        assert st["spec"]["draft_programs"] == 1
    finally:
        base.stop()
        spec.stop()


# ------------------------------------- schedule invariance + compile pins

def test_spec_arrival_schedule_invariance():
    """The same requests produce the same tokens whether submitted as a
    burst (slots share draft/verify calls) or strictly one at a time
    (each runs alone) — and the whole mix still compiles exactly one
    step, one verify, one draft program."""
    net = _lstm_net()
    draft = _lstm_net(seed=11, width=8)
    eng = DecodeEngine(net, slots=4, max_len=48,
                       spec=SpecConfig(draft, k=4)).start()
    try:
        sequential = _run_cases(eng)
        futs = [eng.submit(p, max_new_tokens=18, seed=s, temperature=t,
                           top_k=k) for p, t, s, k in CASES]
        burst = [f.result(timeout=120)["tokens"] for f in futs]
        assert burst == sequential
        _assert_spec_pins(eng)
    finally:
        eng.stop()


# --------------------------------------------- full-rejection rewind

@pytest.mark.parametrize("prefix_cache,tree", [
    (False, None), (True, None), (True, (2, 2)),
], ids=["no-prefix", "prefix", "prefix-tree"])
def test_fully_rejected_windows_rewind_bitwise_paged(prefix_cache, tree):
    """Regression for the paged rewind path: an adversarial draft whose
    proposals NEVER match (every tree node, every tick) forces every
    verify to full rejection (emit = correction token only). The stream
    must still be bitwise the plain engine's, including a SECOND request
    that (with the prefix cache on) re-claims blocks published by the
    garbage-writing first stream — proving rejected-position KV is
    neither read nor published, branching trees included."""
    net = _transformer()
    # block_size 4: the 6-token prompt fills one FULL block, so the first
    # stream publishes it and the second can take a prefix hit
    kv_kw = dict(kv="paged", kv_block_size=4, prefix_cache=prefix_cache)
    prompt = [0, 4, 2, 9, 7, 1]
    base = DecodeEngine(net, slots=2, max_len=64, **kv_kw).start()
    try:
        ref = base.generate(prompt, max_new_tokens=20, timeout=120)
    finally:
        base.stop()
    # a token id the greedy trajectory never emits → never equals the
    # oracle → every draft proposal is rejected
    unused = sorted(set(range(V)) - set(ref["tokens"]))
    assert unused, "need a token id outside the reference trajectory"
    wrong = unused[0]

    spec = DecodeEngine(net, slots=2, max_len=64,
                        spec=SpecConfig(_draft_transformer(), k=4,
                                        tree=tree),
                        **kv_kw).start()
    real_step = spec._draft.step

    def adversarial_step(*args, **kw):
        props, sides = real_step(*args, **kw)
        return np.full_like(props, wrong), np.full_like(sides, wrong)

    spec._draft.step = adversarial_step
    try:
        for _ in range(2):   # second pass exercises prefix-block reuse
            out = spec.generate(prompt, max_new_tokens=20, timeout=120)
            assert out["tokens"] == ref["tokens"]
        st = spec.stats()["spec"]
        assert st["accepted_tokens"] == 0
        assert st["drafted_tokens"] > 0
        assert st["acceptance_rate"] == 0.0
        assert st["mean_accepted_depth"] == 0.0
        if prefix_cache:
            assert spec.stats()["kv"]["prefix_hits"] >= 1
    finally:
        spec.stop()


# ------------------------------------------------- replica flag plumbing

def test_replica_spec_flags_subprocess(tmp_path):
    """``--spec-tree`` / ``--spec-self-draft`` ride ReplicaProcess →
    replica CLI → build_server → SpecConfig: the child boots, /generate
    is bitwise ``generate_naive`` over the same stock weights (the
    lossless claim end-to-end through the subprocess boundary), and
    /stats advertises the tree shape."""
    from deeplearning4j_tpu.serving import InferenceClient
    from deeplearning4j_tpu.serving.replica import (ReplicaProcess,
                                                    build_model)
    rep = ReplicaProcess(str(tmp_path), model="charlstm", chaos=False,
                         warmup=False, name="spec-tree",
                         spec_tree="2,2", spec_self_draft="int8").start()
    try:
        rep.wait_ready()
        cli = InferenceClient(rep.url)
        prompt = [1, 2, 3]
        out = cli.generate(prompt, max_new_tokens=10, seed=0)
        ref = generate_naive(build_model("charlstm"), prompt,
                             max_new_tokens=10, max_len=64)
        assert out["tokens"] == ref["tokens"]
        spec = cli.stats()["decode"]["spec"]
        assert spec["tree"] == [2, 2]
        assert spec["self_draft"] == "int8"
        assert spec["drafted_tokens"] > 0
        assert spec["verify_programs"] == 1
        assert spec["draft_programs"] == 1
        cli.close()
    finally:
        rep.stop()


# ------------------------------------------------- one sampling oracle

def test_generate_naive_shares_sampling_oracle():
    """Satellite of the subsystem: the naive generator and the engine run
    the SAME oracle, so they agree under temperature sampling and top-k
    filtering, not just under greedy argmax."""
    net = _lstm_net()
    eng = DecodeEngine(net, slots=2, max_len=48).start()
    try:
        for temp, seed, tk in [(0.0, 0, 0), (0.8, 42, 0), (0.6, 9, 4)]:
            naive = generate_naive(net, [1, 2, 3], max_new_tokens=12,
                                   max_len=48, seed=seed, temperature=temp,
                                   top_k=tk)
            served = eng.generate([1, 2, 3], max_new_tokens=12, seed=seed,
                                  temperature=temp, top_k=tk, timeout=120)
            assert naive["tokens"] == served["tokens"]
    finally:
        eng.stop()


# ------------------------------------------------------------- guards

def test_spec_config_validation():
    net = _lstm_net()
    with pytest.raises(ValueError, match="spec.k"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_lstm_net(seed=11, width=8), k=0))

    class _Vocab:
        size = V + 1

    class _Conf:
        input_type = _Vocab()

    class _BadDraft:
        conf = _Conf()

    with pytest.raises(ValueError, match="vocabulary"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_BadDraft(), k=4))

    # exactly one of draft_model / self_draft
    with pytest.raises(ValueError, match="exactly one"):
        DecodeEngine(net, slots=2, max_len=48, spec=SpecConfig(k=4))
    with pytest.raises(ValueError, match="exactly one"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_lstm_net(seed=11, width=8), k=4,
                                     self_draft="int8"))
    with pytest.raises(ValueError, match="self_draft"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(self_draft="int7"))
    with pytest.raises(ValueError, match="positive layer count"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(self_draft="early_exit:0"))
    with pytest.raises(ValueError, match="out of range"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(self_draft="early_exit:9"))
    with pytest.raises(ValueError, match="conflicts"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(self_draft="int8",
                                     draft_precision="fp8"))
    with pytest.raises(ValueError, match="kvec"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_lstm_net(seed=11, width=8),
                                     tree=(2, 0)))
    # early-exit needs a layer stack, not a graph
    with pytest.raises(ValueError, match="MultiLayerNetwork"):
        DecodeEngine(_transformer(), slots=2, max_len=64,
                     spec=SpecConfig(self_draft="early_exit:1"))
