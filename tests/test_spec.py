"""Speculative decoding subsystem (serving/spec/).

The load-bearing claims pinned here:

- LOSSLESS: speculative output is token-for-token the non-speculative
  engine's — greedy AND seeded temperature sampling (the fixed-seed
  trace form of rejection sampling: draft, verify and the plain step
  share one oracle) — for the charRNN (recurrent carries → snapshot
  rewind) and the causal transformer (positional KV → causal-mask
  rewind), over dense and paged KV;
- COMPILE PINS: one step, one verify, one draft program per engine
  regardless of k, arrival schedule, prompt lengths or slot mix;
- REWIND REGRESSION: a slot whose draft windows are ALL fully rejected
  emits exactly the oracle's correction tokens and continues bitwise —
  paged KV, prefix cache on and off (garbage KV written for rejected
  positions is never read and never published);
- acceptance rule semantics (leading match + correction token);
- ``generate_naive`` and the engine share the sampling oracle at
  temperature > 0, not just under greedy argmax.
"""

import threading

import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import DecodeEngine, generate_naive
from deeplearning4j_tpu.serving.spec import SpecConfig, accept_length
from deeplearning4j_tpu.zoo.simple import TinyTransformer

V = 13


def _lstm_net(seed=7, width=16):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=width, activation="tanh"))
            .layer(LSTM(n_out=width, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build())
    return MultiLayerNetwork(conf).init()


def _transformer(seed=7):
    return TinyTransformer(vocab_size=V, n_layers=2, d_model=32, n_heads=4,
                           max_len=64, seed=seed).init()


def _draft_transformer():
    return TinyTransformer(vocab_size=V, n_layers=1, d_model=16, n_heads=2,
                           max_len=64, seed=3).init()


CASES = [([1, 2, 3], 0.0, 0, 0),        # greedy
         ([5], 0.0, 0, 0),              # one-token prompt: verify wipes
         ([0, 4, 2, 9, 7], 0.9, 123, 0),  # seeded sampling
         ([3, 3], 0.7, 7, 5)]           # sampling + top-k filter


def _run_cases(eng, max_new=18):
    return [eng.generate(p, max_new_tokens=max_new, seed=s, temperature=t,
                         top_k=k, timeout=120)["tokens"]
            for p, t, s, k in CASES]


def _assert_spec_pins(eng, step_programs=1):
    st = eng.stats()
    assert st["compiled_programs"] == step_programs, st
    assert st["spec"]["verify_programs"] == 1, st
    assert st["spec"]["draft_programs"] == 1, st
    assert st["spec"]["drafted_tokens"] > 0


# ------------------------------------------------------- acceptance rule

def test_accept_length_leading_match_plus_correction():
    oracle = jnp.array([[5, 6, 7, 8], [5, 6, 7, 8], [5, 6, 7, 8],
                        [5, 6, 7, 8]])
    draft = jnp.array([[5, 6, 9, 8],    # match, match, miss, (match)
                       [5, 6, 7, 8],    # full match
                       [9, 6, 7, 8],    # first-token miss
                       [5, 6, 7, 8]])
    n_in = jnp.array([4, 4, 4, 2])      # last row: short window
    a, e = accept_length(oracle, draft, n_in)
    # a trailing match AFTER a miss must not count (cumprod, not sum)
    assert a.tolist() == [2, 4, 0, 2]
    # emitted = accepted + correction token, capped at the window
    assert e.tolist() == [3, 4, 1, 2]
    a0, e0 = accept_length(oracle, draft, jnp.array([0, 0, 0, 0]))
    assert a0.tolist() == [0, 0, 0, 0] and e0.tolist() == [0, 0, 0, 0]


# ------------------------------------------------- lossless: charRNN

@pytest.mark.parametrize("k", [2, 4])
def test_spec_matches_plain_charlstm(k):
    net = _lstm_net()
    draft = _lstm_net(seed=11, width=8)
    base = DecodeEngine(net, slots=4, max_len=48).start()
    spec = DecodeEngine(net, slots=4, max_len=48,
                        spec=SpecConfig(draft, k=k)).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        assert base.stats()["compiled_programs"] == 1
        _assert_spec_pins(spec)
    finally:
        base.stop()
        spec.stop()


# -------------------------------------------- lossless: transformer

@pytest.mark.parametrize("kv_kw", [
    dict(kv="dense"),
    dict(kv="paged", kv_block_size=16, prefix_cache=False),
    dict(kv="paged", kv_block_size=16, prefix_cache=True),
], ids=["dense", "paged", "paged-prefix"])
def test_spec_matches_plain_transformer(kv_kw):
    net = _transformer()
    draft = _draft_transformer()
    base = DecodeEngine(net, slots=4, max_len=64, **kv_kw).start()
    spec = DecodeEngine(net, slots=4, max_len=64,
                        spec=SpecConfig(draft, k=4), **kv_kw).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        _assert_spec_pins(spec)
    finally:
        base.stop()
        spec.stop()


def test_spec_with_chunked_prefill_matches_plain():
    """Chunked prefill + speculation compose: the chunk program consumes
    the prompt, the draft catches up in parallel, verify emits. The plain
    step program never even runs in this configuration (0 traces)."""
    net = _transformer()
    kv_kw = dict(kv="paged", kv_block_size=16, prefix_cache=True,
                 chunk_tokens=4)
    base = DecodeEngine(net, slots=4, max_len=64, **kv_kw).start()
    spec = DecodeEngine(net, slots=4, max_len=64,
                        spec=SpecConfig(_draft_transformer(), k=4),
                        **kv_kw).start()
    try:
        assert _run_cases(spec) == _run_cases(base)
        st = spec.stats()
        assert st["compiled_programs"] <= 1
        assert st["spec"]["verify_programs"] == 1
        assert st["spec"]["draft_programs"] == 1
    finally:
        base.stop()
        spec.stop()


# ------------------------------------- schedule invariance + compile pins

def test_spec_arrival_schedule_invariance():
    """The same requests produce the same tokens whether submitted as a
    burst (slots share draft/verify calls) or strictly one at a time
    (each runs alone) — and the whole mix still compiles exactly one
    step, one verify, one draft program."""
    net = _lstm_net()
    draft = _lstm_net(seed=11, width=8)
    eng = DecodeEngine(net, slots=4, max_len=48,
                       spec=SpecConfig(draft, k=4)).start()
    try:
        sequential = _run_cases(eng)
        futs = [eng.submit(p, max_new_tokens=18, seed=s, temperature=t,
                           top_k=k) for p, t, s, k in CASES]
        burst = [f.result(timeout=120)["tokens"] for f in futs]
        assert burst == sequential
        _assert_spec_pins(eng)
    finally:
        eng.stop()


# --------------------------------------------- full-rejection rewind

@pytest.mark.parametrize("prefix_cache", [False, True],
                         ids=["no-prefix", "prefix"])
def test_fully_rejected_windows_rewind_bitwise_paged(prefix_cache):
    """Regression for the paged rewind path: an adversarial draft whose
    proposals NEVER match forces every window to full rejection (emit =
    correction token only). The stream must still be bitwise the plain
    engine's, including a SECOND request that (with the prefix cache on)
    re-claims blocks published by the garbage-writing first stream —
    proving rejected-position KV is neither read nor published."""
    net = _transformer()
    # block_size 4: the 6-token prompt fills one FULL block, so the first
    # stream publishes it and the second can take a prefix hit
    kv_kw = dict(kv="paged", kv_block_size=4, prefix_cache=prefix_cache)
    prompt = [0, 4, 2, 9, 7, 1]
    base = DecodeEngine(net, slots=2, max_len=64, **kv_kw).start()
    try:
        ref = base.generate(prompt, max_new_tokens=20, timeout=120)
    finally:
        base.stop()
    # a token id the greedy trajectory never emits → never equals the
    # oracle → every draft window is fully rejected
    unused = sorted(set(range(V)) - set(ref["tokens"]))
    assert unused, "need a token id outside the reference trajectory"
    wrong = unused[0]

    spec = DecodeEngine(net, slots=2, max_len=64,
                        spec=SpecConfig(_draft_transformer(), k=4),
                        **kv_kw).start()
    real_step = spec._draft.step

    def adversarial_step(*args, **kw):
        props = real_step(*args, **kw)
        return np.full_like(props, wrong)

    spec._draft.step = adversarial_step
    try:
        for _ in range(2):   # second pass exercises prefix-block reuse
            out = spec.generate(prompt, max_new_tokens=20, timeout=120)
            assert out["tokens"] == ref["tokens"]
        st = spec.stats()["spec"]
        assert st["accepted_tokens"] == 0
        assert st["drafted_tokens"] > 0
        assert st["acceptance_rate"] == 0.0
        if prefix_cache:
            assert spec.stats()["kv"]["prefix_hits"] >= 1
    finally:
        spec.stop()


# ------------------------------------------------- one sampling oracle

def test_generate_naive_shares_sampling_oracle():
    """Satellite of the subsystem: the naive generator and the engine run
    the SAME oracle, so they agree under temperature sampling and top-k
    filtering, not just under greedy argmax."""
    net = _lstm_net()
    eng = DecodeEngine(net, slots=2, max_len=48).start()
    try:
        for temp, seed, tk in [(0.0, 0, 0), (0.8, 42, 0), (0.6, 9, 4)]:
            naive = generate_naive(net, [1, 2, 3], max_new_tokens=12,
                                   max_len=48, seed=seed, temperature=temp,
                                   top_k=tk)
            served = eng.generate([1, 2, 3], max_new_tokens=12, seed=seed,
                                  temperature=temp, top_k=tk, timeout=120)
            assert naive["tokens"] == served["tokens"]
    finally:
        eng.stop()


# ------------------------------------------------------------- guards

def test_spec_config_validation():
    net = _lstm_net()
    with pytest.raises(ValueError, match="spec.k"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_lstm_net(seed=11, width=8), k=0))

    class _Vocab:
        size = V + 1

    class _Conf:
        input_type = _Vocab()

    class _BadDraft:
        conf = _Conf()

    with pytest.raises(ValueError, match="vocabulary"):
        DecodeEngine(net, slots=2, max_len=48,
                     spec=SpecConfig(_BadDraft(), k=4))
