"""Low-precision serving: quantization parity + engine integration.

The load-bearing claims pinned here (docs/QUANTIZATION.md):
- per-channel int8/fp8 weight quantization round-trips within documented
  per-layer error bars (int8 rel ≤ 1%, fp8-e4m3 rel ≤ 5%), and the f32
  "quantization" is the identity on the SAME objects — the f32 serving
  path stays bitwise-untouched;
- an int8 tree is ≤ 0.30× the f32 bytes once matrices dominate;
- engines under int8/fp8 serve within an end-to-end accuracy delta bar
  of the f32 engine, while hot swaps still validate f32 candidates and
  perform ZERO new XLA compiles (the quantize-behind-the-gate design);
- each (model, precision) pair costs exactly ONE compiled decode-step
  program, and bucketed serving compiles per bucket as before.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.quant import (QTensor, dequantize, dequantize_tree,
                                      quant_error_report, quantize,
                                      quantize_tree, resolve_precision,
                                      tree_bytes)
from deeplearning4j_tpu.serving.decode import DecodeEngine
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.replica import build_model


def _net(seed=3, n_in=8, hidden=64, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _blobs(n=240, seed=0, d=8, k=3):
    rs = np.random.RandomState(seed)
    centers = rs.randn(k, d) * 3
    y = rs.randint(0, k, n)
    X = centers[y] + rs.randn(n, d) * 0.5
    return X.astype(np.float32), y


# --------------------------------------------------------------- mechanism
class TestQTensor:

    def test_resolve_precision_aliases_and_rejects(self):
        for alias in (None, "", "f32", "float32", "fp32", "none"):
            assert resolve_precision(alias) == "f32"
        for alias in ("int8", "i8", "INT8"):
            assert resolve_precision(alias) == "int8"
        for alias in ("fp8", "e4m3", "fp8_e4m3", "float8"):
            assert resolve_precision(alias) == "fp8"
        with pytest.raises(ValueError):
            resolve_precision("int4")

    @pytest.mark.parametrize("precision,rel_bar", [("int8", 0.01),
                                                   ("fp8", 0.05)])
    def test_roundtrip_error_bounds(self, precision, rel_bar):
        rs = np.random.RandomState(0)
        # mixed per-channel magnitudes — the case per-TENSOR scales fail
        w = (rs.randn(64, 32) * np.logspace(-2, 1, 32)).astype(np.float32)
        qt = quantize(jnp.asarray(w), precision)
        assert isinstance(qt, QTensor)
        assert qt.shape == w.shape
        back = np.asarray(dequantize(qt))
        rel = np.max(np.abs(back - w)) / np.max(np.abs(w))
        assert rel <= rel_bar, rel

    def test_zero_channel_is_exact_and_finite(self):
        w = jnp.zeros((4, 3), jnp.float32)
        for p in ("int8", "fp8"):
            back = np.asarray(dequantize(quantize(w, p)))
            assert np.all(back == 0) and np.all(np.isfinite(back))

    def test_f32_is_identity_same_objects(self):
        tree = {"W": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        assert quantize_tree(tree, "f32") is tree
        # and dequantize of an unquantized tree keeps the same leaves
        out = dequantize_tree(tree)
        assert out["W"] is tree["W"] and out["b"] is tree["b"]

    def test_tree_quantization_skips_vectors_and_exclusions(self):
        tree = {"layer0": {"W": jnp.ones((8, 8)), "b": jnp.ones((8,))},
                "head": {"W": jnp.ones((8, 2))}}
        q = quantize_tree(tree, "int8", exclude=("head",))
        assert isinstance(q["layer0"]["W"], QTensor)
        assert not isinstance(q["layer0"]["b"], QTensor)   # 1-D: never
        assert not isinstance(q["head"]["W"], QTensor)     # excluded

    def test_int8_bytes_ratio(self):
        rs = np.random.RandomState(1)
        tree = {"W1": jnp.asarray(rs.randn(256, 256), jnp.float32),
                "W2": jnp.asarray(rs.randn(256, 128), jnp.float32),
                "b": jnp.zeros((256,), jnp.float32)}
        f32 = tree_bytes(tree)
        q = tree_bytes(quantize_tree(tree, "int8"))
        assert q <= 0.30 * f32, (q, f32)

    def test_error_report_shape(self):
        tree = {"W": jnp.ones((8, 8)) * 0.5}
        rep = quant_error_report(tree, quantize_tree(tree, "int8"))
        assert "max" in rep and "rel_max" in rep
        assert rep["rel_max"] <= 0.01

    def test_qtensor_flows_through_jit(self):
        w = jnp.asarray(np.random.RandomState(2).randn(16, 8), jnp.float32)
        qt = quantize(w, "int8")

        @jax.jit
        def f(q, x):
            return x @ dequantize(q)

        x = jnp.ones((2, 16))
        out = f(qt, x)
        ref = x @ dequantize(qt)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


# ------------------------------------------------------- engine integration
class TestQuantizedServing:

    @pytest.mark.parametrize("precision,out_bar", [("int8", 0.02),
                                                   ("fp8", 0.05)])
    def test_engine_parity_and_weight_bytes(self, precision, out_bar):
        net = _net(hidden=64)
        X, _ = _blobs(64)
        e32 = InferenceEngine(net, max_batch=64)
        eq = InferenceEngine(net, max_batch=64, precision=precision)
        y32 = e32.predict_host(X)
        yq = eq.predict_host(X)
        assert float(np.max(np.abs(yq - y32))) <= out_bar
        assert eq.stats()["precision"] == precision
        assert eq.stats()["weight_bytes"] < e32.stats()["weight_bytes"]

    def test_f32_engine_path_is_bitwise_unchanged(self):
        net = _net(seed=11)
        X, _ = _blobs(32, seed=4)
        plain = InferenceEngine(net, max_batch=32)
        explicit = InferenceEngine(net, max_batch=32, precision="f32")
        assert np.array_equal(plain.predict_host(X),
                              explicit.predict_host(X))
        assert np.array_equal(plain.predict_host(X),
                              np.asarray(net.output(X)))

    def test_eval_accuracy_delta_within_bar(self):
        X, y = _blobs(240)
        net = _net()
        from deeplearning4j_tpu.data.dataset import DataSet
        onehot = np.eye(3, dtype=np.float32)[y]
        for _ in range(15):
            net.fit(DataSet(X, onehot))
        acc = {}
        for precision in ("f32", "int8", "fp8"):
            e = InferenceEngine(net, max_batch=256, precision=precision)
            pred = np.argmax(e.predict_host(X), -1)
            acc[precision] = float(np.mean(pred == y))
        # documented bars (docs/QUANTIZATION.md): int8 ≤ 1%, fp8 ≤ 2%
        assert abs(acc["int8"] - acc["f32"]) <= 0.01, acc
        assert abs(acc["fp8"] - acc["f32"]) <= 0.02, acc

    def test_swap_under_quantization_zero_new_compiles(self):
        net = _net(seed=5)
        X, _ = _blobs(16, seed=1)
        e = InferenceEngine(net, max_batch=16, precision="int8")
        e.predict_host(X)
        before = e.trace_count
        # candidate arrives in f32 (trainer/checkpoint format)
        cand = jax.tree_util.tree_map(
            lambda a: np.asarray(a) * 1.01, net.params)
        v = e.swap_weights(cand)
        assert v == 1
        e.predict_host(X)
        assert e.trace_count == before
        # and a wrong-shape f32 candidate still rejects cleanly
        from deeplearning4j_tpu.resilience.errors import WeightSwapError
        bad = jax.tree_util.tree_map(
            lambda a: np.zeros((2, 2), np.float32), cand)
        with pytest.raises(WeightSwapError):
            e.swap_weights(bad)

    def test_decode_engine_one_program_per_precision(self):
        net = build_model("charlstm")
        e32 = DecodeEngine(net, slots=2, max_len=32).start()
        e8 = DecodeEngine(net, slots=2, max_len=32,
                          precision="int8").start()
        try:
            r32 = e32.generate([3, 1, 4], max_new_tokens=6)
            r8 = e8.generate([3, 1, 4], max_new_tokens=6)
        finally:
            e32.stop()
            e8.stop()
        # one donated program each — quantization keys a separate program
        # on ITS engine, never a second one
        assert e32.trace_count == 1
        assert e8.trace_count == 1
        assert len(r8["tokens"]) == 6
        assert e8.stats()["precision"] == "int8"
        assert e8.stats()["weight_bytes"] < e32.stats()["weight_bytes"]

    def test_decode_swap_under_quantization_zero_new_compiles(self):
        net = build_model("charlstm")
        e = DecodeEngine(net, slots=2, max_len=32, precision="int8").start()
        try:
            e.generate([3, 1, 4], max_new_tokens=4)
            before = e.trace_count
            e.swap_weights(jax.tree_util.tree_map(np.asarray, net.params))
            out = e.generate([3, 1, 4], max_new_tokens=4)
        finally:
            e.stop()
        assert e.trace_count == before
        assert len(out["tokens"]) == 4

    def test_executor_precision_policy_reaches_engines(self):
        from deeplearning4j_tpu import exec as ex
        old = ex.get_executor()
        try:
            ex.set_executor(ex.Executor(precision="int8"))
            net = _net(seed=9)
            e = InferenceEngine(net, max_batch=8)
            assert e.precision == "int8"
            assert e.stats()["precision"] == "int8"
        finally:
            ex.set_executor(old)
