"""Paged KV-cache subsystem (serving/kv/ + DecodeEngine(kv="paged")).

The load-bearing claims pinned here:
- the block pool is a correct refcounted allocator: all-or-nothing
  allocation, LRU eviction of cached blocks, scratch block pinned;
- a paged engine's greedy output is BITWISE-equal to the dense engine's
  for a transformer at f32 AND bf16 compute, sequentially and under
  concurrent arrival with chunked prefill — and still ONE compiled step
  program (trace_count == 1), at most two kv side programs;
- prefix-cache reuse (including the copy-on-write partial-block path)
  never changes output: requests sharing a prefix decode exactly as if
  they were independent;
- slot release is complete: after claim → free → re-claim cycles the
  pool's occupancy returns to baseline (the eos leak regression);
- /healthz reports ``kv_pool_exhausted`` with the pool occupancy while
  the queue head cannot claim blocks, and recovers;
- the paged flash kernel (interpret mode) matches the dense gather path.
"""

import time

import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu.serving import (DecodeEngine, InferenceClient,
                                        InferenceServer)
from deeplearning4j_tpu.serving.kv import (BlockPool, PoolExhaustedError,
                                           PrefixCache, blocks_for_span,
                                           plan_chunks)
from deeplearning4j_tpu.zoo.simple import TinyTransformer

V = 13


def _transformer(max_len=64, compute_dtype=None, seed=7):
    kw = {"compute_dtype": compute_dtype} if compute_dtype else {}
    return TinyTransformer(vocab_size=V, n_layers=2, d_model=32, n_heads=4,
                          max_len=max_len, seed=seed, **kw).init()


def _lstm_net():
    from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build())
    return MultiLayerNetwork(conf).init()


def _prompts(sizes, seed=0):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, V, size=n))) for n in sizes]


# ------------------------------------------------------------------ pool

def test_pool_alloc_free_refcount():
    p = BlockPool(8, 16)
    assert p.usable == 7 and p.free_count == 7 and p.in_use == 0
    a = p.alloc(3)
    assert len(a) == 3 and 0 not in a            # scratch never handed out
    assert p.in_use == 3 and p.free_count == 4
    p.incref(a[0])
    p.decref(a[0])
    assert p.refcount(a[0]) == 1                 # still held once
    for b in a:
        p.decref(b)
    assert p.in_use == 0 and p.free_count == 7
    with pytest.raises(ValueError):
        p.decref(a[0])                           # double free
    with pytest.raises(ValueError):
        p.incref(0)                              # scratch is pinned


def test_pool_alloc_all_or_nothing():
    p = BlockPool(4, 8)
    a = p.alloc(2)
    with pytest.raises(PoolExhaustedError):
        p.alloc(2)                               # only 1 left
    assert p.in_use == 2 and p.free_count == 1   # no partial side effects
    p.decref(a[0])
    assert len(p.alloc(2)) == 2


def test_pool_cached_blocks_evict_lru():
    p = BlockPool(4, 8)
    dropped = []
    p.on_evict = dropped.append
    a = p.alloc(3)
    for b in a:
        p.mark_cached(b)
        p.decref(b)                              # ref 0 → evictable, LRU
    assert p.free_count == 3 and p.cached_count == 3 and p.in_use == 0
    # a hit revives the middle block; eviction then takes LRU order
    p.incref(a[1])
    got = p.alloc(2)                             # evicts a[0] then a[2]
    assert dropped == [a[0], a[2]]
    assert sorted(got) == sorted([a[0], a[2]])
    assert p.is_cached(a[1]) and not p.is_cached(a[0])
    p.decref(a[1])
    assert p.flush_cached() == 1                 # weight swap: drop ref-0


def test_plan_chunks_and_blocks_for_span():
    assert plan_chunks(0, 10, 4) == [(0, 4), (4, 4), (8, 2)]
    assert plan_chunks(3, 3, 4) == []
    assert blocks_for_span(1, 16) == 1
    assert blocks_for_span(16, 16) == 1
    assert blocks_for_span(17, 16) == 2


# ---------------------------------------------------------------- prefix

def test_prefix_chain_match_and_insert():
    p = BlockPool(16, 4)
    pc = PrefixCache(p)
    prompt = list(range(10))                     # blocks: [0..3] [4..7] |8,9
    blocks = p.alloc(3)
    assert pc.insert(prompt, blocks) == 2        # two FULL prompt blocks
    for b in blocks:
        p.decref(b)
    assert p.in_use == 0 and p.cached_count == 2
    # same prompt again: both full blocks claimed, skip capped at plen-1
    shared, cow, skip = pc.match(prompt)
    assert shared == blocks[:2] and skip == 8 and cow is None
    assert p.refcount(blocks[0]) == 1            # claimed read-only
    for b in shared:
        p.decref(b)
    # diverging inside block 1 → one full-block hit + CoW partial tail
    other = prompt[:6] + [99, 98, 97, 96]
    shared, cow, skip = pc.match(other)
    assert shared == blocks[:1]
    assert cow == (blocks[1], 2) and skip == 4 + 2
    p.decref(shared[0])
    p.decref(cow[0])
    # unrelated prompt: no match
    assert pc.match([7, 7, 7, 7, 7, 7]) == ([], None, 0)


def test_prefix_eviction_drops_index_entries():
    p = BlockPool(4, 4)
    pc = PrefixCache(p)
    prompt = list(range(8))
    blocks = p.alloc(2)
    pc.insert(prompt, blocks)
    for b in blocks:
        p.decref(b)
    assert len(pc) == 2
    p.alloc(3)                                   # forces both evictions
    assert len(pc) == 0
    assert pc.match(prompt) == ([], None, 0)     # index never dangles


# ------------------------------------------------- engine bitwise parity

@pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
def test_paged_engine_bitwise_equals_dense(compute_dtype):
    net = _transformer(max_len=64, compute_dtype=compute_dtype)
    prompts = _prompts((1, 5, 17, 33))
    dense = DecodeEngine(net, slots=2, max_len=64).start()
    try:
        ref = [dense.generate(p, max_new_tokens=10) for p in prompts]
    finally:
        dense.stop()
    pag = DecodeEngine(net, slots=2, max_len=64, kv="paged",
                       kv_block_size=16, prefix_cache=False).start()
    try:
        got = [pag.generate(p, max_new_tokens=10) for p in prompts]
        assert pag.trace_count == 1              # one step program
    finally:
        pag.stop()
    for a, b in zip(ref, got):
        assert a["tokens"] == b["tokens"]


def test_paged_chunked_concurrent_bitwise_equals_dense():
    net = _transformer(max_len=64)
    prompts = _prompts((1, 3, 9, 17, 33, 21), seed=3)
    dense = DecodeEngine(net, slots=4, max_len=64).start()
    try:
        ref = [dense.generate(p, max_new_tokens=12) for p in prompts]
    finally:
        dense.stop()
    pag = DecodeEngine(net, slots=4, max_len=64, kv="paged",
                       kv_block_size=16, prefix_cache=True,
                       chunk_tokens=8).start()
    try:
        futs = [pag.submit(p, max_new_tokens=12) for p in prompts]
        got = [f.result(timeout=120) for f in futs]
        st = pag.stats()
    finally:
        pag.stop()
    for a, b in zip(ref, got):
        assert a["tokens"] == b["tokens"]
    # arrival schedule never mints programs: 1 step + at most 2 kv side
    assert st["compiled_programs"] == 1
    assert st["kv"]["kv_programs"] <= 2
    assert st["kv"]["prefill_chunks"] > 0
    assert st["kv"]["blocks_in_use"] == 0        # everything released


def test_shared_prefix_reuse_and_cow_divergence():
    # two requests with a common 64-token prefix and different
    # continuations (one diverging INSIDE a block → copy-on-write):
    # outputs must equal independent decodes
    net = _transformer(max_len=96)
    rng = np.random.default_rng(11)
    common = list(map(int, rng.integers(0, V, size=64)))
    cont_a = list(map(int, rng.integers(0, V, size=16)))
    cont_b = cont_a[:4] + list(map(int, rng.integers(0, V, size=12)))
    pa, pb = common + cont_a, common + cont_b
    assert pa != pb and pa[:68] == pb[:68]

    def run(prefix_cache):
        eng = DecodeEngine(net, slots=2, max_len=96, kv="paged",
                           kv_block_size=16,
                           prefix_cache=prefix_cache).start()
        try:
            ra = eng.generate(pa, max_new_tokens=8)
            rb = eng.generate(pb, max_new_tokens=8)
            return ra, rb, eng.stats()
        finally:
            eng.stop()

    (ia, ib, _) = run(False)
    (ca, cb, st) = run(True)
    assert ca["tokens"] == ia["tokens"]
    assert cb["tokens"] == ib["tokens"]
    kv = st["kv"]
    # request B claimed A's four full prefix blocks + a CoW tail block
    assert kv["prefix_hits"] == 1
    assert kv["prefix_tokens_saved"] >= 64
    assert kv["cow_copies"] == 1
    assert kv["kv_programs"] <= 2
    assert kv["blocks_in_use"] == 0


# ------------------------------------------------------- release / leaks

def test_slot_reclaim_releases_kv_blocks():
    # the eos leak regression: claim → free → re-claim must return pool
    # occupancy to baseline — with the prefix cache ON, released blocks
    # park ref-0 in the evictable LRU (still allocatable), never leak refs
    net = _transformer(max_len=64)
    for prefix_cache in (False, True):
        eng = DecodeEngine(net, slots=2, max_len=64, kv="paged",
                           kv_block_size=16, eos_id=0,
                           prefix_cache=prefix_cache).start()
        try:
            pool = eng._pool
            baseline = (pool.in_use, pool.free_count)
            for round_ in range(3):
                for p in _prompts((17, 33), seed=round_):
                    eng.generate(p, max_new_tokens=10)
                assert pool.in_use == baseline[0] == 0
                assert pool.free_count == baseline[1]
            if prefix_cache:
                assert pool.cached_count > 0     # cached, yet allocatable
        finally:
            eng.stop()
        assert pool.in_use == 0


def test_engine_stop_releases_inflight_blocks():
    net = _transformer(max_len=64)
    eng = DecodeEngine(net, slots=2, max_len=64, kv="paged",
                       kv_block_size=16, prefix_cache=False).start()
    futs = [eng.submit(p, max_new_tokens=40) for p in _prompts((17, 9))]
    eng.stop()                                   # mid-flight abort
    assert eng._pool.in_use == 0
    for f in futs:
        assert f.done()


# ------------------------------------------------------------ validation

def test_paged_config_validation():
    net = _transformer(max_len=64)
    with pytest.raises(ValueError, match="kv_block_size"):
        DecodeEngine(net, max_len=60, kv="paged", kv_block_size=16)
    with pytest.raises(ValueError, match="chunk_tokens"):
        DecodeEngine(net, max_len=64, chunk_tokens=8)
    with pytest.raises(ValueError, match="kv must be"):
        DecodeEngine(net, max_len=64, kv="virtual")
    # recurrent decode state cannot share prefix blocks
    with pytest.raises(ValueError, match="prefix_cache"):
        DecodeEngine(_lstm_net(), max_len=64, kv="paged",
                     prefix_cache=True)
    # an LSTM paged engine is fine with the prefix cache off
    eng = DecodeEngine(_lstm_net(), max_len=64, kv="paged",
                       prefix_cache=False)
    assert eng.kv == "paged"
    # a request that could NEVER fit the pool fails fast at submit
    small = DecodeEngine(net, slots=1, max_len=64, kv="paged",
                         kv_block_size=16, kv_blocks=3, prefix_cache=False)
    with pytest.raises(ValueError, match="KV blocks"):
        small.submit(list(range(5)) * 8, max_new_tokens=20)


# --------------------------------------------------------------- healthz

def test_healthz_reports_kv_pool_exhausted():
    net = _transformer(max_len=256)
    # pool sized so ONE long request takes every block: the second queues
    # and /healthz degrades with the pool occupancy until blocks free up
    dec = DecodeEngine(net, slots=2, max_len=256, kv="paged",
                       kv_block_size=16, kv_blocks=17,
                       prefix_cache=False).start()
    srv = InferenceServer(net, port=0, decode_engine=dec).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        assert cli.health()["status"] == "ok"
        prompt = _prompts((4,), seed=5)[0]
        f1 = dec.submit(prompt, max_new_tokens=240)   # needs all 16 blocks
        f2 = dec.submit(prompt, max_new_tokens=240)
        seen = None
        deadline = time.time() + 60
        while time.time() < deadline:
            h = cli.health()
            if h["status"] == "degraded" and h["reason"] == "kv_pool_exhausted":
                seen = h
                break
            if f2.done():
                break
            time.sleep(0.002)
        assert seen is not None, "never observed kv_pool_exhausted"
        assert seen["kv"]["blocks"] == 16
        assert seen["kv"]["blocks_free"] == 0
        f1.result(timeout=120)
        f2.result(timeout=120)
        deadline = time.time() + 30
        while cli.health()["status"] != "ok" and time.time() < deadline:
            time.sleep(0.01)
        assert cli.health()["status"] == "ok"    # recovers once released
        assert dec.stats()["kv"]["exhausted_events"] >= 1
    finally:
        srv.stop()


# ---------------------------------------------------------- paged kernel

def test_flash_decode_paged_kernel_matches_gather():
    from deeplearning4j_tpu.ops.flash_decode import (flash_decode_step,
                                                     flash_decode_step_paged,
                                                     supported_paged)
    assert supported_paged(16, 8) and not supported_paged(12, 8)
    rng = np.random.default_rng(0)
    B, H, Dh, bs, nb, MB = 3, 4, 8, 16, 9, 4
    pk = rng.standard_normal((nb, bs, H, Dh)).astype(np.float32)
    pv = rng.standard_normal((nb, bs, H, Dh)).astype(np.float32)
    q = rng.standard_normal((B, H, Dh)).astype(np.float32)
    # distinct scattered tables per row; positions mid-block
    bt = np.array([[1, 3, 5, 7], [2, 4, 6, 8], [8, 1, 2, 3]], np.int32)
    pos = np.array([37, 5, 63], np.int32)
    got = np.asarray(flash_decode_step_paged(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv), pos, bt,
        interpret=True))
    # oracle: gather the dense per-row cache, run the dense flash kernel
    kc = pk[bt].reshape(B, MB * bs, H, Dh)
    vc = pv[bt].reshape(B, MB * bs, H, Dh)
    ref = np.asarray(flash_decode_step(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc), pos,
        interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
