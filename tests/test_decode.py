"""Incremental decoding engine (serving/decode.py).

The load-bearing claims pinned here:
- a token decoded incrementally (stateful ``decode_step`` caches: LSTM
  (h, c) carries, attention KV caches) is BITWISE-equal to the same
  position of a teacher-forced full-prefix forward — for the LSTM stack
  and the transformer graph, at f32 AND bf16 compute;
- the continuous-batching engine matches the naive full-prefix-re-forward
  generator token-for-token under greedy decoding;
- sampling is deterministic in (seed, position) alone: the same request
  produces the same text regardless of arrival schedule or co-tenants;
- slot reuse never leaks state: a freed slot re-claimed by a new request
  produces bit-identical output (and decode-state) to a fresh engine;
- ONE compiled program per model covers every arrival schedule
  (trace_count == 1, counted the engine.py way).
"""

import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (DecodeEngine, InferenceClient,
                                        InferenceServer, generate_naive)
from deeplearning4j_tpu.zoo.simple import TinyTransformer

V = 13


def _lstm_net(compute_dtype=None):
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(LSTM(n_out=16, activation="tanh"))
            .layer(LSTM(n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_out=V, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(V))
            .build())
    if compute_dtype:
        conf.global_conf.compute_dtype = compute_dtype
    return MultiLayerNetwork(conf).init()


def _transformer(compute_dtype=None):
    kw = {"compute_dtype": compute_dtype} if compute_dtype else {}
    return TinyTransformer(vocab_size=V, n_layers=2, d_model=32, n_heads=4,
                           max_len=16, **kw).init()


def _onehot(tok):
    B, T = tok.shape
    x = np.zeros((B, T, V), np.float32)
    x[np.arange(B)[:, None], np.arange(T)[None, :], tok] = 1
    return jnp.asarray(x)


def _decode_all(model, x, T, B, is_graph):
    dstate = model.init_decode_state(B, max_len=T)
    step = jax.jit(model.decode_step)
    outs = []
    for t in range(T):
        y, dstate = step(model.params, model.state, dstate,
                         x[:, t:t + 1], jnp.full((B,), t, jnp.int32))
        outs.append(y)
    return jnp.concatenate(outs, axis=1)


def _full_forward(model, x, is_graph):
    if is_graph:
        acts, _, _ = jax.jit(lambda p, x: model._forward(
            p, model.state, [x], train=False, rng=None))(model.params, x)
        return acts[model.conf.network_outputs[0]]
    out, _, _ = jax.jit(lambda p, x: model._forward(
        p, model.state, x, train=False, rng=None))(model.params, x)
    return out


# ---------------------------------------------------------- bitwise parity

@pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
def test_lstm_decode_bitwise_equals_teacher_forcing(compute_dtype):
    net = _lstm_net(compute_dtype)
    rs = np.random.RandomState(0)
    tok = rs.randint(0, V, (2, 10))
    x = _onehot(tok)
    full = _full_forward(net, x, False)
    dec = _decode_all(net, x, 10, 2, False)
    assert np.array_equal(np.asarray(full, np.float32),
                          np.asarray(dec, np.float32))


@pytest.mark.parametrize("compute_dtype", [None, "bfloat16"])
def test_transformer_decode_bitwise_equals_teacher_forcing(compute_dtype):
    net = _transformer(compute_dtype)
    rs = np.random.RandomState(1)
    tok = rs.randint(0, V, (2, 10))
    x = _onehot(tok)
    full = _full_forward(net, x, True)
    # KV capacity == teacher-forced length: same softmax axis, so masked
    # cache rows are exact zeros in the attention sum (docs/DECODING.md)
    dec = _decode_all(net, x, 10, 2, True)
    assert np.array_equal(np.asarray(full, np.float32),
                          np.asarray(dec, np.float32))


# --------------------------------------------------------- engine vs naive

def test_engine_matches_naive_greedy_lstm():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=2, max_len=24).start()
    try:
        prompt = [3, 1, 4, 1, 5]
        got = eng.generate(prompt, max_new_tokens=8)
        ref = generate_naive(net, prompt, 8, max_len=24)
        assert got["tokens"] == ref["tokens"]
        assert got["prompt_len"] == 5
    finally:
        eng.stop()


def test_engine_matches_naive_greedy_transformer():
    net = _transformer()
    eng = DecodeEngine(net, slots=2, max_len=16).start()
    try:
        prompt = [2, 7, 11]
        got = eng.generate(prompt, max_new_tokens=6)
        ref = generate_naive(net, prompt, 6, max_len=16)
        assert got["tokens"] == ref["tokens"]
    finally:
        eng.stop()


# ---------------------------------------------------------------- sampling

def test_sampling_deterministic_across_arrival_schedules():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=4, max_len=24).start()
    try:
        prompt = [1, 2, 3]
        # solo run, empty engine
        a = eng.generate(prompt, max_new_tokens=8, seed=11,
                         temperature=0.9, top_k=4)
        # same request racing a crowd of co-tenants in other slots
        noise = [eng.submit([5, 6], 10, seed=i, temperature=1.3)
                 for i in range(3)]
        b = eng.generate(prompt, max_new_tokens=8, seed=11,
                         temperature=0.9, top_k=4)
        for f in noise:
            f.result(timeout=60)
        assert a["tokens"] == b["tokens"]
        # a different seed must decode differently (sanity that sampling
        # is live, not collapsed to greedy)
        c = eng.generate(prompt, max_new_tokens=8, seed=12,
                         temperature=0.9, top_k=4)
        assert len(c["tokens"]) == 8
        assert eng.trace_count == 1
    finally:
        eng.stop()


def test_greedy_is_temperature_zero_and_topk_one():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=2, max_len=24).start()
    try:
        prompt = [4, 4]
        greedy = eng.generate(prompt, max_new_tokens=6)
        # top_k=1 with any temperature can only pick the argmax token
        k1 = eng.generate(prompt, max_new_tokens=6, seed=99,
                          temperature=2.0, top_k=1)
        assert greedy["tokens"] == k1["tokens"]
    finally:
        eng.stop()


# ------------------------------------------------------------ slot reuse

def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x, np.float32), np.asarray(y, np.float32))
        for x, y in zip(la, lb))


def test_slot_reuse_is_bitwise_fresh():
    net = _lstm_net()
    req_b = dict(max_new_tokens=7, seed=3, temperature=0.8, top_k=5)
    # engine 1: request A occupies slot 0, finishes, then B re-claims it
    eng1 = DecodeEngine(net, slots=1, max_len=24).start()
    try:
        eng1.generate([9, 8, 7], max_new_tokens=9, seed=1, temperature=1.1)
        b_reused = eng1.generate([2, 6], **req_b)
        state_reused = eng1._dstate
        # engine 2: B decodes in a never-used slot
        eng2 = DecodeEngine(net, slots=1, max_len=24).start()
        try:
            b_fresh = eng2.generate([2, 6], **req_b)
            state_fresh = eng2._dstate
            assert b_reused["tokens"] == b_fresh["tokens"]
            # the reset mask wiped A completely: the device-resident state
            # after B is bit-identical to a fresh engine's
            assert _tree_equal(state_reused, state_fresh)
        finally:
            eng2.stop()
    finally:
        eng1.stop()


# ------------------------------------------------- continuous batching

def test_staggered_arrivals_one_program_all_complete():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=4, max_len=24).start()
    try:
        # sequential ground truth (empty engine per request)
        prompts = [[1, 2], [3, 4, 5], [6], [7, 8, 9, 10], [11], [2, 3]]
        solo = [eng.generate(p, max_new_tokens=5, seed=i, temperature=0.7)
                for i, p in enumerate(prompts)]
        results = {}

        def fire(i):
            time.sleep(0.002 * i)   # staggered arrivals, mid-flight claims
            results[i] = eng.generate(prompts[i], max_new_tokens=5, seed=i,
                                      temperature=0.7)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == len(prompts)
        for i, r in enumerate(solo):
            assert results[i]["tokens"] == r["tokens"]
        # iteration-level batching: 6 requests > 4 slots, still ONE program
        assert eng.trace_count == 1
        st = eng.stats()
        assert st["requests"] >= 12 and st["compiled_programs"] == 1
    finally:
        eng.stop()


def test_eos_frees_slot_early():
    net = _lstm_net()
    # force EOS on the greedy argmax of the first generated position
    probe = DecodeEngine(net, slots=1, max_len=24).start()
    try:
        eos = probe.generate([1, 2, 3], max_new_tokens=1)["tokens"][0]
    finally:
        probe.stop()
    eng = DecodeEngine(net, slots=1, max_len=24, eos_id=eos).start()
    try:
        out = eng.generate([1, 2, 3], max_new_tokens=10)
        assert out["tokens"][-1] == eos
        assert len(out["tokens"]) < 10 or out["tokens"][0] == eos
    finally:
        eng.stop()


def test_capacity_and_id_validation():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=1, max_len=8)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit([1, 2, 3, 4], max_new_tokens=5)
    with pytest.raises(ValueError, match="token ids"):
        eng.submit([V + 3], max_new_tokens=1)
    with pytest.raises(ValueError, match="at least one"):
        eng.submit([], max_new_tokens=1)


# ------------------------------------------------------------------- HTTP

def test_generate_over_http():
    net = _lstm_net()
    dec = DecodeEngine(net, slots=2, max_len=24)
    srv = InferenceServer(net, port=0, decode_engine=dec).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        out = cli.generate([3, 1, 4], max_new_tokens=6)
        ref = generate_naive(net, [3, 1, 4], 6, max_len=24)
        assert out["tokens"] == ref["tokens"]
        st = cli.stats()
        assert st["decode"]["compiled_programs"] == 1
        assert st["decode"]["requests"] >= 1
        # malformed payloads: structured 400s, not 500s
        with pytest.raises(ValueError, match="tokens"):
            cli._request("/generate", {"max_new_tokens": 3})
        with pytest.raises(ValueError, match="max_len"):
            cli._request("/generate", {"tokens": [1] * 30,
                                       "max_new_tokens": 30})
    finally:
        srv.stop()


def test_generate_404_without_decode_engine():
    net = _lstm_net()
    srv = InferenceServer(net, port=0).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        with pytest.raises(ValueError, match="decode engine"):
            cli._request("/generate", {"tokens": [1]})
    finally:
        srv.stop()


# ------------------------------------------------------------------- soak

@pytest.mark.slow
def test_decode_soak_many_requests_one_program():
    net = _lstm_net()
    eng = DecodeEngine(net, slots=8, max_len=32).start()
    try:
        rs = np.random.RandomState(5)
        futs = []
        for i in range(64):
            plen = int(rs.randint(1, 12))
            futs.append(eng.submit(list(rs.randint(0, V, plen)),
                                   max_new_tokens=int(rs.randint(1, 16)),
                                   seed=i, temperature=float(rs.rand())))
        outs = [f.result(timeout=300) for f in futs]
        assert all(len(o["tokens"]) >= 1 for o in outs)
        assert eng.trace_count == 1
        assert eng.stats()["requests"] == 64
    finally:
        eng.stop()


def test_healthz_degraded_while_decode_slots_saturated():
    """Satellite: a server whose DecodeEngine has every slot busy must
    report ``degraded`` (reason decode_saturated) on /healthz — routers
    steer prefill-heavy traffic away from it — and return to ``ok`` once
    slots free up."""
    net = _lstm_net()
    dec = DecodeEngine(net, slots=1, max_len=24)
    srv = InferenceServer(net, port=0, decode_engine=dec).start()
    try:
        cli = InferenceClient(f"http://127.0.0.1:{srv.port}")
        assert cli.health() == {"status": "ok"}
        futs = [dec.submit([1, 2], max_new_tokens=20) for _ in range(6)]
        saw = None
        deadline = time.time() + 60
        while time.time() < deadline:
            h = cli.health()
            if h.get("status") == "degraded":
                saw = h
                break
            time.sleep(0.001)
        assert saw == {"status": "degraded", "reason": "decode_saturated"}
        assert dec.saturated
        for f in futs:
            f.result(timeout=120)
        assert cli.health() == {"status": "ok"}
    finally:
        srv.stop()
