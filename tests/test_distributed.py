"""Two-process multi-host test for parallel/distributed.py (VERDICT r1 #8).

Spawns two real OS processes, each with 2 virtual CPU devices, forms the
jax.distributed cluster through a local coordinator, and asserts a pod-mesh
psum sums across the process boundary. CI-runnable, no TPU — the moral
equivalent of the reference's Spark `local[N]` distributed tests
(BaseSparkTest.java, SURVEY.md §4).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_WORKER = Path(__file__).with_name("_dist_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_pod_mesh_psum():
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(_WORKER.parents[1])
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(_WORKER), str(port), str(pid), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers hung:\n" + "\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_{pid}_OK" in out, out
    if any("psum=unsupported" in out for out in outs):
        # cluster formation, pod_mesh and device counts DID validate across
        # real process boundaries above; only the collective itself is
        # unavailable in this jaxlib build
        pytest.skip("this jaxlib's CPU backend implements no cross-process "
                    "collectives (psum raises INVALID_ARGUMENT); "
                    "run on TPU/GPU or a gloo-enabled jaxlib for the "
                    "psum assertion")
    for pid, out in enumerate(outs):
        assert f"WORKER_{pid}_OK psum=10.0" in out, out
