"""Two-process multi-host test for parallel/distributed.py (VERDICT r1 #8).

Spawns two real OS processes, each with 2 virtual CPU devices, forms the
jax.distributed cluster through a local coordinator, and asserts a pod-mesh
psum sums across the process boundary. CI-runnable, no TPU — the moral
equivalent of the reference's Spark `local[N]` distributed tests
(BaseSparkTest.java, SURVEY.md §4).

The cluster runs ONCE (module fixture); cluster formation, pod_mesh and
local_batch_slice assert unconditionally against it. Only the psum test is
gated on the jaxlib build actually shipping cross-process CPU collectives —
a missing transport must not mask a formation regression (it used to skip
the whole module).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_WORKER = Path(__file__).with_name("_dist_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def cluster_outs():
    """[(returncode, stdout)] for the two workers of one real cluster."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(_WORKER.parents[1])
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(_WORKER), str(port), str(pid), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers hung:\n" + "\n".join(outs))
    return [(p.returncode, out) for p, out in zip(procs, outs)]


def test_cluster_forms_across_real_processes(cluster_outs):
    for pid, (rc, out) in enumerate(cluster_outs):
        assert rc == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_{pid}_OK" in out, out


def test_pod_mesh_and_batch_slice_span_the_cluster(cluster_outs):
    # the worker asserts jax.process_count/index, the 4-device global mesh
    # and its local_batch_slice offsets before printing the marker
    for pid, (rc, out) in enumerate(cluster_outs):
        assert f"WORKER_{pid}_FORMED global=4 local=2" in out, out


def test_cross_process_psum(cluster_outs):
    if any("psum=unsupported" in out for _, out in cluster_outs):
        # formation/mesh/slice DID validate (tests above); only the
        # collective transport is absent in this jaxlib build
        pytest.skip("this jaxlib's CPU backend implements no cross-process "
                    "collectives (psum raises INVALID_ARGUMENT); "
                    "run on TPU/GPU or a gloo-enabled jaxlib for the "
                    "psum assertion")
    for pid, (_, out) in enumerate(cluster_outs):
        assert f"WORKER_{pid}_OK psum=10.0" in out, out
