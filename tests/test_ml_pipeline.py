"""ML-pipeline facade tests (parity role: dl4j-spark-ml SparkDl4jNetwork /
AutoEncoder estimator tests — see scaleout/ml_pipeline.py)."""

import numpy as np

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.scaleout import (NetworkClassifier,
                                         AutoEncoderEstimator, Pipeline,
                                         NetworkModel)


def _clf_conf():
    # updater pinned: the default SGD at its default rate deterministically
    # under-trains these blobs in the epoch budget (plateaus ~0.8, below
    # the score bars) — Adam reaches 1.0 on every scenario here
    return (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=8, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())


def _blobs(n=240, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(3, 8) * 3
    y = rs.randint(0, 3, n)
    X = centers[y] + rs.randn(n, 8) * 0.5
    return X.astype(np.float32), y


def test_classifier_fit_predict_score():
    X, y = _blobs()
    clf = NetworkClassifier(_clf_conf, epochs=20, batch_size=32)
    model = clf.fit(X, y)
    assert model.score(X, y) > 0.9
    proba = model.predict_proba(X[:5])
    assert proba.shape == (5, 3)
    np.testing.assert_allclose(proba.sum(-1), 1.0, atol=1e-4)
    # estimator delegates after fit (sklearn-style)
    assert clf.score(X, y) == model.score(X, y)


def test_classifier_sklearn_protocol_and_save_load(tmp_path):
    X, y = _blobs(120, seed=3)
    clf = NetworkClassifier(_clf_conf, epochs=5)
    assert clf.get_params()["epochs"] == 5
    clf.set_params(epochs=15, batch_size=64)
    model = clf.fit(X, y)
    p = str(tmp_path / "clf.zip")
    model.save(p)
    loaded = NetworkModel.load(p)
    np.testing.assert_allclose(loaded.predict_proba(X[:8]),
                               model.predict_proba(X[:8]), atol=1e-6)


def test_autoencoder_transform_shape_and_pipeline():
    def ae_conf():
        return (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=8, n_out=3, activation="tanh"))
                .layer(OutputLayer(n_in=3, n_out=8, activation="identity",
                                   loss="mse"))
                .build())

    X, y = _blobs(160, seed=5)
    ae = AutoEncoderEstimator(ae_conf, compressed_layer=0, epochs=10)
    enc = ae.fit(X).transform(X)
    assert enc.shape == (160, 3)

    def clf_conf():
        return (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_in=3, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())

    pipe = Pipeline([
        ("encode", AutoEncoderEstimator(ae_conf, compressed_layer=0,
                                        epochs=10)),
        ("classify", NetworkClassifier(clf_conf, epochs=25, batch_size=32)),
    ])
    pipe.fit(X, y)
    assert pipe.predict(X).shape == (160,)
    assert pipe.score(X, y) > 0.6


def test_classifier_on_mesh():
    """workers= routes training through ParallelWrapper (TrainingMaster
    role) over the virtual device mesh."""
    X, y = _blobs(192, seed=9)
    clf = NetworkClassifier(_clf_conf, epochs=40, batch_size=48, workers=8)
    model = clf.fit(X, y)
    assert model.score(X, y) > 0.85
