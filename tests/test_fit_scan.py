"""fit_scan (device-resident multi-step training) equivalence tests.

fit_scan runs k train steps inside one compiled lax.scan; it must produce
bit-identical math to k sequential fit() calls (same per-step rng fold-in,
same updater application). No reference equivalent (the reference's fit loop
dispatches per minibatch, MultiLayerNetwork.java:1204) — this is the
XLA-idiomatic fast path, so the oracle is our own sequential path.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.data.dataset import DataSet


def _mln():
    conf = (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(1e-2))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(k, b=16, f=6, c=3, seed=0):
    rs = np.random.RandomState(seed)
    xs = rs.randn(k, b, f).astype(np.float32)
    ys = np.eye(c, dtype=np.float32)[rs.randint(0, c, (k, b))]
    return xs, ys


class TestMLNFitScan:
    def test_matches_sequential_fit(self):
        k = 5
        xs, ys = _batches(k)
        seq = _mln()
        for i in range(k):
            seq.fit(DataSet(xs[i], ys[i]))
        scanned = _mln()
        scanned.fit_scan(xs, ys)

        assert scanned.iteration == seq.iteration == k
        for p_scan, p_seq in zip(scanned.params, seq.params):
            for key in p_seq:
                np.testing.assert_allclose(
                    np.asarray(p_scan[key]), np.asarray(p_seq[key]),
                    rtol=1e-5, atol=1e-6, err_msg=key)
        assert np.isfinite(scanned.get_score())
        np.testing.assert_allclose(scanned.get_score(), seq.get_score(),
                                   rtol=1e-5, atol=1e-6)

    def test_continues_iteration_count(self):
        xs, ys = _batches(3)
        net = _mln()
        net.fit_scan(xs, ys)
        net.fit_scan(xs, ys)
        assert net.iteration == 6


class TestCGFitScan:
    def test_matches_sequential_fit(self):
        from deeplearning4j_tpu.models import ComputationGraph
        from deeplearning4j_tpu.nn.conf.inputs import InputType

        def build():
            g = (NeuralNetConfiguration.builder()
                 .seed(7)
                 .updater(Adam(1e-2))
                 .weight_init("xavier")
                 .graph_builder()
                 .add_inputs("in")
                 .set_input_types(InputType.feed_forward(6))
                 .add_layer("h", DenseLayer(n_out=8, activation="tanh"), "in")
                 .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                               loss="mcxent"), "h")
                 .set_outputs("out")
                 .build())
            return ComputationGraph(g).init()

        k = 4
        xs, ys = _batches(k, seed=3)
        seq = build()
        for i in range(k):
            seq.fit(xs[i], ys[i])
        scanned = build()
        scanned.fit_scan(xs, ys)

        assert scanned.iteration == seq.iteration == k
        for name in seq.params:
            for key in seq.params[name]:
                np.testing.assert_allclose(
                    np.asarray(scanned.params[name][key]),
                    np.asarray(seq.params[name][key]),
                    rtol=1e-5, atol=1e-6, err_msg=f"{name}/{key}")
        np.testing.assert_allclose(scanned.get_score(), seq.get_score(),
                                   rtol=1e-5, atol=1e-6)


class TestMixedPrecision:
    def test_bf16_compute_keeps_f32_master_params(self):
        """compute_dtype='bfloat16': forward/backward run in bf16 (params
        cast inside _forward), master params and BN running stats stay f32,
        training still learns."""
        from deeplearning4j_tpu.nn.layers import (ConvolutionLayer,
                                                  BatchNormalization)
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(1e-2)).weight_init("xavier")
                .compute_dtype("bfloat16")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=3,
                                        has_bias=False))
                .layer(BatchNormalization(activation="relu"))
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(0)
        x = rs.randn(16, 8, 8, 1).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
        l0 = net.score(x=x, y=y)
        for _ in range(20):
            net.fit(x, y)
        assert net.score(x=x, y=y) < l0
        import jax
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32, leaf.dtype
        for leaf in jax.tree_util.tree_leaves(net.state):
            if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                         jnp.floating):
                assert leaf.dtype == jnp.float32, leaf.dtype

    def test_bf16_compute_on_graph(self):
        from deeplearning4j_tpu.models import ComputationGraph
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        g = (NeuralNetConfiguration.builder().seed(2).updater(Adam(1e-2))
             .weight_init("xavier").compute_dtype("bfloat16")
             .graph_builder().add_inputs("in")
             .set_input_types(InputType.feed_forward(6))
             .add_layer("h", DenseLayer(n_out=8, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                           loss="mcxent"), "h"))
        cg = ComputationGraph(g.set_outputs("out").build()).init()
        xs, ys = _batches(1)
        cg.fit(xs[0], ys[0])
        assert np.isfinite(cg.get_score())
        import jax
        for leaf in jax.tree_util.tree_leaves(cg.params):
            assert leaf.dtype == jnp.float32
