"""Observability subsystem: metrics registry, span tracer, and their wiring.

The load-bearing claims pinned here:
- the registry is exact under concurrent writers (8 threads of increments
  lose nothing — Counter holds a lock, not a hope);
- histogram buckets use Prometheus ``le`` (≤) semantics and the rendered
  text exposition round-trips through an independent parser: cumulative
  buckets are monotone and the ``+Inf`` bucket equals ``_count``;
- the tracer emits balanced, correctly NESTED begin/end events and valid
  Chrome trace JSON; disabled, it returns a shared no-op span and records
  nothing;
- a streamed ``fit`` under tracing produces ``train_step`` spans that
  nest the ``wait``/``step`` (and ``fetch``/``h2d``) children — the
  acceptance shape for a Perfetto timeline;
- ``GET /metrics`` serves the request-latency histogram and queue-depth
  gauge in valid exposition text, ``GET /healthz`` answers, and ``/stats``
  agrees with ``/metrics`` because both read the same registry cells;
- training is bitwise-identical with monitoring on vs off.
"""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.monitor import (
    MetricsRegistry, Tracer, get_registry, set_metrics_enabled, trace)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updaters import Adam, Sgd
from deeplearning4j_tpu.serving import InferenceServer


@pytest.fixture(autouse=True)
def _restore_observability():
    """Every test leaves the process-wide registry/tracer as it found them."""
    reg = get_registry()
    prev_enabled = reg.enabled
    prev_trace = trace.enabled
    yield
    reg.enabled = prev_enabled
    trace.enable(prev_trace)
    trace.clear()


def _mlp(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _toy_data(n_batches=6, batch=8, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        x = rs.rand(batch, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, size=batch)]
        out.append(DataSet(x, y))
    return out


# A parser independent of the renderer: Prometheus text exposition lines.
_LINE = re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (\S+)$')


def _parse_exposition(text):
    """{series_with_labels: float} plus {name: TYPE} from a /metrics body."""
    series, types = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = _LINE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        series[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return series, types


# ------------------------------------------------------------- registry core

def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", "jobs", ("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2.5)
    c.labels(kind="b").inc()
    assert c.labels(kind="a").value == 3.5
    assert c.labels(kind="b").value == 1.0
    with pytest.raises(ValueError):
        c.labels(kind="a").inc(-1)
    with pytest.raises(ValueError):
        c.labels(wrong="a")
    g = reg.gauge("depth")
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value == 9.0


def test_registry_rejects_kind_and_label_mismatch():
    reg = MetricsRegistry()
    reg.counter("x_total", "x", ("a",))
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", "x", ("b",))


def test_histogram_bucket_boundaries_le_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "l", buckets=(1.0, 2.0, 5.0))
    for v in (1.0, 2.5, 7.0):     # 1.0 lands IN the le=1 bucket (≤, not <)
        h.observe(v)
    assert h.cumulative() == [(1.0, 1), (2.0, 1), (5.0, 2),
                              (float("inf"), 3)]
    assert h.count == 3 and h.sum == pytest.approx(10.5)


def test_histogram_percentile():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "l", buckets=(0.01, 0.1, 1.0))
    assert h.percentile(0.5) is None          # nothing observed yet
    for _ in range(100):
        h.observe(0.05)                        # all in the (0.01, 0.1] bucket
    p50 = h.percentile(0.5)
    assert 0.01 < p50 <= 0.1
    h.observe(50.0)                            # beyond the last finite bound
    assert h.percentile(1.0) == 1.0            # saturates at that bound


def test_registry_thread_safety_exact_totals():
    reg = MetricsRegistry()
    c = reg.counter("hits_total")
    h = reg.histogram("obs", buckets=(0.5,))
    n_threads, n_incs = 8, 5000

    def worker():
        for _ in range(n_incs):
            c.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_incs
    assert h.count == n_threads * n_incs
    assert h.cumulative()[-1] == (float("inf"), n_threads * n_incs)


def test_enabled_flag_gates_recording():
    reg = MetricsRegistry()
    c = reg.counter("n_total")
    h = reg.histogram("h", buckets=(1.0,))
    reg.enabled = False
    c.inc()
    h.observe(0.5)
    assert c.value == 0 and h.count == 0
    reg.enabled = True
    c.inc()
    assert c.value == 1


def test_function_gauge_reads_live():
    reg = MetricsRegistry()
    box = {"v": 3}
    g = reg.gauge("live").set_function(lambda: box["v"])
    assert g.value == 3.0
    box["v"] = 11
    assert g.value == 11.0
    assert 'live 11.0' in reg.render()


def test_prometheus_exposition_round_trip():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests", ("path",)).labels(
        path="/a").inc(4)
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_seconds", "latency", ("path",), buckets=(0.1, 1.0))
    h.labels(path="/a").observe(0.05)
    h.labels(path="/a").observe(0.5)
    h.labels(path="/a").observe(5.0)
    series, types = _parse_exposition(reg.render())
    assert types == {"req_total": "counter", "depth": "gauge",
                     "lat_seconds": "histogram"}
    assert series['req_total{path="/a"}'] == 4.0
    assert series["depth"] == 2.0
    # cumulative buckets are monotone and +Inf equals _count
    b1 = series['lat_seconds_bucket{path="/a",le="0.1"}']
    b2 = series['lat_seconds_bucket{path="/a",le="1.0"}']
    binf = series['lat_seconds_bucket{path="/a",le="+Inf"}']
    assert (b1, b2, binf) == (1.0, 2.0, 3.0)
    assert series['lat_seconds_count{path="/a"}'] == 3.0
    assert series['lat_seconds_sum{path="/a"}'] == pytest.approx(5.55)


def test_snapshot_flat_dict():
    reg = MetricsRegistry()
    reg.counter("a_total").inc(2)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap["a_total"] == 2.0
    assert snap["h_count"] == 1 and snap["h_sum"] == 0.5
    assert reg.snapshot(kinds=("counter",)) == {"a_total": 2.0}


# ----------------------------------------------------------------- tracer

def _span_pairs(events):
    """Match B/E per tid by stack discipline; returns [(B, E), ...] and
    asserts balance + proper nesting (an E always closes the open B)."""
    stacks, pairs = {}, []
    for ev in sorted(events, key=lambda e: e["ts"]):
        if ev["ph"] == "B":
            stacks.setdefault(ev["tid"], []).append(ev)
        elif ev["ph"] == "E":
            top = stacks[ev["tid"]].pop()
            assert top["name"] == ev["name"], "interleaved, not nested"
            pairs.append((top, ev))
    assert all(not s for s in stacks.values()), "unbalanced B/E"
    return pairs


def test_tracer_nested_spans_and_export(tmp_path):
    tr = Tracer(enabled=True)
    with tr.span("outer", n=1):
        with tr.span("inner"):
            pass
        with tr.span("inner"):
            pass
    tr.instant("marker")
    pairs = _span_pairs([e for e in tr.events() if e["ph"] in "BE"])
    names = sorted(b["name"] for b, _ in pairs)
    assert names == ["inner", "inner", "outer"]
    outer = next(b for b, _ in pairs if b["name"] == "outer")
    outer_end = next(e for b, e in pairs if b["name"] == "outer")
    for b, e in pairs:
        if b["name"] == "inner":
            assert outer["ts"] <= b["ts"] and e["ts"] <= outer_end["ts"]
    assert outer["args"] == {"n": 1}

    path = tmp_path / "trace.json"
    doc = tr.export(str(path))
    loaded = json.loads(path.read_text())   # valid JSON on disk
    assert loaded["traceEvents"] == doc["traceEvents"]
    ts = [e["ts"] for e in loaded["traceEvents"]]
    assert ts == sorted(ts)
    assert any(e["ph"] == "i" and e["name"] == "marker"
               for e in loaded["traceEvents"])


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    s1 = tr.span("a")
    s2 = tr.span("b", k=1)
    assert s1 is s2                       # the shared null span: no alloc
    with s1:
        pass
    tr.instant("x")
    assert tr.events() == []


def test_tracer_ring_buffer_bounds_memory():
    tr = Tracer(capacity=10, enabled=True)
    for i in range(50):
        with tr.span(f"s{i}"):
            pass
    evs = tr.events()
    assert len(evs) == 10
    assert evs[-1]["name"] == "s49"       # newest kept, oldest dropped


# ------------------------------------------------------- training integration

def test_streamed_fit_trace_nests_step_spans():
    net = _mlp()
    trace.enable(True)
    trace.clear()
    try:
        net.fit(_toy_data(n_batches=6))
    finally:
        trace.enable(False)
    events = [e for e in trace.events() if e["ph"] in "BE"]
    pairs = _span_pairs(events)
    by_name = {}
    for b, e in pairs:
        by_name.setdefault(b["name"], []).append((b, e))
    for required in ("train_step", "wait", "step", "fetch", "h2d"):
        assert required in by_name, f"missing span {required!r}"
    # every step span sits inside some train_step span
    for sb, se in by_name["step"]:
        assert any(tb["ts"] <= sb["ts"] and se["ts"] <= te["ts"]
                   for tb, te in by_name["train_step"]
                   if tb["tid"] == sb["tid"]), "step not nested in train_step"


def test_train_metrics_recorded_and_pipeline_published():
    reg = get_registry()
    steps_fam = reg.counter("dl4jtpu_train_steps_total",
                            labelnames=("model",))
    before = steps_fam.labels(model="MultiLayerNetwork").value
    net = _mlp()
    net.fit(_toy_data(n_batches=6))
    after = steps_fam.labels(model="MultiLayerNetwork").value
    assert after - before == 6            # every scanned step is counted
    ex_fam = reg.get("dl4jtpu_train_examples_total")
    assert ex_fam is not None
    stage = reg.get("dl4jtpu_pipeline_stage_seconds_total")
    assert stage is not None
    assert stage.labels(path="fit", stage="step").value > 0
    frac = reg.get("dl4jtpu_pipeline_host_stall_frac")
    assert 0.0 <= frac.labels(path="fit").value <= 1.0
    # the registry snapshot renders cleanly with everything above in it
    assert "dl4jtpu_train_steps_total" in reg.render()


def test_training_bitwise_identical_monitored_or_not():
    data = _toy_data(n_batches=4)
    set_metrics_enabled(True)
    trace.enable(True)
    try:
        net_on = _mlp(seed=7)
        net_on.fit(data)
    finally:
        trace.enable(False)
    set_metrics_enabled(False)
    try:
        net_off = _mlp(seed=7)
        net_off.fit(data)
    finally:
        set_metrics_enabled(True)
    for a, b in zip(net_on.params, net_off.params):
        for k in a:
            assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), \
                f"monitoring changed the training math at {k}"


# ------------------------------------------------------------ serving surface

def test_metrics_and_healthz_endpoints():
    net = _mlp()
    srv = InferenceServer(net, port=0, max_latency_ms=1.0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        # traffic first, so the latency histogram has observations
        rs = np.random.RandomState(3)
        for n in (1, 5, 8):
            out = srv.batcher.predict(rs.rand(n, 4).astype(np.float32))
            assert out.shape == (n, 3)

        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read()) == {"status": "ok"}

        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        series, types = _parse_exposition(body)
        assert types["dl4jtpu_serving_request_latency_seconds"] == "histogram"
        assert types["dl4jtpu_serving_queue_depth"] == "gauge"
        bid = srv.batcher.id
        assert series[f'dl4jtpu_serving_request_latency_seconds_count'
                      f'{{batcher="{bid}"}}'] == 3.0
        assert series[f'dl4jtpu_serving_queue_depth{{batcher="{bid}"}}'] == 0.0
        assert series[f'dl4jtpu_serving_requests_total{{batcher="{bid}"}}'] \
            == 3.0
        # the /healthz hit above landed in the HTTP counter by scrape time
        assert series['dl4jtpu_http_requests_total{path="/healthz"}'] >= 1.0
    finally:
        srv.stop()


def test_stats_and_metrics_read_the_same_cells():
    net = _mlp()
    srv = InferenceServer(net, port=0, max_latency_ms=1.0).start()
    try:
        rs = np.random.RandomState(4)
        for n in (2, 3, 9, 1):
            srv.batcher.predict(rs.rand(n, 4).astype(np.float32))
        st = srv.stats()
        series, _ = _parse_exposition(get_registry().render())
        bid, eid = st["batcher"]["id"], st["engine"]["id"]
        assert st["batcher"]["requests"] == series[
            f'dl4jtpu_serving_requests_total{{batcher="{bid}"}}']
        assert st["batcher"]["rows"] == series[
            f'dl4jtpu_serving_rows_total{{batcher="{bid}"}}'] == 15
        assert st["batcher"]["device_calls"] == series[
            f'dl4jtpu_serving_device_calls_total{{batcher="{bid}"}}']
        assert st["engine"]["compiled_programs"] == series[
            f'dl4jtpu_serving_compiled_programs_total{{engine="{eid}"}}']
        assert st["engine"]["rows"] == series[
            f'dl4jtpu_serving_batch_rows_total{{engine="{eid}"}}']
        assert 0.0 <= st["engine"]["pad_waste_frac"] < 1.0
        assert st["batcher"]["latency_p50_ms"] > 0
    finally:
        srv.stop()


# --------------------------------------------------------------- listeners

def test_score_listener_logs_without_stdout(capsys):
    from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

    class _M:
        def get_score(self):
            return 0.5

    lst = ScoreIterationListener(1)
    lst.iteration_done(_M(), 10, 0)
    assert capsys.readouterr().out == ""   # logger only, no bare print


def test_performance_listener_registry_sink():
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    class _M:
        _last_input = np.zeros((16, 4), np.float32)
        _last_fit_time = 0.002

        def get_score(self):
            return 0.25

    reg = MetricsRegistry()
    lst = PerformanceListener(frequency=10, registry=reg)
    lst.iteration_done(_M(), 10, 0)        # arms the window
    lst.iteration_done(_M(), 20, 0)        # reports
    batches = reg.get("dl4jtpu_listener_batches_per_sec").value
    samples = reg.get("dl4jtpu_listener_samples_per_sec").value
    assert batches > 0
    assert samples == pytest.approx(batches * 16)
