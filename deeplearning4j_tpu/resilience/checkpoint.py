"""Crash-safe checkpointing: manager, listener, resume discovery.

Layout (docs/FAULT_TOLERANCE.md): a checkpoint directory holds

- ``checkpoint_iter0000000123_epoch0002.zip`` — one atomic ModelSerializer
  zip per save (params + updater state + iteration/epoch/epoch_batch);
  names sort lexicographically in save order, so the directory is
  self-describing even without the manifest;
- ``manifest.json`` — the manager's ledger: every live checkpoint with its
  counters, save wall-time and pinned flag, plus the running save count.
  Rewritten atomically after every save/rotation, so it never references a
  half-written zip and a torn manifest is impossible.

Rotation keeps the newest ``keep_last`` unpinned checkpoints; with
``keep_every=M`` every M-th save (the 1st, M+1th, 2M+1th, …) is pinned and
exempt from rotation — long runs retain a sparse history plus a dense
recent window.

``CheckpointListener`` triggers on an iteration DELTA
(``iteration - last_saved >= every_n_iterations``), not ``%`` — under
``fit_scan`` the iteration counter advances in chunk-sized jumps and a
modulo test can skip its own cadence forever.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import time
from dataclasses import dataclass
from typing import List, Optional

from deeplearning4j_tpu.util import model_serializer

MANIFEST_NAME = "manifest.json"
_FILE_RE = re.compile(r"^checkpoint_iter(\d{10})_epoch(\d{4})\.zip$")

__all__ = ["Checkpoint", "CheckpointManager", "CheckpointListener",
           "checkpoint_filename", "latest_checkpoint", "MANIFEST_NAME"]


def checkpoint_filename(iteration: int, epoch: int) -> str:
    return f"checkpoint_iter{iteration:010d}_epoch{epoch:04d}.zip"


@dataclass(frozen=True)
class Checkpoint:
    """One manifest entry."""

    filename: str
    iteration: int
    epoch: int
    epoch_batch: int = 0
    pinned: bool = False
    saved_at: float = 0.0

    def path(self, directory) -> str:
        return os.path.join(os.fspath(directory), self.filename)


class CheckpointManager:
    """Owns a checkpoint directory: atomic saves, manifest, rotation.

    Not thread-safe by design — one manager per training loop, called from
    the listener on the fit thread.
    """

    def __init__(self, directory, keep_last: int = 3,
                 keep_every: Optional[int] = None, save_updater: bool = True):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if keep_every is not None and keep_every < 1:
            raise ValueError(f"keep_every must be >= 1, got {keep_every}")
        self.directory = os.fspath(directory)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.save_updater = save_updater
        os.makedirs(self.directory, exist_ok=True)
        self._entries: List[Checkpoint] = []
        self._save_count = 0
        self._anchor_iteration: Optional[int] = None
        self._load_manifest()

    # -- manifest ----------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    def _load_manifest(self):
        try:
            with open(self._manifest_path(), "r") as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            self._recover_from_scan()
            return
        except (json.JSONDecodeError, OSError):
            # a manifest damaged out-of-band (we only ever os.replace it)
            # is advisory — the zips are the truth, rebuild from them
            self._recover_from_scan()
            return
        self._save_count = int(doc.get("save_count", 0))
        anchor = doc.get("anchor_iteration")
        self._anchor_iteration = int(anchor) if anchor is not None else None
        self._entries = [
            Checkpoint(filename=e["filename"], iteration=int(e["iteration"]),
                       epoch=int(e["epoch"]),
                       epoch_batch=int(e.get("epoch_batch", 0)),
                       pinned=bool(e.get("pinned", False)),
                       saved_at=float(e.get("saved_at", 0.0)))
            for e in doc.get("checkpoints", ())]
        # drop entries whose zip vanished out-of-band
        self._entries = [c for c in self._entries
                         if os.path.exists(c.path(self.directory))]

    def _recover_from_scan(self):
        found = []
        for name in sorted(os.listdir(self.directory)):
            m = _FILE_RE.match(name)
            if m:
                found.append(Checkpoint(filename=name,
                                        iteration=int(m.group(1)),
                                        epoch=int(m.group(2))))
        self._entries = found
        self._save_count = len(found)

    def _write_manifest(self):
        doc = {"format": "deeplearning4j_tpu/checkpoint-manifest/v1",
               "save_count": self._save_count,
               "anchor_iteration": self._anchor_iteration,
               "checkpoints": [
                   {"filename": c.filename, "iteration": c.iteration,
                    "epoch": c.epoch, "epoch_batch": c.epoch_batch,
                    "pinned": c.pinned, "saved_at": c.saved_at}
                   for c in self._entries]}
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(doc, fh, indent=1)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self._manifest_path())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- public surface ----------------------------------------------------

    def save(self, model, normalizer=None) -> str:
        """Write one checkpoint atomically, record it, rotate. Returns the
        checkpoint path."""
        name = checkpoint_filename(model.iteration, model.epoch)
        path = os.path.join(self.directory, name)
        model_serializer.write_model(model, path,
                                     save_updater=self.save_updater,
                                     normalizer=normalizer)
        self._save_count += 1
        pinned = (self.keep_every is not None
                  and (self._save_count - 1) % self.keep_every == 0)
        entry = Checkpoint(filename=name, iteration=model.iteration,
                           epoch=model.epoch,
                           epoch_batch=int(getattr(model, "_epoch_batch", 0)),
                           pinned=pinned, saved_at=time.time())
        # re-saving at the same (iteration, epoch) replaces the entry
        self._entries = [c for c in self._entries if c.filename != name]
        self._entries.append(entry)
        self._rotate()
        self._write_manifest()
        return path

    def _rotate(self):
        unpinned = [c for c in self._entries if not c.pinned]
        while len(unpinned) > self.keep_last:
            victim = unpinned.pop(0)        # oldest unpinned
            self._entries.remove(victim)
            try:
                os.unlink(victim.path(self.directory))
            except OSError:
                pass
            # the AOT artifact rides its checkpoint: rotate them together
            try:
                from deeplearning4j_tpu.exec.aot import companion_path
                aot = companion_path(victim.path(self.directory))
                if os.path.exists(aot):
                    os.unlink(aot)
            except Exception:   # noqa: BLE001 — rotation must not raise
                pass

    def pin(self, iteration: int) -> Checkpoint:
        """Pin the checkpoint saved at ``iteration`` after the fact so it is
        exempt from ``keep_last`` rotation — what a promotion pins so its
        rollback target survives arbitrarily long training runs. Idempotent;
        raises ``ValueError`` when no live checkpoint has that iteration."""
        return self._set_pinned(iteration, True)

    def unpin(self, iteration: int) -> Checkpoint:
        """Drop the pin on ``iteration``'s checkpoint. The entry immediately
        re-enters ``keep_last`` rotation (and may be rotated away by this
        very call if it is already outside the recent window)."""
        return self._set_pinned(iteration, False)

    def _set_pinned(self, iteration: int, flag: bool) -> Checkpoint:
        iteration = int(iteration)
        hits = [i for i, c in enumerate(self._entries)
                if c.iteration == iteration]
        if not hits:
            live = sorted(c.iteration for c in self._entries)
            raise ValueError(
                f"no checkpoint at iteration {iteration} in "
                f"{self.directory} (live iterations: {live})")
        entry = self._entries[hits[0]]
        if entry.pinned != flag:
            for i in hits:
                self._entries[i] = dataclasses.replace(self._entries[i],
                                                       pinned=flag)
            entry = self._entries[hits[0]]
            if not flag:
                self._rotate()
            self._write_manifest()
        return entry

    def set_anchor(self, iteration: int) -> Checkpoint:
        """Advance the recovery anchor to ``iteration``: pin it, then unpin
        the previous anchor so only one checkpoint is ever anchor-held.
        The elastic coordinator calls this after every checkpoint commit —
        the anchored step is where survivors barrier and replacements
        restore from, so rotation must never take it, no matter how far
        training runs ahead. The anchor persists in the manifest, so a
        replacement rank 0 opening the same directory unpins its dead
        predecessor's anchor instead of leaking the pin forever."""
        iteration = int(iteration)
        prev = self._anchor_iteration
        # set before pin: pin's manifest write must carry the new anchor
        self._anchor_iteration = iteration
        entry = self.pin(iteration)
        if prev is not None and prev != iteration:
            try:
                self.unpin(prev)
            except ValueError:
                pass            # previous anchor already rotated/unknown
        if prev != iteration:
            # pin/unpin skip writing when the flag did not flip (e.g. the
            # entry was already pinned); the moved anchor must still land
            self._write_manifest()
        return entry

    @property
    def anchor(self) -> Optional[int]:
        """Iteration of the current recovery anchor (None before the first
        ``set_anchor``)."""
        return self._anchor_iteration

    def checkpoints(self) -> List[Checkpoint]:
        return list(self._entries)

    def latest(self) -> Optional[str]:
        if not self._entries:
            return None
        best = max(self._entries, key=lambda c: (c.iteration, c.epoch))
        return best.path(self.directory)

    def latest_aot(self) -> Optional[str]:
        """The AOT artifact riding the latest checkpoint
        (``<checkpoint>.aot.zip``), or None when absent — what an
        autoscaler hands to ``ReplicaProcess(aot=...)``."""
        path = self.latest()
        if path is None:
            return None
        from deeplearning4j_tpu.exec.aot import companion_path
        aot = companion_path(path)
        return aot if os.path.exists(aot) else None


def latest_checkpoint(directory) -> Optional[str]:
    """Most recent checkpoint in ``directory`` (manifest first, filename
    scan as fallback), or None. What ``fit(resume_from=...)`` accepts when
    handed a directory instead of a zip path."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        return None
    return CheckpointManager(directory, keep_last=10 ** 9).latest()


class CheckpointListener:
    """Save every N iterations and/or epochs during ``fit`` (IterationListener
    SPI — duck-typed so this module never imports optimize.listeners).

    ``fit(..., checkpoint=...)`` attaches one of these for the duration of
    the call; it can equally be added to ``model.listeners`` directly.
    """

    def __init__(self, directory, every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = None, keep_last: int = 3,
                 keep_every: Optional[int] = None, save_updater: bool = True,
                 normalizer=None):
        if not every_n_iterations and not every_n_epochs:
            raise ValueError("CheckpointListener needs every_n_iterations "
                             "and/or every_n_epochs")
        self.manager = CheckpointManager(directory, keep_last=keep_last,
                                         keep_every=keep_every,
                                         save_updater=save_updater)
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.normalizer = normalizer
        self._baseline_iter: Optional[int] = None
        self.last_saved_path: Optional[str] = None

    def _save(self, model):
        self.last_saved_path = self.manager.save(model,
                                                 normalizer=self.normalizer)

    def iteration_done(self, model, iteration: int, epoch: int):
        if not self.every_n_iterations:
            return
        if self._baseline_iter is None:
            # first observation: anchor the cadence so a resumed run saves
            # at the same iteration numbers as an uninterrupted one
            self._baseline_iter = iteration - 1
        if iteration - self._baseline_iter >= self.every_n_iterations:
            self._save(model)
            self._baseline_iter = iteration

    def on_epoch_end(self, model):
        if self.every_n_epochs and model.epoch % self.every_n_epochs == 0:
            self._save(model)
