"""Test-only fault injection (docs/FAULT_TOLERANCE.md).

Everything here exists to PROVE the resilience layer's claims in
tests/test_resilience.py rather than to ship in a training loop:

- ``CrashAfter`` — an IterationListener that raises ``SimulatedCrash`` once
  the iteration counter crosses a threshold, killing a fit mid-epoch from
  the inside (the fast, in-process stand-in for SIGKILL; the subprocess
  soak test does the real kill).
- ``FlakyIterator`` — wraps a DataSetIterator and raises a scripted error
  on chosen ``next()`` calls (transient or fatal).
- ``FlakyBroker`` — wraps the in-memory kafka client; scripted poll/send
  failures and corrupt records exercise the consumer pump's retry + skip
  paths.
- ``FlakyEngine`` — wraps an inference engine; scripted delays and
  failures drive the serving storm tests (expired deadlines, 429s, engine
  faults → 500).

``SimulatedCrash`` subclasses BaseException on purpose: production code is
entitled to ``except Exception`` around batches, and a simulated kill must
not be swallowable by any of it — exactly like a real SIGKILL isn't.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["SimulatedCrash", "CrashAfter", "FlakyIterator", "FlakyBroker",
           "FlakyEngine"]


class SimulatedCrash(BaseException):
    """An injected process death. BaseException so no ``except Exception``
    handler between the fit loop and the test can eat it."""


class CrashAfter:
    """IterationListener that crashes the fit once ``iteration >= at_iteration``.

    Order it BEFORE any CheckpointListener in the listeners list so the
    crash fires before the same iteration gets checkpointed — the resumed
    run then genuinely re-trains from an older step.
    """

    def __init__(self, at_iteration: int):
        self.at_iteration = at_iteration
        self.fired = False

    def iteration_done(self, model, iteration: int, epoch: int):
        if iteration >= self.at_iteration and not self.fired:
            self.fired = True
            raise SimulatedCrash(
                f"injected crash at iteration {iteration} (epoch {epoch})")

    def on_epoch_end(self, model):
        pass


class FlakyIterator:
    """Wrap a DataSetIterator; raise ``errors[n]`` on the n-th ``next()``
    call (0-based, counted across resets). Everything else delegates."""

    def __init__(self, base, errors: Optional[Dict[int, BaseException]] = None):
        self._base = base
        self._errors = dict(errors or {})
        self.calls = 0

    def __iter__(self):
        iter(self._base)
        return self

    def __next__(self):
        n = self.calls
        self.calls += 1
        exc = self._errors.pop(n, None)
        if exc is not None:
            raise exc
        return next(self._base)

    def reset(self):
        self._base.reset()

    def __getattr__(self, name):
        return getattr(self._base, name)


class FlakyBroker:
    """Wrap a kafka-like client: scripted failures on poll/send plus
    optional corrupt records injected into poll results.

    ``fail_polls`` / ``fail_sends``: {call_index: exception} (0-based).
    ``corrupt_at``: poll call indices whose records get their payloads
    replaced with garbage bytes (undecodable by ``decode_record``).
    """

    def __init__(self, base, fail_polls: Optional[Dict[int, BaseException]] = None,
                 fail_sends: Optional[Dict[int, BaseException]] = None,
                 corrupt_at: Optional[set] = None):
        self._base = base
        self._fail_polls = dict(fail_polls or {})
        self._fail_sends = dict(fail_sends or {})
        self._corrupt_at = set(corrupt_at or ())
        self.poll_calls = 0
        self.send_calls = 0

    def poll(self, *args, **kwargs):
        n = self.poll_calls
        self.poll_calls += 1
        exc = self._fail_polls.pop(n, None)
        if exc is not None:
            raise exc
        records = self._base.poll(*args, **kwargs)
        if n in self._corrupt_at and records:
            records = [type(r)(*[b"\x00garbage" if isinstance(v, bytes) else v
                                 for v in r]) if isinstance(r, tuple)
                       else b"\x00garbage" for r in records]
        return records

    def send(self, *args, **kwargs):
        n = self.send_calls
        self.send_calls += 1
        exc = self._fail_sends.pop(n, None)
        if exc is not None:
            raise exc
        return self._base.send(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._base, name)


class FlakyEngine:
    """Wrap an inference engine for serving storm tests.

    ``delay``: seconds to sleep inside every ``predict`` (makes the
    micro-batcher queue fill so 429/deadline paths are reachable).
    ``fail_calls``: {call_index: exception} raised instead of predicting.
    ``gate``: optional threading.Event — when set, predict blocks on it
    before running, letting a test hold the device "busy" deterministically.
    """

    def __init__(self, base, delay: float = 0.0,
                 fail_calls: Optional[Dict[int, BaseException]] = None,
                 gate: Optional[threading.Event] = None):
        self._base = base
        self.delay = delay
        self._fail_calls = dict(fail_calls or {})
        self.gate = gate
        self.calls = 0
        self.rows_seen = 0

    def _intercept(self, x):
        n = self.calls
        self.calls += 1
        if self.gate is not None:
            self.gate.wait()
        if self.delay > 0:
            time.sleep(self.delay)
        exc = self._fail_calls.pop(n, None)
        if exc is not None:
            raise exc
        try:
            self.rows_seen += int(x.shape[0])
        except Exception:
            pass

    def predict_host(self, x, *args, **kwargs):
        """The micro-batcher's entry point."""
        self._intercept(x)
        return self._base.predict_host(x, *args, **kwargs)

    def predict(self, x, *args, **kwargs):
        self._intercept(x)
        return self._base.predict(x, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._base, name)
