"""Test-only fault injection (docs/FAULT_TOLERANCE.md).

Everything here exists to PROVE the resilience layer's claims in
tests/test_resilience.py rather than to ship in a training loop:

- ``CrashAfter`` — an IterationListener that raises ``SimulatedCrash`` once
  the iteration counter crosses a threshold, killing a fit mid-epoch from
  the inside (the fast, in-process stand-in for SIGKILL; the subprocess
  soak test does the real kill).
- ``FlakyIterator`` — wraps a DataSetIterator and raises a scripted error
  on chosen ``next()`` calls (transient or fatal).
- ``FlakyBroker`` — wraps the in-memory kafka client; scripted poll/send
  failures and corrupt records exercise the consumer pump's retry + skip
  paths.
- ``FlakyEngine`` — wraps an inference engine; scripted delays and
  failures drive the serving storm tests (expired deadlines, 429s, engine
  faults → 500).
- ``ServerFaultInjector`` — PROCESS-LEVEL chaos at a replica server:
  injected latency and 5xx on /predict//generate, reconfigurable live over
  ``POST /chaos`` so the router chaos soak can brown out a subprocess
  replica it cannot reach in-process.
- ``BlackholeProxy`` — a TCP forwarder in front of a replica that can
  black-hole its socket (accept, then forward nothing): connects succeed
  but every request hangs until the client's timeout — the failure mode
  health checks exist for, distinct from connection-refused.
- ``kill_replica`` / ``kill_worker`` — SIGKILL a replica/training-worker
  process: the real crash, no drain, no goodbye (the chaos soaks' mid-storm
  and mid-fit kills).
- ``WorkerChaos`` — in-worker chaos for elastic training, parsed from the
  ``DL4JTPU_WORKER_CHAOS`` env var the cluster manager plants: a per-step
  slowdown (straggler injection) and/or a scripted self-SIGKILL at a given
  step, so a subprocess worker can die mid-fit without the test needing to
  time an external kill against a race.

``SimulatedCrash`` subclasses BaseException on purpose: production code is
entitled to ``except Exception`` around batches, and a simulated kill must
not be swallowable by any of it — exactly like a real SIGKILL isn't.
"""

from __future__ import annotations

import os
import signal
import socket
import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.resilience.errors import InjectedFaultError

__all__ = ["SimulatedCrash", "CrashAfter", "FlakyIterator", "FlakyBroker",
           "FlakyEngine", "ServerFaultInjector", "BlackholeProxy",
           "kill_replica", "kill_worker", "WorkerChaos"]


class SimulatedCrash(BaseException):
    """An injected process death. BaseException so no ``except Exception``
    handler between the fit loop and the test can eat it."""


class CrashAfter:
    """IterationListener that crashes the fit once ``iteration >= at_iteration``.

    Order it BEFORE any CheckpointListener in the listeners list so the
    crash fires before the same iteration gets checkpointed — the resumed
    run then genuinely re-trains from an older step.
    """

    def __init__(self, at_iteration: int):
        self.at_iteration = at_iteration
        self.fired = False

    def iteration_done(self, model, iteration: int, epoch: int):
        if iteration >= self.at_iteration and not self.fired:
            self.fired = True
            raise SimulatedCrash(
                f"injected crash at iteration {iteration} (epoch {epoch})")

    def on_epoch_end(self, model):
        pass


class FlakyIterator:
    """Wrap a DataSetIterator; raise ``errors[n]`` on the n-th ``next()``
    call (0-based, counted across resets). Everything else delegates."""

    def __init__(self, base, errors: Optional[Dict[int, BaseException]] = None):
        self._base = base
        self._errors = dict(errors or {})
        self.calls = 0

    def __iter__(self):
        iter(self._base)
        return self

    def __next__(self):
        n = self.calls
        self.calls += 1
        exc = self._errors.pop(n, None)
        if exc is not None:
            raise exc
        return next(self._base)

    def reset(self):
        self._base.reset()

    def __getattr__(self, name):
        return getattr(self._base, name)


class FlakyBroker:
    """Wrap a kafka-like client: scripted failures on poll/send plus
    optional corrupt records injected into poll results.

    ``fail_polls`` / ``fail_sends``: {call_index: exception} (0-based).
    ``corrupt_at``: poll call indices whose records get their payloads
    replaced with garbage bytes (undecodable by ``decode_record``).
    """

    def __init__(self, base, fail_polls: Optional[Dict[int, BaseException]] = None,
                 fail_sends: Optional[Dict[int, BaseException]] = None,
                 corrupt_at: Optional[set] = None):
        self._base = base
        self._fail_polls = dict(fail_polls or {})
        self._fail_sends = dict(fail_sends or {})
        self._corrupt_at = set(corrupt_at or ())
        self.poll_calls = 0
        self.send_calls = 0

    def poll(self, *args, **kwargs):
        n = self.poll_calls
        self.poll_calls += 1
        exc = self._fail_polls.pop(n, None)
        if exc is not None:
            raise exc
        records = self._base.poll(*args, **kwargs)
        if n in self._corrupt_at and records:
            records = [type(r)(*[b"\x00garbage" if isinstance(v, bytes) else v
                                 for v in r]) if isinstance(r, tuple)
                       else b"\x00garbage" for r in records]
        return records

    def send(self, *args, **kwargs):
        n = self.send_calls
        self.send_calls += 1
        exc = self._fail_sends.pop(n, None)
        if exc is not None:
            raise exc
        return self._base.send(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._base, name)


class FlakyEngine:
    """Wrap an inference engine for serving storm tests.

    ``delay``: seconds to sleep inside every ``predict`` (makes the
    micro-batcher queue fill so 429/deadline paths are reachable).
    ``fail_calls``: {call_index: exception} raised instead of predicting.
    ``gate``: optional threading.Event — when set, predict blocks on it
    before running, letting a test hold the device "busy" deterministically.
    """

    def __init__(self, base, delay: float = 0.0,
                 fail_calls: Optional[Dict[int, BaseException]] = None,
                 gate: Optional[threading.Event] = None):
        self._base = base
        self.delay = delay
        self._fail_calls = dict(fail_calls or {})
        self.gate = gate
        self.calls = 0
        self.rows_seen = 0

    def _intercept(self, x):
        n = self.calls
        self.calls += 1
        if self.gate is not None:
            self.gate.wait()
        if self.delay > 0:
            time.sleep(self.delay)
        exc = self._fail_calls.pop(n, None)
        if exc is not None:
            raise exc
        try:
            self.rows_seen += int(x.shape[0])
        except Exception:
            pass

    def predict_host(self, x, *args, **kwargs):
        """The micro-batcher's entry point."""
        self._intercept(x)
        return self._base.predict_host(x, *args, **kwargs)

    def predict(self, x, *args, **kwargs):
        self._intercept(x)
        return self._base.predict(x, *args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._base, name)


class ServerFaultInjector:
    """Replica-server chaos: latency and 5xx injection on /predict and
    /generate, reconfigurable at runtime (the server exposes it at
    ``POST /chaos`` when constructed with one of these).

    ``latency_ms``: sleep inside every handled request (brownout).
    ``fail_next``: deterministically fail the next N requests.
    ``fail_rate``: additionally fail this fraction of requests, decided by
    a seeded counter (every ``round(1/rate)``-th request) so a chaos run is
    reproducible — no RNG, no flaky tests.
    ``error_code``: status for injected failures (500 by default; 503
    exercises the router's draining-vs-fault classification).
    """

    def __init__(self, latency_ms: float = 0.0, fail_next: int = 0,
                 fail_rate: float = 0.0, error_code: int = 500):
        self._lock = threading.Lock()
        self.configure(latency_ms=latency_ms, fail_next=fail_next,
                       fail_rate=fail_rate, error_code=error_code)
        self.injected_faults = 0
        self.requests_seen = 0

    def configure(self, latency_ms=None, fail_next=None, fail_rate=None,
                  error_code=None, **_ignored):
        with self._lock:
            if latency_ms is not None:
                self.latency_ms = float(latency_ms)
            if fail_next is not None:
                self.fail_next = int(fail_next)
            if fail_rate is not None:
                self.fail_rate = float(fail_rate)
            if error_code is not None:
                self.error_code = int(error_code)

    def describe(self) -> dict:
        return {"latency_ms": self.latency_ms, "fail_next": self.fail_next,
                "fail_rate": self.fail_rate, "error_code": self.error_code,
                "injected_faults": self.injected_faults,
                "requests_seen": self.requests_seen}

    def maybe_inject(self, path: str = "") -> None:
        with self._lock:
            self.requests_seen += 1
            n = self.requests_seen
            latency = self.latency_ms
            fail = False
            if self.fail_next > 0:
                self.fail_next -= 1
                fail = True
            elif self.fail_rate > 0:
                every = max(1, round(1.0 / self.fail_rate))
                fail = (n % every) == 0
            if fail:
                self.injected_faults += 1
                code = self.error_code
        if latency > 0:
            time.sleep(latency / 1000.0)
        if fail:
            raise InjectedFaultError(
                f"chaos-injected fault on {path or 'request'} #{n}",
                code=code)


class BlackholeProxy:
    """TCP proxy that can stop forwarding on command.

    Route a replica's traffic through ``proxy = BlackholeProxy(replica_port)
    .start()`` and point the router at ``proxy.port``. In ``blackhole``
    mode, new connections are ACCEPTED and then starved — no bytes flow
    either way — so the router sees hangs-until-timeout, the slow-failure
    mode that only deadline-aware health checking catches (a dead process
    at least refuses connections fast).
    """

    def __init__(self, target_port: int, target_host: str = "127.0.0.1",
                 port: int = 0):
        self.target = (target_host, int(target_port))
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._blackholed = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._open: list = []
        self._lock = threading.Lock()

    def start(self) -> "BlackholeProxy":
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def blackhole(self, on: bool = True) -> None:
        """Starve the socket: existing connections stall mid-stream, new
        ones accept and then hang. ``on=False`` restores forwarding for
        NEW connections (stalled ones stay stalled, like a real partition
        healing under old flows)."""
        if on:
            self._blackholed.set()
        else:
            self._blackholed.clear()

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._open = self._open, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._open.append(client)
            if self._blackholed.is_set():
                continue        # accepted, never serviced: the black hole
            try:
                upstream = socket.create_connection(self.target, timeout=10)
            except OSError:
                client.close()
                continue
            with self._lock:
                self._open.append(upstream)
            for a, b in ((client, upstream), (upstream, client)):
                threading.Thread(target=self._pump, args=(a, b),
                                 daemon=True).start()

    def _pump(self, src, dst):
        try:
            while not self._stopped.is_set():
                data = src.recv(65536)
                if not data or self._blackholed.is_set():
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass


def kill_replica(proc) -> None:
    """SIGKILL a replica process (a ``subprocess.Popen`` or anything with
    ``.pid``): no drain, no atexit, no flushed sockets — the genuine crash
    the failover path must absorb."""
    os.kill(proc.pid, signal.SIGKILL)


def kill_worker(proc) -> None:
    """SIGKILL a training worker (a ``subprocess.Popen``, a
    ``cluster.WorkerProcess``, or anything with ``.pid``). Same primitive as
    ``kill_replica``, named for the elastic-training soak: the coordinator
    must detect the silence via lease expiry — there is no exit hook."""
    os.kill(int(getattr(proc, "pid")), signal.SIGKILL)


class WorkerChaos:
    """Scripted in-worker chaos for elastic training.

    ``slow_ms``: sleep this long inside every training step (the straggler
    a lease-based detector must NOT evict while heartbeats keep flowing).
    ``die_at_step``: the worker SIGKILLs ITSELF when about to execute this
    step — deterministic mid-fit death with no cross-process kill race.

    Spec string (the ``DL4JTPU_WORKER_CHAOS`` env var the cluster manager
    plants per worker): comma-separated ``key=value``, e.g.
    ``"die_at_step=5"`` or ``"slow_ms=200,die_at_step=9"``.
    """

    def __init__(self, slow_ms: float = 0.0,
                 die_at_step: Optional[int] = None):
        self.slow_ms = float(slow_ms)
        self.die_at_step = None if die_at_step is None else int(die_at_step)

    @classmethod
    def parse(cls, spec: str) -> "WorkerChaos":
        kw = {}
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key not in ("slow_ms", "die_at_step"):
                raise ValueError(f"unknown worker-chaos key {key!r} in "
                                 f"{spec!r} (want slow_ms/die_at_step)")
            kw[key] = float(val) if key == "slow_ms" else int(val)
        return cls(**kw)

    @classmethod
    def from_env(cls, var: str = "DL4JTPU_WORKER_CHAOS") -> "WorkerChaos":
        return cls.parse(os.environ.get(var, ""))

    def on_step(self, step: int) -> None:
        """Call at the top of every training step. May never return."""
        if self.die_at_step is not None and step >= self.die_at_step:
            os.kill(os.getpid(), signal.SIGKILL)
        if self.slow_ms > 0:
            time.sleep(self.slow_ms / 1000.0)
