"""Fault tolerance: crash-safe checkpoints, shared retry/backoff, typed
errors, and a test-only fault-injection harness (docs/FAULT_TOLERANCE.md).

``faults`` is deliberately NOT imported here — it is test-only and stays
out of production import paths; ``from deeplearning4j_tpu.resilience import
faults`` explicitly when injecting failures.
"""

from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError,
    CorruptCheckpointError,
    DeadlineExceededError,
    FatalError,
    InjectedFaultError,
    RetriesExhaustedError,
    RetryBudgetExhaustedError,
    ServerOverloadedError,
    StreamStalledError,
    TransientError,
)
from deeplearning4j_tpu.resilience.retry import (
    DEFAULT_POLICY,
    RetryPolicy,
    default_classifier,
    retry_call,
    retryable,
)
from deeplearning4j_tpu.resilience.checkpoint import (
    Checkpoint,
    CheckpointListener,
    CheckpointManager,
    latest_checkpoint,
)

__all__ = [
    "BatcherStoppedError",
    "Checkpoint",
    "CheckpointListener",
    "CheckpointManager",
    "CorruptCheckpointError",
    "DEFAULT_POLICY",
    "DeadlineExceededError",
    "FatalError",
    "InjectedFaultError",
    "RetriesExhaustedError",
    "RetryBudgetExhaustedError",
    "RetryPolicy",
    "ServerOverloadedError",
    "StreamStalledError",
    "TransientError",
    "default_classifier",
    "latest_checkpoint",
    "retry_call",
    "retryable",
]
