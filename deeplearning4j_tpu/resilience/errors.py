"""Typed errors for the resilience layer.

The retry primitive (resilience/retry.py) classifies errors into RETRYABLE
(transient — worth another attempt after backoff) and FATAL (deterministic —
retrying cannot help). These classes are the explicit markers; anything else
is classified structurally (connection/timeout errors are transient, value
errors are fatal — see ``default_classifier``).

Serving raises the overload/deadline errors below so the HTTP layer can map
them to status codes (429 / 503 / 504) without string matching, and so
clients can classify them for their own retry loops.
"""

from __future__ import annotations


class TransientError(Exception):
    """Always retryable, whatever the classifier says (e.g. a broker poll
    that failed because a partition was mid-rebalance)."""


class FatalError(Exception):
    """Never retryable (e.g. an auth failure: every attempt will fail the
    same way, backing off just delays the report)."""


class RetriesExhaustedError(Exception):
    """retry_call gave up: attempts or deadline budget spent. ``__cause__``
    is the last underlying error; ``attempts`` says how many were made."""

    def __init__(self, message: str, attempts: int = 0,
                 elapsed: float = 0.0):
        super().__init__(message)
        self.attempts = attempts
        self.elapsed = elapsed


class DeadlineExceededError(TimeoutError):
    """A per-request deadline expired before the work ran. Deliberately NOT
    retryable: the caller's time budget is spent — retrying would only
    deliver a late answer nobody is waiting for. Maps to HTTP 504."""


class ServerOverloadedError(RuntimeError):
    """The serving queue is full — load was shed instead of queued. Maps to
    HTTP 429; RETRYABLE (with backoff) by the default classifier, because
    overload is transient by definition."""


class BatcherStoppedError(RuntimeError):
    """submit() after stop(): the batcher is draining or gone. Maps to HTTP
    503 with a ``draining`` health state; not retryable against the same
    instance."""


class RetryBudgetExhaustedError(RuntimeError):
    """The router's shared retry budget is spent: failover/hedging stops and
    the client gets a FAST 503 instead of queueing behind doomed attempts.
    The budget exists so retries cannot amplify a brownout into a retry
    storm — when every replica is failing, added attempts only add load."""


class InjectedFaultError(RuntimeError):
    """A chaos-injected server fault (resilience/faults.py
    ``ServerFaultInjector``). Carries the HTTP status the injection site
    should answer with, so the serving layer maps it without string
    matching. Test-only in practice, but defined here so production code
    never has to import the faults module to classify it."""

    def __init__(self, message: str, code: int = 500):
        super().__init__(message)
        self.code = int(code)


class CorruptCheckpointError(ValueError):
    """A checkpoint zip is truncated or damaged. Raised by
    util/model_serializer.py with the missing/unreadable member named, so a
    restore failure reads as one actionable message instead of a bare
    ``KeyError``/``BadZipFile`` from deep inside zipfile."""

    def __init__(self, path, member=None, detail=None):
        self.path = str(path)
        self.member = member
        where = f" (member {member!r})" if member else ""
        why = f": {detail}" if detail else ""
        super().__init__(
            f"corrupt or truncated checkpoint {self.path}{where}{why}")


class WeightSwapError(ValueError):
    """A hot-swap candidate pytree does not match the serving engine's live
    weights — missing/extra arrays, or a shape/dtype mismatch. Raised BEFORE
    any engine state is touched, so a rejected swap leaves serving exactly as
    it was; the admin endpoint maps it to HTTP 409. ``mismatches`` lists the
    offending array paths with expected-vs-got detail."""

    def __init__(self, message: str, mismatches=None):
        self.mismatches = list(mismatches or ())
        if self.mismatches:
            shown = "; ".join(self.mismatches[:3])
            more = len(self.mismatches) - 3
            if more > 0:
                shown += f"; … {more} more"
            message = f"{message}: {shown}"
        super().__init__(message)


class StreamStalledError(TimeoutError):
    """A streaming iterator saw no data for longer than ``stall_timeout``
    while the stream was still nominally open — the producer likely died
    without calling ``end()``."""
