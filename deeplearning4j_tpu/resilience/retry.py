"""Shared retry primitive: bounded exponential backoff + decorrelated jitter.

One retry loop for the whole framework — the kafka pump, the remote
fetchers, the UI reporter and the serving/KNN HTTP clients all call
``retry_call`` instead of hand-rolling ``for _ in range(n)`` loops, so
every remote interaction gets the same semantics:

- **bounded attempts** (``max_attempts``) and an optional overall
  **deadline** in seconds — the loop never sleeps past the point where the
  budget is already spent;
- **decorrelated jitter** (the AWS architecture-blog variant):
  ``delay = min(max_delay, uniform(base_delay, prev_delay * 3))`` — grows
  roughly exponentially but desynchronizes a thundering herd of clients
  retrying against the same recovering endpoint;
- **classification**: ``TransientError`` / connection / timeout errors are
  retried, ``FatalError`` / value-type errors are raised immediately
  (retrying a deterministic failure only delays the report);
- **metrics**: every attempt lands in
  ``dl4jtpu_retry_attempts_total{component, outcome}`` and every backoff
  sleep in ``dl4jtpu_retry_backoff_seconds`` — GET /metrics shows which
  dependency is flapping, fleet-wide.

The clock, sleeper and RNG are injectable so tests drive the policy with a
fake clock (tests/test_resilience.py) — no real sleeping in tier-1.
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass
from typing import Callable, Optional

from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.resilience.errors import (
    DeadlineExceededError, FatalError, RetriesExhaustedError,
    ServerOverloadedError, TransientError)

__all__ = ["RetryPolicy", "retry_call", "retryable", "default_classifier"]


def default_classifier(exc: BaseException) -> bool:
    """True = transient (retry), False = fatal (raise now).

    Explicit markers win; otherwise network-shaped errors (connection
    resets, timeouts, DNS/socket failures) are transient and everything
    else — type errors, value errors, missing files — is fatal."""
    if isinstance(exc, (TransientError, ServerOverloadedError)):
        return True
    if isinstance(exc, (FatalError, DeadlineExceededError)):
        return False
    # late import keeps urllib out of the hot path for non-HTTP users
    import urllib.error
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in (429, 502, 503, 504)
    if isinstance(exc, (ConnectionError, TimeoutError, socket.timeout,
                        urllib.error.URLError, BrokenPipeError)):
        return True
    return False


@dataclass(frozen=True)
class RetryPolicy:
    """Knobs for one call-site's retry behavior (docs/FAULT_TOLERANCE.md).

    ``max_attempts``: total tries including the first (``None`` = unbounded,
    pair it with ``deadline`` or a ``give_up`` callback).
    ``base_delay``/``max_delay``: backoff bounds in seconds.
    ``deadline``: overall wall budget across attempts, in seconds.
    ``classify``: error → retryable? (default ``default_classifier``)."""

    max_attempts: Optional[int] = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    deadline: Optional[float] = None
    classify: Callable[[BaseException], bool] = default_classifier


DEFAULT_POLICY = RetryPolicy()

# backoff sleep buckets: 10ms jitter floor through the 30s circuit-breaker
# scale (coarser than request latency — backoff is seconds, not micros)
_BACKOFF_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                    10.0, 30.0)


def _metrics():
    reg = get_registry()
    return (reg.counter(
                "dl4jtpu_retry_attempts_total",
                "Attempts made by the shared retry primitive. outcome: "
                "success | error (will retry) | exhausted (gave up) | "
                "fatal (not retryable).",
                ("component", "outcome")),
            reg.histogram(
                "dl4jtpu_retry_backoff_seconds",
                "Backoff sleeps taken between retry attempts.",
                ("component",), buckets=_BACKOFF_BUCKETS))


def retry_call(fn, *args, policy: RetryPolicy = DEFAULT_POLICY,
               component: str = "default",
               give_up: Optional[Callable[[], bool]] = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               rng: Optional[random.Random] = None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying transient failures under
    ``policy``. ``give_up()`` is polled before every attempt and before
    every sleep — a shutdown flag aborts the loop promptly (raising
    ``RetriesExhaustedError``). Raises the original error for fatal
    failures, ``RetriesExhaustedError`` (with ``__cause__``) otherwise."""
    attempts_total, backoff_hist = _metrics()
    rng = rng if rng is not None else random
    start = clock()
    prev_delay = policy.base_delay
    attempt = 0
    last_exc: Optional[BaseException] = None
    while True:
        if give_up is not None and give_up():
            raise RetriesExhaustedError(
                f"{component}: aborted by give_up() after {attempt} "
                f"attempt(s)", attempts=attempt,
                elapsed=clock() - start) from last_exc
        attempt += 1
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — classified right below
            last_exc = e
            if not policy.classify(e):
                attempts_total.labels(component=component,
                                      outcome="fatal").inc()
                raise
            elapsed = clock() - start
            out_of_attempts = (policy.max_attempts is not None
                               and attempt >= policy.max_attempts)
            out_of_time = (policy.deadline is not None
                           and elapsed >= policy.deadline)
            if out_of_attempts or out_of_time:
                attempts_total.labels(component=component,
                                      outcome="exhausted").inc()
                why = ("deadline" if out_of_time else "attempts")
                raise RetriesExhaustedError(
                    f"{component}: {why} budget spent after {attempt} "
                    f"attempt(s) in {elapsed:.3f}s: "
                    f"{type(e).__name__}: {e}",
                    attempts=attempt, elapsed=elapsed) from e
            attempts_total.labels(component=component,
                                  outcome="error").inc()
            delay = min(policy.max_delay,
                        rng.uniform(policy.base_delay, prev_delay * 3.0))
            prev_delay = delay
            if policy.deadline is not None:
                remaining = policy.deadline - (clock() - start)
                if remaining <= 0 or delay >= remaining:
                    # sleeping would only carry us past the budget — one
                    # last immediate attempt is still within it, so take
                    # the largest sleep that is not
                    delay = max(0.0, remaining - 1e-3)
            if give_up is not None and give_up():
                continue        # top-of-loop raises with the abort message
            if delay > 0:
                backoff_hist.labels(component=component).observe(delay)
                sleep(delay)
        else:
            attempts_total.labels(component=component,
                                  outcome="success").inc()
            return result


def retryable(policy: RetryPolicy = DEFAULT_POLICY,
              component: str = "default"):
    """Decorator form: ``@retryable(policy, component="fetcher")``."""
    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return retry_call(fn, *args, policy=policy,
                              component=component, **kwargs)
        return inner
    return wrap
