"""KNN REST server + client.

Parity: reference deeplearning4j-nearestneighbor-server/
NearestNeighborsServer.java (Play REST service over a VPTree index),
-client/NearestNeighborsClient.java (JSON + Base64 NDArray transport),
-model (request/response DTOs).

Design: stdlib ThreadingHTTPServer; the index is the device-side brute-force
``NearestNeighbors`` (one XLA distance matmul per batch — the TPU-idiomatic
choice; the reference needed a VPTree because JVM-side distance loops were
slow) with a VPTree fallback for hosts without an accelerator. Array
transport is Base64 of raw little-endian f32 plus a shape header — same
role as the reference's Base64 NDArray codec."""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse
import urllib.request

import numpy as np


def ndarray_to_b64(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a, dtype=np.float32)
    return {"shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode()}


def ndarray_from_b64(obj: dict) -> np.ndarray:
    raw = base64.b64decode(obj["data"])
    return np.frombuffer(raw, dtype=np.float32).reshape(obj["shape"]).copy()


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _json(self, obj, code=200):
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        srv = self.server.knn
        path = urlparse(self.path).path
        n = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(n).decode())
        except Exception as e:
            self._json({"error": f"bad json: {e}"}, 400)
            return
        try:
            if path == "/knn":            # by index into the corpus
                idx = int(payload["index"])
                k = int(payload.get("k", 1))
                if not 0 <= idx < len(srv.points):
                    self._json({"error": f"index {idx} out of range "
                                         f"[0, {len(srv.points)})"}, 400)
                    return
                q = srv.points[idx:idx + 1]
                ids, dists = srv.query(q, k + 1)
                # drop the query point itself (reference does the same)
                results = [{"index": int(i), "distance": float(d)}
                           for i, d in zip(ids[0], dists[0])
                           if int(i) != idx][:k]
                self._json({"results": results})
            elif path == "/knnnew":       # by raw vector
                k = int(payload.get("k", 1))
                q = ndarray_from_b64(payload["ndarray"])
                if q.ndim == 1:
                    q = q[None, :]
                ids, dists = srv.query(q, k)
                self._json({"results": [
                    [{"index": int(i), "distance": float(d)}
                     for i, d in zip(row_i, row_d)]
                    for row_i, row_d in zip(ids, dists)]})
            else:
                self._json({"error": "not found"}, 404)
        except Exception as e:  # noqa: BLE001 — service must answer
            self._json({"error": str(e)}, 500)


class NearestNeighborsServer:
    """Serve KNN over a fixed corpus (parity: NearestNeighborsServer.java).

        srv = NearestNeighborsServer(points, port=0).start()
        ... NearestNeighborsClient(f"http://localhost:{srv.port}")
    """

    def __init__(self, points: np.ndarray, port: int = 9200,
                 use_device: bool = True, host: str = "127.0.0.1"):
        self.points = np.asarray(points, dtype=np.float32)
        self._port_req = port
        self._host = host
        self.use_device = use_device
        self._index = None
        self._httpd = None
        self.port: Optional[int] = None

    def _build_index(self):
        if self.use_device:
            try:
                from deeplearning4j_tpu.clustering.knn import NearestNeighbors
                self._index = NearestNeighbors(self.points)
                return
            except Exception:
                pass
        from deeplearning4j_tpu.clustering.trees import VPTree
        self._index = VPTree(self.points)

    def query(self, q: np.ndarray, k: int):
        k = min(k, len(self.points))
        if hasattr(self._index, "knn") and self._index.__class__.__name__ \
                == "NearestNeighbors":
            ids, dists = self._index.knn(q, k)
            return np.asarray(ids), np.asarray(dists)
        ids, dists = [], []
        for row in q:
            i, d = self._index.knn(row, k)
            ids.append(i)
            dists.append(d)
        return np.asarray(ids), np.asarray(dists)

    def start(self):
        self._build_index()
        self._httpd = ThreadingHTTPServer((self._host, self._port_req),
                                          _Handler)
        self._httpd.knn = self
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


class NearestNeighborsClient:
    """Parity: NearestNeighborsClient.java. Connection failures and 5xx
    responses retry with backoff through the shared primitive
    (resilience/retry.py, component="knn_client")."""

    def __init__(self, url: str, timeout: float = 10.0, retries: int = 3):
        from deeplearning4j_tpu.resilience.retry import RetryPolicy
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.retry_policy = RetryPolicy(max_attempts=max(1, retries),
                                        base_delay=0.05, max_delay=1.0)

    def _post_once(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                out = json.loads(e.read().decode())
            except Exception:
                raise RuntimeError(f"HTTP {e.code}") from e
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def _post(self, path, payload):
        from deeplearning4j_tpu.resilience.retry import retry_call
        return retry_call(self._post_once, path, payload,
                          policy=self.retry_policy, component="knn_client")

    def knn(self, index: int, k: int):
        return self._post("/knn", {"index": index, "k": k})["results"]

    def knn_new(self, vector: np.ndarray, k: int):
        return self._post("/knnnew", {
            "k": k, "ndarray": ndarray_to_b64(np.asarray(vector))})["results"]
