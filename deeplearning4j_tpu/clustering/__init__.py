from deeplearning4j_tpu.clustering.trees import VPTree, KDTree, QuadTree, SpTree
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.knn import NearestNeighbors

__all__ = ["VPTree", "KDTree", "QuadTree", "SpTree", "KMeansClustering",
           "NearestNeighbors"]
