"""Spatial trees: VPTree, KDTree, QuadTree, SpTree (Barnes-Hut).

Parity surface: reference nearestneighbor-core — clustering/vptree/
VPTree.java (608 LoC), kdtree/KDTree.java, quadtree/QuadTree.java,
sptree/SpTree.java.

Design note: on TPU the fastest exact-KNN for the dataset sizes these trees
serve is usually a single batched distance GEMM (see knn.py) — the trees are
kept for API parity and for host-side algorithms that need them (Barnes-Hut
t-SNE uses SpTree).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class VPTree:
    """Vantage-point tree over an (N, D) matrix (parity: VPTree.java).
    Metrics: 'euclidean' | 'cosine' (cosine converted to distance)."""

    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 leaf_size: int = 16, seed: int = 123):
        self.points = np.asarray(points, np.float64)
        self.distance = distance
        self.leaf_size = leaf_size
        self._rng = np.random.RandomState(seed)
        if distance == "cosine":
            norms = np.maximum(np.linalg.norm(self.points, axis=1,
                                              keepdims=True), 1e-12)
            self._normed = self.points / norms
        self.root = self._build(np.arange(len(self.points)))

    def _dist(self, idx_a: int, idx_many: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            return 1.0 - self._normed[idx_many] @ self._normed[idx_a]
        diff = self.points[idx_many] - self.points[idx_a]
        return np.sqrt((diff ** 2).sum(-1))

    def _build(self, idx: np.ndarray):
        if len(idx) == 0:
            return None
        if len(idx) <= self.leaf_size:
            return {"leaf": idx}
        vp_pos = self._rng.randint(len(idx))
        vp = idx[vp_pos]
        rest = np.delete(idx, vp_pos)
        d = self._dist(vp, rest)
        median = np.median(d)
        inner = rest[d <= median]
        outer = rest[d > median]
        return {"vp": vp, "mu": median,
                "inner": self._build(inner), "outer": self._build(outer)}

    def _query_dist(self, q: np.ndarray, idx_many: np.ndarray) -> np.ndarray:
        if self.distance == "cosine":
            qn = q / max(np.linalg.norm(q), 1e-12)
            return 1.0 - self._normed[idx_many] @ qn
        diff = self.points[idx_many] - q
        return np.sqrt((diff ** 2).sum(-1))

    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        import heapq

        def consider(indices):
            for i, d in zip(indices, self._query_dist(q, np.asarray(indices))):
                if len(heap) < k:
                    heapq.heappush(heap, (-d, int(i)))
                elif -heap[0][0] > d:
                    heapq.heapreplace(heap, (-d, int(i)))

        def search(node):
            if node is None:
                return
            if "leaf" in node:
                if len(node["leaf"]):
                    consider(node["leaf"])
                return
            vp = node["vp"]
            consider([vp])
            d_vp = self._query_dist(q, np.asarray([vp]))[0]
            tau = -heap[0][0] if len(heap) == k else np.inf
            if d_vp <= node["mu"]:
                search(node["inner"])
                tau = -heap[0][0] if len(heap) == k else np.inf
                if d_vp + tau > node["mu"]:
                    search(node["outer"])
            else:
                search(node["outer"])
                tau = -heap[0][0] if len(heap) == k else np.inf
                if d_vp - tau <= node["mu"]:
                    search(node["inner"])

        search(self.root)
        pairs = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in pairs], [d for d, _ in pairs]


class KDTree:
    """Axis-split k-d tree (parity: kdtree/KDTree.java)."""

    def __init__(self, points: np.ndarray, leaf_size: int = 16):
        self.points = np.asarray(points, np.float64)
        self.root = self._build(np.arange(len(self.points)), 0)
        self.leaf_size = leaf_size

    def _build(self, idx, depth):
        if len(idx) == 0:
            return None
        if len(idx) <= 16:
            return {"leaf": idx}
        axis = depth % self.points.shape[1]
        vals = self.points[idx, axis]
        order = np.argsort(vals)
        mid = len(idx) // 2
        return {"axis": axis, "split": vals[order[mid]],
                "point": idx[order[mid]],
                "left": self._build(idx[order[:mid]], depth + 1),
                "right": self._build(idx[order[mid + 1:]], depth + 1)}

    def knn(self, query, k):
        q = np.asarray(query, np.float64)
        import heapq
        heap = []

        def consider(indices):
            d = np.sqrt(((self.points[np.asarray(indices)] - q) ** 2).sum(-1))
            for i, dd in zip(indices, d):
                if len(heap) < k:
                    heapq.heappush(heap, (-dd, int(i)))
                elif -heap[0][0] > dd:
                    heapq.heapreplace(heap, (-dd, int(i)))

        def search(node):
            if node is None:
                return
            if "leaf" in node:
                if len(node["leaf"]):
                    consider(node["leaf"])
                return
            consider([node["point"]])
            axis, split = node["axis"], node["split"]
            near, far = ((node["left"], node["right"]) if q[axis] <= split
                         else (node["right"], node["left"]))
            search(near)
            tau = -heap[0][0] if len(heap) == k else np.inf
            if abs(q[axis] - split) < tau:
                search(far)

        search(self.root)
        pairs = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in pairs], [d for d, _ in pairs]


class QuadTree:
    """2D quadtree (parity: quadtree/QuadTree.java) — used by 2D Barnes-Hut."""

    MAX_POINTS = 1

    def __init__(self, points: np.ndarray):
        pts = np.asarray(points, np.float64)
        assert pts.shape[1] == 2
        lo = pts.min(0)
        hi = pts.max(0)
        center = (lo + hi) / 2
        half = max((hi - lo).max() / 2, 1e-9)
        self.root = _QTNode(center, half)
        for i, p in enumerate(pts):
            self.root.insert(i, p)

    def depth(self):
        return self.root.depth()


class _QTNode:
    def __init__(self, center, half):
        self.center = np.asarray(center, np.float64)
        self.half = half
        self.idx = None
        self.point = None
        self.children = None
        self.count = 0
        self.mass_center = np.zeros(2)

    def insert(self, i, p):
        self.count += 1
        self.mass_center += (p - self.mass_center) / self.count
        if self.children is None and self.idx is None:
            self.idx, self.point = i, p
            return
        if self.children is None:
            self._split()
        self._child_for(p).insert(i, p)

    def _split(self):
        h = self.half / 2
        c = self.center
        self.children = [
            _QTNode(c + np.array([dx, dy]) * h, h)
            for dx in (-1, 1) for dy in (-1, 1)]
        if self.idx is not None:
            i, p = self.idx, self.point
            self.idx = self.point = None
            self._child_for(p).insert(i, p)

    def _child_for(self, p):
        ix = 0 if p[0] <= self.center[0] else 2
        iy = 0 if p[1] <= self.center[1] else 1
        return self.children[ix + iy]

    def depth(self):
        if self.children is None:
            return 1
        return 1 + max(c.depth() for c in self.children if c.count > 0)


class SpTree:
    """N-d Barnes-Hut tree with center-of-mass aggregation
    (parity: sptree/SpTree.java). Provides the non-edge-force estimation used
    by Barnes-Hut t-SNE."""

    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        n, d = self.points.shape
        lo = self.points.min(0)
        hi = self.points.max(0)
        center = (lo + hi) / 2
        half = max((hi - lo).max() / 2, 1e-9)
        self.d = d
        self.root = _SpNode(center, half, d)
        for i, p in enumerate(self.points):
            self.root.insert(i, p)

    def compute_non_edge_forces(self, query: np.ndarray, theta: float = 0.5):
        """Returns (neg_force (d,), sum_q) for one embedded point — the
        Barnes-Hut approximation of the t-SNE repulsive term."""
        neg = np.zeros(self.d)
        sum_q = 0.0
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node.count == 0:
                continue
            diff = query - node.mass_center
            dist2 = (diff ** 2).sum()
            if node.children is None or \
                    (node.half * 2) / max(np.sqrt(dist2), 1e-12) < theta:
                if node.is_self_leaf(query):
                    continue
                q = 1.0 / (1.0 + dist2)
                mult = node.count * q
                sum_q += mult
                neg += mult * q * diff
            else:
                stack.extend(c for c in node.children if c.count > 0)
        return neg, sum_q


class _SpNode:
    __slots__ = ("center", "half", "d", "idx", "point", "children", "count",
                 "mass_center")

    def __init__(self, center, half, d):
        self.center = np.asarray(center, np.float64)
        self.half = half
        self.d = d
        self.idx = None
        self.point = None
        self.children = None
        self.count = 0
        self.mass_center = np.zeros(d)

    def insert(self, i, p, depth=0):
        self.count += 1
        self.mass_center += (p - self.mass_center) / self.count
        if self.children is None and self.idx is None:
            self.idx, self.point = i, p
            return
        if self.children is None:
            if depth > 64 or np.allclose(self.point, p):
                return  # duplicate points: aggregate only
            self._split()
        self._child_for(p).insert(i, p, depth + 1)

    def _split(self):
        h = self.half / 2
        self.children = []
        for code in range(2 ** self.d):
            offset = np.array([1 if (code >> b) & 1 else -1
                               for b in range(self.d)]) * h
            self.children.append(_SpNode(self.center + offset, h, self.d))
        if self.idx is not None:
            i, p = self.idx, self.point
            self.idx = self.point = None
            self._child_for(p).insert(i, p)

    def _child_for(self, p):
        code = 0
        for b in range(self.d):
            if p[b] > self.center[b]:
                code |= (1 << b)
        return self.children[code]

    def is_self_leaf(self, q):
        return self.children is None and self.point is not None and \
            np.allclose(self.point, q)
