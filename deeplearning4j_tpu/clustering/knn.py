"""Brute-force exact KNN on device.

The TPU-native fast path for nearest neighbors: one (Q, N) distance matrix
via a single GEMM (‖a-b‖² = ‖a‖² + ‖b‖² - 2a·b) + top-k — this is what the
reference's VPTree serves, but batched on the MXU it is faster for any
corpus that fits HBM. Used by the KNN server and t-SNE input stage.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("k", "metric"))
def _knn_kernel(corpus, queries, k, metric):
    if metric == "cosine":
        c = corpus / jnp.maximum(
            jnp.linalg.norm(corpus, axis=1, keepdims=True), 1e-12)
        q = queries / jnp.maximum(
            jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        d = 1.0 - q @ c.T
    else:
        cn = (corpus ** 2).sum(1)
        qn = (queries ** 2).sum(1)
        d = qn[:, None] + cn[None, :] - 2.0 * (queries @ corpus.T)
        d = jnp.maximum(d, 0.0)
    neg_d, idx = jax.lax.top_k(-d, k)
    return idx, -neg_d


class NearestNeighbors:
    def __init__(self, corpus, metric: str = "euclidean"):
        self.corpus = jnp.asarray(np.asarray(corpus, np.float32))
        self.metric = metric

    def knn(self, queries, k: int):
        """queries: (Q, D) or (D,). Returns (indices (Q,k), distances (Q,k))
        — euclidean distances are true (sqrt'd) distances."""
        q = np.asarray(queries, np.float32)
        single = q.ndim == 1
        if single:
            q = q[None]
        idx, d = _knn_kernel(self.corpus, jnp.asarray(q), k, self.metric)
        idx, d = np.asarray(idx), np.asarray(d)
        if self.metric != "cosine":
            d = np.sqrt(d)
        return (idx[0], d[0]) if single else (idx, d)
