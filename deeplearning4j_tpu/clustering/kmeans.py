"""KMeans clustering.

Parity surface: reference clustering/kmeans/KMeansClustering.java + the
cluster/ framework (Point, Cluster, ClusterSet).

TPU design: Lloyd iterations as jit'd batched ops — assignment is one
distance GEMM + argmin, centroid update is a segment mean — instead of the
reference's per-point Java loops.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(points, centroids, k):
    d = ((points[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
    assign = d.argmin(1)                              # (N,)
    onehot = jax.nn.one_hot(assign, k, dtype=points.dtype)  # (N, k)
    counts = onehot.sum(0)                            # (k,)
    sums = onehot.T @ points                          # (k, D)
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts[:, None], 1.0),
                              centroids)
    cost = (d.min(1)).sum()
    return new_centroids, assign, cost


class KMeansClustering:
    """k-means with k-means++ init (parity: KMeansClustering.setup)."""

    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 seed: int = 123):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids: Optional[np.ndarray] = None
        self.assignments: Optional[np.ndarray] = None
        self.cost = float("inf")

    def _init_pp(self, pts, rng):
        n = len(pts)
        centroids = [pts[rng.randint(n)]]
        for _ in range(1, self.k):
            d2 = np.min(((pts[:, None, :] - np.asarray(centroids)[None]) ** 2)
                        .sum(-1), axis=1)
            probs = d2 / max(d2.sum(), 1e-12)
            centroids.append(pts[rng.choice(n, p=probs)])
        return np.asarray(centroids, np.float32)

    def apply_to(self, points):
        pts = np.asarray(points, np.float32)
        rng = np.random.RandomState(self.seed)
        centroids = jnp.asarray(self._init_pp(pts, rng))
        pts_j = jnp.asarray(pts)
        prev_cost = np.inf
        for it in range(self.max_iterations):
            centroids, assign, cost = _lloyd_step(pts_j, centroids, self.k)
            cost = float(cost)
            if abs(prev_cost - cost) < self.tol * max(abs(prev_cost), 1.0):
                break
            prev_cost = cost
        self.centroids = np.asarray(centroids)
        self.assignments = np.asarray(assign)
        self.cost = cost
        return self

    def predict(self, points):
        pts = np.asarray(points, np.float32)
        d = ((pts[:, None, :] - self.centroids[None]) ** 2).sum(-1)
        return d.argmin(1)
