from deeplearning4j_tpu.transferlearning.transfer import (
    TransferLearning, FineTuneConfiguration, TransferLearningHelper,
)

__all__ = ["TransferLearning", "FineTuneConfiguration", "TransferLearningHelper"]
