"""Transfer learning.

Parity surface: reference nn/transferlearning/ — TransferLearning.Builder
(TransferLearning.java:34: setFeatureExtractor freeze point, nOutReplace,
removeOutputLayer, addLayer), FineTuneConfiguration (global hyperparameter
overrides), TransferLearningHelper (featurize: run the frozen front once and
train only the tail).

TPU design: freezing = wrapping layers in FrozenLayer (stop_gradient + zero
updater) — parameters are copied by reference (immutable arrays, no clone
cost).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Optional, List

import jax

from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.special import FrozenLayer
from deeplearning4j_tpu.nn.updaters import Updater
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.data.dataset import DataSet


@dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to every retained layer
    (parity: FineTuneConfiguration.java)."""
    updater: Optional[Updater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    activation: Optional[str] = None
    seed: Optional[int] = None

    def apply(self, conf: MultiLayerConfiguration):
        g = conf.global_conf
        if self.updater is not None:
            g.updater = self.updater
        if self.l1 is not None:
            g.l1 = self.l1
        if self.l2 is not None:
            g.l2 = self.l2
        if self.seed is not None:
            g.seed = self.seed
        for l in conf.layers:
            if self.updater is not None and l.updater is not None:
                l.updater = self.updater
            if self.l1 is not None:
                l.l1 = self.l1
            if self.l2 is not None:
                l.l2 = self.l2
            if self.dropout is not None and l.dropout is not None:
                l.dropout = self.dropout


class TransferLearning:
    """Namespace matching the reference API: TransferLearning.Builder(net)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._conf = MultiLayerConfiguration.from_json(net.conf.to_json())
            self._params = [p for p in net.params]
            self._state = [s for s in net.state]
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._removed_from_end = 0
            self._added: List = []
            self._nout_replaced = {}

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0, layer_index] (parity: setFeatureExtractor)."""
            self._freeze_until = layer_index
            return self

        def remove_output_layer(self):
            self._removed_from_end += 1
            return self

        def remove_layers_from_output(self, n: int):
            self._removed_from_end += n
            return self

        def add_layer(self, layer):
            self._added.append(layer)
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: str = "xavier"):
            """Re-initialize layer at index with a new n_out (parity:
            nOutReplace — also fixes the following layer's n_in)."""
            self._nout_replaced[layer_index] = (n_out, weight_init)
            return self

        def build(self) -> MultiLayerNetwork:
            conf = self._conf
            layers = conf.layers
            params = list(self._params)
            state = list(self._state)

            # remove tail layers
            for _ in range(self._removed_from_end):
                layers.pop()
                params.pop()
                state.pop()

            # replace n_out (and downstream n_in)
            reinit = set()
            for idx, (n_out, winit) in self._nout_replaced.items():
                layers[idx].n_out = n_out
                layers[idx].weight_init = winit
                reinit.add(idx)
                if idx + 1 < len(layers) and hasattr(layers[idx + 1], "n_in"):
                    layers[idx + 1].n_in = n_out
                    reinit.add(idx + 1)

            # append new layers (shape-infer their n_in from predecessor)
            it = None
            if conf.input_type is not None:
                it = conf.input_type
                for l in layers:
                    it = l.output_type(it)
            for l in self._added:
                l.apply_defaults(conf.global_conf.defaults_dict())
                if it is not None:
                    l.set_n_in(it)
                    it = l.output_type(it)
                layers.append(l)
                params.append(None)  # init below
                state.append(l.init_state())

            # freeze front
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(layers))):
                    if not isinstance(layers[i], FrozenLayer):
                        layers[i] = FrozenLayer(inner=layers[i])

            if self._fine_tune is not None:
                self._fine_tune.apply(conf)

            conf._finalized = True
            net = MultiLayerNetwork(conf)
            rng = jax.random.PRNGKey(conf.global_conf.seed)
            keys = jax.random.split(rng, max(len(layers), 1))
            new_params = []
            for i, l in enumerate(layers):
                if i < len(params) and params[i] is not None and i not in reinit:
                    new_params.append(params[i])
                else:
                    new_params.append(l.init(keys[i]))
            net.params = new_params
            net.state = state
            net._build_optimizer()
            return net


class TransferLearningHelper:
    """Featurization helper (parity: TransferLearningHelper.java): run the
    frozen front once per dataset, train only the unfrozen tail on the cached
    features."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: Optional[int] = None):
        if frozen_until is None:
            # infer: leading FrozenLayer prefix
            frozen_until = -1
            for i, l in enumerate(net.layers):
                if isinstance(l, FrozenLayer):
                    frozen_until = i
                else:
                    break
        self.frozen_until = frozen_until
        self.full_net = net
        # tail network over the unfrozen suffix
        conf = MultiLayerConfiguration.from_json(net.conf.to_json())
        tail_layers = conf.layers[frozen_until + 1:]
        tail_conf = MultiLayerConfiguration(
            global_conf=conf.global_conf, layers=tail_layers,
            input_type=None, backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        tail_conf._finalized = True
        self.unfrozen = MultiLayerNetwork(tail_conf)
        self.unfrozen.params = list(net.params[frozen_until + 1:])
        self.unfrozen.state = list(net.state[frozen_until + 1:])
        self.unfrozen._build_optimizer()

    def featurize(self, ds: DataSet) -> DataSet:
        import jax.numpy as jnp
        x = jnp.asarray(ds.features)
        act, _, _ = self.full_net._forward(
            self.full_net.params, self.full_net.state, x, train=False,
            rng=None, upto=self.frozen_until + 1)
        import numpy as np
        return DataSet(np.asarray(act), ds.labels, ds.features_mask,
                       ds.labels_mask)

    def fit_featurized(self, ds: DataSet):
        self.unfrozen.fit(ds)
        # write trained tail params back into the full net
        for i, p in enumerate(self.unfrozen.params):
            self.full_net.params[self.frozen_until + 1 + i] = p
        return self

    def unfrozen_mln(self) -> MultiLayerNetwork:
        return self.unfrozen
