"""ComputationGraph — the DAG network container.

Parity surface: reference nn/graph/ComputationGraph.java (3,363 LoC):
``init`` + topo sort (:370/:394), ``fit`` (:863/:988), forward over
topologicalOrder, ``calcBackpropGradients`` (:1629 — here jax.grad),
multi-input/multi-output ``output`` (:1532), ``rnnTimeStep`` (:2362).

TPU design mirrors MultiLayerNetwork: one jit'd pure train step; the DAG is
unrolled along the precomputed topological order at trace time so XLA fuses
the whole graph.
"""

from __future__ import annotations

import itertools
import time
from typing import Optional, Dict, Any, List

import numpy as np
import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.monitor.tracing import trace
from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.updaters import make_gradient_transform
from deeplearning4j_tpu.nn.layers.special import FrozenLayer


def _dtype_of(name):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


from deeplearning4j_tpu.util.dtypes import (cast_floats as _cast_floats,
                                             restore_dtypes as _restore_dtypes)


class ComputationGraph:
    _prog_ids = itertools.count()

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Optional[Dict[str, Dict]] = None
        self.state: Optional[Dict[str, Dict]] = None
        self.opt_state: Optional[Dict[str, Any]] = None
        self.listeners: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self._epoch_batch = 0         # batches consumed in the current epoch
                                      # (persisted in checkpoints → resume
                                      # restarts mid-epoch at the right batch)
        self._score = float("nan")
        self._last_input = None       # last fit batch (activation capture)
        self._rnn_carries = None      # rnnTimeStep stateMap
        self._train_step_cache = {}
        self._scan_fit = None
        self._output_fn = None
        self._serving = None          # bucketed inference engine (lazy)
        self._transforms = None
        self._fused = None            # fused update plan (nn/fused_update.py)
        self._update_step = None      # standalone donated update program
        self._compile_count = 0       # train programs traced (see _note_compile)
        self._flight = None           # FlightRecorder (monitor/flight.py)
        self._train_mon = None        # lazy TrainMonitor (metric children)
        self._exec = None             # execution core (lazy; exec/executor.py)
        # per-instance caller id for the XLA program registry (/programs):
        # a rebuilt graph gets fresh registry rows, never a stale hit
        self._prog_caller = f"cg{next(ComputationGraph._prog_ids)}"

    @property
    def _executor(self):
        """The execution core all compile sites build programs through
        (mesh placement, in/out shardings, donation — docs/SHARDING.md)."""
        if self._exec is None:
            from deeplearning4j_tpu.exec import get_executor
            self._exec = get_executor()
        return self._exec

    # ------------------------------------------------------------------ init
    def init(self, rng=None):
        gc = self.conf.global_conf
        dtype = _dtype_of(gc.dtype)
        if rng is None:
            rng = jax.random.PRNGKey(gc.seed)
        self.params, self.state = {}, {}
        layer_nodes = [n for n in self.conf.topological_order
                       if self.conf.nodes[n].kind == "layer"]
        keys = jax.random.split(rng, max(len(layer_nodes), 1))
        for name, k in zip(layer_nodes, keys):
            l = self.conf.nodes[name].layer
            self.params[name] = l.init(k, dtype)
            self.state[name] = l.init_state(dtype)
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        import json
        from deeplearning4j_tpu.nn.fused_update import (build_fused_update,
                                                        fused_update_enabled)
        gc = self.conf.global_conf
        self._transforms = {}
        group_keys = {}
        for name, p in self.params.items():
            l = self.conf.nodes[name].layer
            if isinstance(l, FrozenLayer) or not p:
                self._transforms[name] = optax.set_to_zero()
                group_keys[name] = None
            else:
                upd = l.updater or gc.updater
                self._transforms[name] = make_gradient_transform(upd)
                group_keys[name] = json.dumps(upd.to_dict(), sort_keys=True)
        self.opt_state = {n: t.init(self.params[n])
                          for n, t in self._transforms.items()}
        self._fused = None
        if fused_update_enabled():
            self._fused = build_fused_update(
                self.params, self._transforms, group_keys,
                {n: self.conf.nodes[n].layer.apply_constraints
                 for n in self.params})
        self._train_step_cache = {}
        self._scan_fit = None
        self._output_fn = None
        self._serving = None
        self._update_step = None

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def attach_flight_recorder(self, recorder):
        """Attach (or detach, with None) a ``monitor.flight.FlightRecorder``.
        The train-step/fit_scan programs re-trace ONCE with the fused
        ``(L, 5)`` telemetry side-output (see monitor/flight.py); detached
        training stays byte-identical to today's path."""
        self._flight = recorder
        if recorder is not None:
            recorder.bind(self)
        self._train_step_cache = {}   # force re-trace with/without the
        self._scan_fit = None         # side-output
        return self

    # ----------------------------------------------------------- forward core
    def _compute_dtype(self, train):
        """The forward's compute dtype: the model's own ``compute_dtype``
        when configured, else the executor's train-precision policy (bf16
        compute, f32 accumulation — docs/TRAINING_PERF.md) on the fit path
        of f32 models. None means no cast. Read at trace time."""
        gc = self.conf.global_conf
        if gc.compute_dtype:
            return _dtype_of(gc.compute_dtype)
        if train:
            dt = self._executor.train_dtype
            if dt is not None and _dtype_of(gc.dtype) == jnp.float32:
                return dt
        return None

    def _forward(self, params, state, inputs: List, *, train, rng, masks=None,
                 carries=None):
        """Forward along topo order. Returns (activations dict, new_state,
        new_carries). ``carries``: dict layer-name → recurrent carry (the
        reference's rnnTimeStep stateMap, ComputationGraph.java:2362); when
        given, recurrent layers resume from it and the updated map is
        returned (None entries mean zero initial state)."""
        gc = self.conf.global_conf
        acts: Dict[str, Any] = {}
        new_state = dict(state)
        new_carries = dict(carries) if carries is not None else None
        cdt = self._compute_dtype(train)
        if cdt is not None:
            params = _cast_floats(params, cdt)
        for i, n in enumerate(self.conf.network_inputs):
            x = inputs[i]
            if cdt is not None:
                x = x.astype(cdt)
            acts[n] = x
        for idx, name in enumerate(self.conf.topological_order):
            node = self.conf.nodes[name]
            if node.kind == "input":
                continue
            ins = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                v = node.vertex
                if getattr(v, "mask_input", None) is not None:
                    # mask-aware vertex (LastTimeStepVertex): the named
                    # network input's (B, T) mask locates true last steps
                    m = masks.get(v.mask_input) if masks else None
                    acts[name] = v.apply(ins, mask=m)
                else:
                    acts[name] = v.apply(ins)
                continue
            lrng = None if rng is None else jax.random.fold_in(rng, idx)
            mask = None
            if masks and node.inputs and node.inputs[0] in masks:
                mask = masks[node.inputs[0]]
            p_n = params.get(name, {})
            if (train and node.layer.weight_noise is not None
                    and lrng is not None):
                p_n = node.layer.weight_noise.apply(
                    p_n, jax.random.fold_in(lrng, 0x5eed))
            if (new_carries is not None
                    and hasattr(node.layer, "apply_with_carry")):
                y, c = node.layer.apply_with_carry(
                    p_n, ins[0], new_carries.get(name), mask=mask)
                new_carries[name] = c
            else:
                y, st = node.layer.apply(p_n, ins[0],
                                         state.get(name), train=train,
                                         rng=lrng, mask=mask)
                if st is not None:
                    new_state[name] = st
            acts[name] = y
        if cdt is not None:
            # persistent state (BN stats) keeps its storage dtype
            new_state = {
                k: _restore_dtypes(v, state[k])
                if k in state and state[k] is not None else v
                for k, v in new_state.items()}
        return acts, new_state, new_carries

    def _loss(self, params, state, inputs, labels, rng, masks=None,
              label_masks=None, carries=None):
        """Aux return is ``new_state`` normally; when ``carries`` is given
        (tBPTT chunked training) it is ``(new_state, new_carries)``."""
        acts, new_state, new_carries = self._forward(
            params, state, inputs, train=True, rng=rng, masks=masks,
            carries=carries)
        total = 0.0
        for oi, out_name in enumerate(self.conf.network_outputs):
            node = self.conf.nodes[out_name]
            if node.kind != "layer" or not hasattr(node.layer, "compute_score"):
                raise ValueError(f"Output '{out_name}' is not a loss-bearing layer")
            pre_act_input = acts[node.inputs[0]]
            lrng = None if rng is None else jax.random.fold_in(rng, 10000 + oi)
            lm = None if not label_masks else label_masks[oi]
            p_out = params.get(out_name, {})
            if node.layer.weight_noise is not None and lrng is not None:
                p_out = node.layer.weight_noise.apply(
                    p_out, jax.random.fold_in(lrng, 0x5eed))
            total = total + node.layer.compute_score(
                p_out, pre_act_input, labels[oi], lm,
                train=True, rng=lrng)
        for name, p in params.items():
            total = total + self.conf.nodes[name].layer.reg_loss(p)
        if self._compute_dtype(True) is not None:
            total = total.astype(jnp.float32)
        if carries is not None:
            return total, (new_state, new_carries)
        return total, new_state

    def _normalize_grads(self, grads):
        from deeplearning4j_tpu.nn.updaters import normalize_layer_grad
        gc = self.conf.global_conf
        kind = gc.gradient_normalization
        if not kind or kind == "None":
            return grads
        thr = gc.gradient_normalization_threshold
        return {n: normalize_layer_grad(g, kind, thr) for n, g in grads.items()}

    # -------------------------------------------- data-parallel protocol
    # Same three-method surface as MultiLayerNetwork so ParallelWrapper is
    # model-agnostic (parity: ParallelWrapper.java:58 takes any Model).
    def _dp_batch(self, ds):
        """DataSet/MultiDataSet → (inputs list, labels list, masks dict|None,
        label_masks list|None)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        if isinstance(ds, DataSet):
            ds = ds.to_multi()
        masks = None
        if ds.features_masks and any(m is not None for m in ds.features_masks):
            masks = {n: np.asarray(m) for n, m in
                     zip(self.conf.network_inputs, ds.features_masks)
                     if m is not None}
        label_masks = None
        if ds.labels_masks and any(m is not None for m in ds.labels_masks):
            label_masks = [None if m is None else np.asarray(m)
                           for m in ds.labels_masks]
        return ([np.asarray(f) for f in ds.features],
                [np.asarray(l) for l in ds.labels], masks, label_masks)

    def _dp_loss(self, params, state, inputs, labels, rng, pad_mask=None,
                 masks=None, label_masks=None):
        if pad_mask is not None:
            pms = [jnp.broadcast_to(pad_mask[:, None], y.shape[:2])
                   if y.ndim == 3 else pad_mask for y in labels]
            if label_masks is None:
                label_masks = pms
            else:
                label_masks = [pm if m is None else m * pm
                               for m, pm in zip(label_masks, pms)]
        return self._loss(params, state, inputs, labels, rng, masks,
                          label_masks)

    def _dp_apply_updates(self, params, opt_state, grads, fused=None):
        """Fused flat update by default (nn/fused_update.py — bitwise-equal
        to the per-node loop below, kept as the DL4JTPU_FUSED_UPDATE=0
        fallback and parity oracle). Tensor-parallel callers pass
        ``fused=False``: raveling row- and column-sharded leaves into one
        vector would gather every shard (and trips a GSPMD mis-partition
        on mixed-axis concat) — the per-node loop keeps TP placement."""
        grads = self._normalize_grads(grads)
        if fused is None:
            fused = self._executor.model_size <= 1
        if fused and self._fused is not None:
            return self._fused.apply(params, opt_state, grads)
        new_params, new_opt = {}, {}
        for name, p in params.items():
            if not p:
                new_params[name], new_opt[name] = p, opt_state[name]
                continue
            u, o = self._transforms[name].update(grads[name], opt_state[name], p)
            np_ = optax.apply_updates(p, u)
            np_ = self.conf.nodes[name].layer.apply_constraints(np_)
            new_params[name], new_opt[name] = np_, o
        return new_params, new_opt

    def _note_compile(self):
        # called from inside jitted train-step bodies: runs only while jit
        # traces a NEW signature, i.e. exactly once per compiled program.
        # Program-registry introspection re-lowers the same body (exec/
        # programs.py) — that re-trace must not count as a fresh compile.
        from deeplearning4j_tpu.exec.programs import is_registering
        if is_registering():
            return
        self._compile_count += 1

    @property
    def _mon(self):
        if self._train_mon is None:
            from deeplearning4j_tpu.monitor.hooks import TrainMonitor
            self._train_mon = TrainMonitor(type(self).__name__)
        return self._train_mon

    # ----------------------------------------------------------- train step
    def _loss_for_grad(self):
        """jax.checkpoint-wrapped loss when remat is configured (see
        GlobalConf.remat / MultiLayerNetwork._loss_for_grad)."""
        from deeplearning4j_tpu.util.remat import remat_loss
        return remat_loss(self._loss, self.conf.global_conf.remat)

    def _make_train_step(self):
        loss_fn = self._loss_for_grad()
        rec = self._flight           # captured at trace-build time: the
        # recorder-off program is byte-identical to the pre-flight path
        sample_k = rec.sample_every if rec is not None else 1

        def step(params, state, opt_state, inputs, labels, it, masks, label_masks):
            self._note_compile()
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self.conf.global_conf.seed), it)
            (loss, new_state), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, inputs, labels, rng,
                                       masks, label_masks)
            new_params, new_opt = self._dp_apply_updates(params, opt_state, grads)
            if rec is None:
                return new_params, new_state, new_opt, loss
            from deeplearning4j_tpu.monitor import flight
            telem = flight.step_telemetry(
                flight.telemetry_triples(params, new_params, grads),
                it, sample_k)
            return new_params, new_state, new_opt, loss, telem

        from deeplearning4j_tpu import exec as ex
        out_specs = (ex.PARAMS, ex.STATE, ex.OPT, ex.REPL)
        if rec is not None:
            out_specs = out_specs + (ex.AUX,)
        return self._executor.jit(
            step,
            in_specs=(ex.PARAMS, ex.STATE, ex.OPT, ex.BATCH, ex.BATCH,
                      ex.REPL, ex.BATCH, ex.BATCH),
            out_specs=out_specs,
            donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------- fit
    def fit_scan(self, inputs_steps, labels_steps):
        """Device-resident training: ``n`` train steps in ONE compiled call
        via lax.scan over a leading step axis (see
        MultiLayerNetwork.fit_scan). ``inputs_steps``/``labels_steps``:
        lists of arrays shaped (n_steps, batch, ...) — or single arrays for
        single-input/-output graphs."""
        if getattr(self.conf, "backprop_type", "standard") == "tbptt":
            raise ValueError(
                "fit_scan runs full-sequence backprop; a graph configured "
                "for truncated BPTT must use fit() (the tbptt chunking path)")
        if not isinstance(inputs_steps, (list, tuple)):
            inputs_steps = [inputs_steps]
        if not isinstance(labels_steps, (list, tuple)):
            labels_steps = [labels_steps]
        inputs_steps = [jnp.asarray(a) for a in inputs_steps]
        labels_steps = [jnp.asarray(a) for a in labels_steps]
        if self._scan_fit is None:
            loss_fn = self._loss_for_grad()
            rec = self._flight       # trace-build capture (see attach)
            sample_k = rec.sample_every if rec is not None else 1

            def inner(params, state, opt_state, xs, ys, it0):
                self._note_compile()

                def body(carry, inp):
                    params, state, opt_state, it = carry
                    x, y = inp
                    rng = jax.random.fold_in(
                        jax.random.PRNGKey(self.conf.global_conf.seed), it)
                    (loss, new_state), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, state, x, y, rng,
                                               None, None)
                    new_params, opt_state = self._dp_apply_updates(
                        params, opt_state, grads)
                    if rec is None:
                        return (new_params, new_state, opt_state,
                                it + 1), loss
                    from deeplearning4j_tpu.monitor import flight
                    telem = flight.step_telemetry(
                        flight.telemetry_triples(params, new_params, grads),
                        it, sample_k)
                    return (new_params, new_state, opt_state, it + 1), \
                        (loss, telem)

                (p, s, o, _), out = jax.lax.scan(
                    body, (params, state, opt_state, it0), (xs, ys))
                if rec is None:
                    return p, s, o, out
                return p, s, o, out[0], out[1]

            from deeplearning4j_tpu import exec as ex
            out_specs = (ex.PARAMS, ex.STATE, ex.OPT, ex.REPL)
            if rec is not None:
                out_specs = out_specs + (ex.AUX,)
            self._scan_fit = self._executor.jit(
                inner,
                in_specs=(ex.PARAMS, ex.STATE, ex.OPT, ex.STEP_BATCH,
                          ex.STEP_BATCH, ex.REPL),
                out_specs=out_specs,
                donate_argnums=(0, 1, 2))
        c0, t0 = self._compile_count, time.perf_counter()
        if self._flight is not None:
            (self.params, self.state, self.opt_state, losses,
             telems) = self._scan_fit(
                self.params, self.state, self.opt_state, inputs_steps,
                labels_steps, jnp.asarray(self.iteration, jnp.int32))
            self._flight.record_scan(self.iteration, telems)
        else:
            self.params, self.state, self.opt_state, losses = self._scan_fit(
                self.params, self.state, self.opt_state, inputs_steps,
                labels_steps, jnp.asarray(self.iteration, jnp.int32))
        self._last_input = [a[-1] for a in inputs_steps]  # activation capture
        n_steps = int(inputs_steps[0].shape[0])
        self.iteration += n_steps
        self._epoch_batch += n_steps
        self._score = losses[-1]
        self._mon.record(seconds=time.perf_counter() - t0, steps=n_steps,
                         examples=n_steps * int(inputs_steps[0].shape[1]),
                         score=self._score,
                         compiled=self._compile_count - c0, path="scan")
        if self._compile_count > c0:
            # fresh XLA program: record its cost/memory analysis so /programs
            # and the bench MFU column read measured numbers, not estimates.
            # Lowering args are the donated call's OUTPUTS (same shapes).
            self._executor.register_program(
                self._prog_caller,
                f"fit_scan_k{n_steps}_b{int(inputs_steps[0].shape[1])}",
                self._scan_fit,
                (self.params, self.state, self.opt_state, inputs_steps,
                 labels_steps, jnp.asarray(self.iteration, jnp.int32)),
                compile_seconds=time.perf_counter() - t0)
        if self.listeners:
            with trace.span("callback"):
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration, self.epoch)
        return self

    def fit(self, data, labels=None, epochs=1, prefetch=None,
            checkpoint=None, resume_from=None):
        """fit(inputs, labels) | fit(MultiDataSet/DataSet) | fit(iterator).

        ``prefetch``: device-resident prefetch depth for the streamed path
        (see data/prefetcher.py and MultiLayerNetwork.fit); ``None`` uses
        the class default ``prefetch_depth``, ``0`` disables. Per-stage
        timing lands in ``self.last_pipeline_stats``.

        ``checkpoint`` / ``resume_from``: crash-safe periodic saves and
        bitwise-identical continuation — same contract as
        MultiLayerNetwork.fit (docs/FAULT_TOLERANCE.md)."""
        from deeplearning4j_tpu.monitor.profiling import profile_scope

        # DL4JTPU_PROFILE=<dir> wraps the whole call in jax.profiler.trace
        # (docs/OBSERVABILITY.md); unset, this is a plain passthrough
        with profile_scope():
            return self._fit_impl(data, labels, epochs, prefetch,
                                  checkpoint, resume_from)

    def _fit_impl(self, data, labels, epochs, prefetch, checkpoint,
                  resume_from):
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet

        ckpt = None
        if checkpoint is not None:
            from deeplearning4j_tpu.resilience.checkpoint import (
                CheckpointListener)
            ckpt = (checkpoint if isinstance(checkpoint, CheckpointListener)
                    else CheckpointListener(checkpoint, every_n_epochs=1))
            self.listeners.append(ckpt)
        try:
            direct = (labels is not None
                      or isinstance(data, (DataSet, MultiDataSet)))
            if direct:
                if resume_from is not None:
                    raise ValueError(
                        "resume_from needs resettable iterator data; a bare "
                        "array/DataSet fit has no epoch stream to replay")
                if labels is not None:
                    return self._fit_batch(MultiDataSet(
                        features=[data] if not isinstance(data, (list, tuple))
                        else list(data),
                        labels=[labels] if not isinstance(labels, (list, tuple))
                        else list(labels)))
                if isinstance(data, DataSet):
                    return self._fit_batch(data.to_multi())
                return self._fit_batch(data)
            n_epochs, skip = epochs, 0
            if resume_from is not None:
                if not hasattr(data, "reset"):
                    raise ValueError(
                        "resume_from needs a resettable iterator (reset()) "
                        "to replay the stream to the crash position")
                skip = self._resume_training(resume_from, data)
                n_epochs = max(0, epochs - self.epoch)
            for k in range(n_epochs):
                if hasattr(data, "reset"):
                    data.reset()
                self._fit_stream(data, prefetch=prefetch,
                                 skip_batches=skip if k == 0 else 0)
                self.epoch += 1
                self._epoch_batch = 0
                for lst in self.listeners:
                    if hasattr(lst, "on_epoch_end"):
                        lst.on_epoch_end(self)
            return self
        finally:
            if ckpt is not None:
                self.listeners.remove(ckpt)

    def _resume_training(self, resume_from, data):
        """See MultiLayerNetwork._resume_training — restore + wind the
        iterator to the crash position; returns batches to skip in the
        first (partial) epoch."""
        import os as _os
        from deeplearning4j_tpu.resilience.checkpoint import latest_checkpoint
        from deeplearning4j_tpu.util.model_serializer import restore_into

        path = _os.fspath(resume_from)
        if _os.path.isdir(path):
            found = latest_checkpoint(path)
            if found is None:
                raise FileNotFoundError(
                    f"resume_from: no checkpoints in directory {path}")
            path = found
        restore_into(self, path)
        # one reset() + ONE iter() + full consumption per completed epoch —
        # the exact call sequence the uninterrupted fit made (a bare
        # `for _ in iter(data)` would invoke __iter__ twice and de-sync
        # reset-counting shuffles; see MultiLayerNetwork._resume_training)
        for _ in range(self.epoch):
            data.reset()
            it = iter(data)
            while True:
                try:
                    next(it)
                except StopIteration:
                    break
        return self._epoch_batch

    # chunk caps — see MultiLayerNetwork._fit_stream (same design: runs of
    # mask-free same-shape batches stack onto the device-resident scan path)
    _CHUNK_MAX_STEPS = 64
    _CHUNK_MAX_BYTES = 256 << 20

    # see MultiLayerNetwork: device-resident prefetch depth for the
    # streamed fit/eval path, and the last epoch's per-stage timing
    prefetch_depth = 2
    last_pipeline_stats = None

    def _resolve_device_pp(self, data):
        """(dev_fn, host_pp) — see MultiLayerNetwork._resolve_device_pp;
        a device_side processor with no device transform falls back to
        host application."""
        from deeplearning4j_tpu.data.iterators import resolve_pre_processor

        pp = resolve_pre_processor(data)
        dev_fn = host_pp = None
        if pp is not None and getattr(pp, "device_side", False):
            f = pp.as_device_transform()
            if f is not None:
                dev_fn = jax.jit(f)
            else:
                host_pp = pp
        return dev_fn, host_pp

    def _stream_chunks(self, data, host_pp, timer, skip_batches=0):
        """Host-side chunk assembly (see MultiLayerNetwork._stream_chunks):
        yields ``("chunk", (xs_list, ys_list))`` stacked host blocks or
        ``("batch", MultiDataSet)`` fallbacks, in base order — chunk
        boundaries do not depend on prefetch depth, so the training math
        is bitwise-identical with prefetch on or off."""
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet

        chunkable = (getattr(self.conf, "backprop_type", "standard")
                     != "tbptt")
        buf, shape = [], None

        def flush():
            nonlocal buf, shape
            out = None
            if len(buf) == 1:
                out = ("batch", buf[0])
            elif buf:
                with timer.stage("stack"):
                    xs = [np.stack([np.asarray(m.features[i]) for m in buf])
                          for i in range(len(buf[0].features))]
                    ys = [np.stack([np.asarray(m.labels[i]) for m in buf])
                          for i in range(len(buf[0].labels))]
                    out = ("chunk", (xs, ys))
            buf, shape = [], None
            return out

        it = iter(data)
        for _ in range(skip_batches):
            # resume path: already trained before the crash — pull and drop
            # so the stream (and any iterator RNG) advances identically
            try:
                next(it)
            except StopIteration:
                return
        while True:
            t0 = time.perf_counter()
            try:
                with trace.span("fetch"):
                    batch = next(it)
            except StopIteration:
                break
            timer.add("fetch", time.perf_counter() - t0)
            if isinstance(batch, DataSet):
                batch = batch.to_multi()
            elif not isinstance(batch, MultiDataSet):
                batch = MultiDataSet(features=[batch[0]], labels=[batch[1]])
            if host_pp is not None:
                with timer.stage("decode"):
                    batch = MultiDataSet(
                        features=[host_pp.transform_features(np.asarray(f))
                                  for f in batch.features],
                        labels=batch.labels,
                        features_masks=batch.features_masks,
                        labels_masks=batch.labels_masks)
            has_mask = (
                (batch.features_masks
                 and any(m is not None for m in batch.features_masks))
                or (batch.labels_masks
                    and any(m is not None for m in batch.labels_masks)))
            if not chunkable or has_mask:
                out = flush()
                if out is not None:
                    yield out
                yield ("batch", batch)
                continue
            key = (tuple(np.asarray(f).shape for f in batch.features),
                   tuple(np.asarray(l).shape for l in batch.labels))
            if shape is not None and key != shape:
                out = flush()
                if out is not None:
                    yield out
            shape = key
            buf.append(batch)
            per = (sum(np.asarray(f).nbytes for f in batch.features)
                   + sum(np.asarray(l).nbytes for l in batch.labels))
            if len(buf) >= max(1, min(self._CHUNK_MAX_STEPS,
                                      self._CHUNK_MAX_BYTES // max(1, per))):
                yield flush()
        out = flush()
        if out is not None:
            yield out

    def _fit_stream(self, data, prefetch=None, skip_batches=0):
        """One epoch: host chunk assembly → device-resident prefetch →
        compiled steps (see MultiLayerNetwork._fit_stream for the overlap
        model and stall accounting)."""
        from deeplearning4j_tpu.data.dataset import MultiDataSet
        from deeplearning4j_tpu.data.prefetcher import DevicePrefetcher
        from deeplearning4j_tpu.util.timing import PipelineTimer

        dev_fn, host_pp = self._resolve_device_pp(data)

        def dev_mds(m):
            if dev_fn is None:
                return m
            return MultiDataSet(
                features=[dev_fn(jnp.asarray(ff)) for ff in m.features],
                labels=m.labels, features_masks=m.features_masks,
                labels_masks=m.labels_masks)

        depth = self.prefetch_depth if prefetch is None else int(prefetch)
        timer = PipelineTimer()
        stream = self._stream_chunks(data, host_pp, timer,
                                     skip_batches=skip_batches)
        if depth > 0:
            stream = DevicePrefetcher(stream, depth=depth, timer=timer)
        it = iter(stream)
        timer.start()
        while True:
            # one "train_step" span per consumer iteration (nests the wait
            # and the step — see MultiLayerNetwork._fit_stream)
            with trace.span("train_step"):
                with timer.stage("wait"):
                    try:
                        kind, payload = next(it)
                    except StopIteration:
                        break
                with timer.stage("step"):
                    if kind == "chunk":
                        xs, ys = payload
                        xs = [jnp.asarray(a) for a in xs]
                        if dev_fn is not None:
                            xs = [dev_fn(a) for a in xs]
                        self.fit_scan(xs, ys)
                    else:
                        # fallback batches must be normalized too (the
                        # iterator emitted them raw for a device_side
                        # processor)
                        self._fit_batch(dev_mds(payload))
        timer.stop()
        self.last_pipeline_stats = timer.summary()
        timer.publish("fit")

    def _fit_batch(self, mds):
        inputs = [jnp.asarray(f) for f in mds.features]
        labels = [jnp.asarray(l) for l in mds.labels]
        self._last_input = inputs     # device ref for activation capture
        c0, t0 = self._compile_count, time.perf_counter()
        masks = None
        if mds.features_masks and any(m is not None for m in mds.features_masks):
            masks = {n: jnp.asarray(m) for n, m in
                     zip(self.conf.network_inputs, mds.features_masks)
                     if m is not None}
        label_masks = None
        if mds.labels_masks and any(m is not None for m in mds.labels_masks):
            label_masks = [None if m is None else jnp.asarray(m)
                           for m in mds.labels_masks]
        if (getattr(self.conf, "backprop_type", "standard") == "tbptt"
                and inputs[0].ndim == 3):
            self._fit_tbptt(inputs, labels, masks, label_masks)
        else:
            key = (masks is not None, label_masks is not None)
            if key not in self._train_step_cache:
                self._train_step_cache[key] = self._make_train_step()
            step = self._train_step_cache[key]
            out = step(
                self.params, self.state, self.opt_state, inputs, labels,
                jnp.asarray(self.iteration, jnp.int32), masks, label_masks)
            self.params, self.state, self.opt_state, loss = out[:4]
            self._score = loss  # device scalar; host-read deferred to
                                # get_score() (sync ~100ms on tunneled TPUs)
            if self._flight is not None:
                self._flight.record(self.iteration, out[4])
            if self._compile_count > c0:
                # fresh XLA program: expose its cost/memory analysis via the
                # registry (/programs). Donated inputs → lower with outputs.
                self._executor.register_program(
                    self._prog_caller,
                    f"train_step_b{int(inputs[0].shape[0])}",
                    step,
                    (self.params, self.state, self.opt_state, inputs, labels,
                     jnp.asarray(self.iteration, jnp.int32), masks,
                     label_masks),
                    compile_seconds=time.perf_counter() - t0)
        self._last_fit_time = time.perf_counter() - t0
        self.iteration += 1
        self._epoch_batch += 1
        self._mon.record(seconds=self._last_fit_time, steps=1,
                         examples=int(inputs[0].shape[0]), score=self._score,
                         compiled=self._compile_count - c0, path="batch")
        if self.listeners:
            with trace.span("callback"):
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration, self.epoch)
        return self

    # ---------------------------------------------------------------- tbptt
    def _make_tbptt_step(self):
        rec = self._flight
        sample_k = rec.sample_every if rec is not None else 1

        def step(params, state, opt_state, inputs, labels, it, masks,
                 label_masks, carries):
            self._note_compile()
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self.conf.global_conf.seed), it)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                self._loss, has_aux=True)(params, state, inputs, labels, rng,
                                          masks, label_masks, carries)
            new_params, new_opt = self._dp_apply_updates(params, opt_state,
                                                         grads)
            if rec is None:
                return new_params, new_state, new_opt, loss, new_carries
            from deeplearning4j_tpu.monitor import flight
            telem = flight.step_telemetry(
                flight.telemetry_triples(params, new_params, grads),
                it, sample_k)
            return new_params, new_state, new_opt, loss, new_carries, telem

        from deeplearning4j_tpu import exec as ex
        out_specs = (ex.PARAMS, ex.STATE, ex.OPT, ex.REPL, ex.BATCH)
        if rec is not None:
            out_specs = out_specs + (ex.AUX,)
        return self._executor.jit(
            step,
            in_specs=(ex.PARAMS, ex.STATE, ex.OPT, ex.BATCH, ex.BATCH,
                      ex.REPL, ex.BATCH, ex.BATCH, ex.BATCH),
            out_specs=out_specs,
            donate_argnums=(0, 1, 2))

    def _fit_tbptt(self, inputs, labels, masks, label_masks):
        """Truncated BPTT over the graph: slice time into tbptt_fwd_length
        chunks, carrying recurrent state across chunks (parity:
        ComputationGraph.java:1617-1629 doTruncatedBPTT). Truncation is
        structural: each chunk's step differentiates only through its own
        forward — the carried state enters as a plain (non-differentiated)
        argument, so no stop_gradient is needed."""
        T = inputs[0].shape[1]
        L = self.conf.tbptt_fwd_length
        if "tbptt" not in self._train_step_cache:
            self._train_step_cache["tbptt"] = self._make_tbptt_step()
        step = self._train_step_cache["tbptt"]
        carries = {}
        losses = []
        telem = None
        for start in range(0, T, L):
            sl = slice(start, start + L)
            ins = [x[:, sl] if x.ndim == 3 else x for x in inputs]
            lbs = [y[:, sl] if y.ndim == 3 else y for y in labels]
            mks = None if masks is None else {
                n: (m[:, sl] if m.ndim >= 2 else m) for n, m in masks.items()}
            lms = None if label_masks is None else [
                None if m is None else (m[:, sl] if m.ndim >= 2 else m)
                for m in label_masks]
            out = step(
                self.params, self.state, self.opt_state, ins, lbs,
                jnp.asarray(self.iteration, jnp.int32), mks, lms, carries)
            self.params, self.state, self.opt_state, loss, carries = out[:5]
            if self._flight is not None:
                telem = out[5]      # every chunk shares the iteration —
                                    # the LAST chunk's stats are the record
            losses.append(loss)
        self._score = jnp.mean(jnp.stack(losses))   # device-side mean
        if self._flight is not None and telem is not None:
            self._flight.record(self.iteration, telem)

    # ------------------------------------------------------------- inference
    def serving_engine(self, **kw):
        """The shape-bucketed inference engine for this graph (lazy, shared
        by ``output``/``evaluate``; see serving/engine.py)."""
        if self._serving is None:
            from deeplearning4j_tpu.serving.engine import InferenceEngine
            self._serving = InferenceEngine(self, **kw)
        return self._serving

    def output(self, *inputs, train=False, bucketed=True):
        """Multi-output inference (parity: ComputationGraph.output :1532).

        Default fast path is shape-bucketed (see
        MultiLayerNetwork.output): every input is padded to the same
        power-of-two batch bucket and pad rows are sliced off the outputs,
        so a handful of compiled programs serve every request size.
        ``bucketed=False`` forces the exact-shape program."""
        inputs = [jnp.asarray(x) for x in inputs]
        if bucketed:
            outs = self.serving_engine().predict(list(inputs))
            return outs
        if self._output_fn is None:
            def fwd(params, state, inputs):
                acts, _, _ = self._forward(params, state, inputs, train=False,
                                           rng=None)
                return [acts[n] for n in self.conf.network_outputs]
            from deeplearning4j_tpu import exec as ex
            self._output_fn = self._executor.jit(
                fwd, in_specs=(ex.PARAMS, ex.STATE, ex.BATCH),
                out_specs=(ex.BATCH,))
        outs = self._output_fn(self.params, self.state, inputs)
        return outs[0] if len(outs) == 1 else outs

    def score(self, mds=None, inputs=None, labels=None):
        from deeplearning4j_tpu.data.dataset import DataSet
        if mds is not None:
            if isinstance(mds, DataSet):
                mds = mds.to_multi()
            inputs, labels = mds.features, mds.labels
        loss, _ = self._loss(self.params, self.state,
                             [jnp.asarray(x) for x in inputs],
                             [jnp.asarray(y) for y in labels], None)
        return float(loss)

    def get_score(self):
        self._score = float(self._score)   # cache: host read is ~100ms on
        return self._score                 # tunneled TPU attachments

    # ------------------------------------------------- external gradients
    def backprop_external(self, inputs, epsilons):
        """Parameter gradients from externally-supplied dL/d(output)
        epsilons (parity: ComputationGraph.calcBackpropGradients(
        externalEpsilons), used when this graph's outputs feed an external
        computation — e.g. featurized transfer-learning workflows).
        ``epsilons``: one array per network output, shaped like it.
        Returns (grads, new_state) — grads include the l1/l2 regularization
        term (this framework applies regularization in the loss, so an
        external-epsilon step must add its gradient explicitly to match
        fit())."""
        inputs = [jnp.asarray(x) for x in inputs] \
            if isinstance(inputs, (list, tuple)) else [jnp.asarray(inputs)]
        epsilons = [jnp.asarray(e) for e in epsilons] \
            if isinstance(epsilons, (list, tuple)) else [jnp.asarray(epsilons)]

        # iteration-seeded PRNG like fit(): dropout/weight-noise behave the
        # same on the external-epsilon path as in ordinary training
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.conf.global_conf.seed), self.iteration)

        def outs(params):
            acts, new_state, _ = self._forward(params, self.state, inputs,
                                               train=True, rng=rng)
            return [acts[n] for n in self.conf.network_outputs], new_state

        _, vjp, new_state = jax.vjp(outs, self.params, has_aux=True)
        (grads,) = vjp(epsilons)

        def reg(params):
            return sum((self.conf.nodes[n].layer.reg_loss(p)
                        for n, p in params.items()), jnp.float32(0))

        reg_grads = jax.grad(reg)(self.params)
        grads = jax.tree_util.tree_map(jnp.add, grads, reg_grads)
        return grads, new_state

    def _apply_updates_jitted(self):
        """The standalone grad→update→apply program: one compile per
        (model, updater), params + opt-state donated so XLA updates in
        place. Traces the same `_dp_apply_updates` math the train step
        embeds (fused flat path by default)."""
        if self._update_step is None:
            def upd(params, opt_state, grads):
                self._note_compile()
                return self._dp_apply_updates(params, opt_state, grads)

            from deeplearning4j_tpu import exec as ex
            self._update_step = self._executor.jit(
                upd, in_specs=(ex.PARAMS, ex.OPT, ex.PARAMS),
                out_specs=(ex.PARAMS, ex.OPT), donate_argnums=(0, 1))
        return self._update_step

    def apply_external_updates(self, grads):
        """One updater step from externally-computed gradients via the
        donated fused-update program (registered as ``apply_updates`` in
        the /programs registry)."""
        step = self._apply_updates_jitted()
        c0, t0 = self._compile_count, time.perf_counter()
        self.params, self.opt_state = step(self.params, self.opt_state,
                                           grads)
        if self._compile_count > c0:
            self._executor.register_program(
                self._prog_caller, "apply_updates", step,
                (self.params, self.opt_state, grads),
                compile_seconds=time.perf_counter() - t0)
        return self

    def fit_external(self, inputs, epsilons):
        """One updater step driven by external epsilons (the training half
        of the externalEpsilons contract). Updates params, updater state and
        layer state (e.g. batchnorm running stats) like fit(). The update
        runs through the standalone donated program, not an eager loop."""
        grads, new_state = self.backprop_external(inputs, epsilons)
        self.apply_external_updates(grads)
        self.state = new_state
        self.iteration += 1
        return self

    # ------------------------------------------------------------------ rnn
    def rnn_time_step(self, *inputs):
        """Stateful streaming inference: feed one (or a few) timesteps,
        recurrent layers resume from the stored state map (parity:
        ComputationGraph.rnnTimeStep :2362). 2-D inputs are treated as a
        single timestep (B, F) → (B, 1, F)."""
        inputs = [jnp.asarray(x) for x in inputs]
        inputs = [x[:, None, :] if x.ndim == 2 else x for x in inputs]
        if self._rnn_carries is None:
            self._rnn_carries = {}
        acts, _, self._rnn_carries = self._forward(
            self.params, self.state, inputs, train=False, rng=None,
            carries=self._rnn_carries)
        outs = [acts[n] for n in self.conf.network_outputs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        """Parity: ComputationGraph.rnnClearPreviousState."""
        self._rnn_carries = None

    # --------------------------------------------------- incremental decode
    def init_decode_state(self, batch: int, max_len: int = 256, kv=None):
        """Decode state keyed by layer-node name (see
        MultiLayerNetwork.init_decode_state; serving/decode.py holds this
        tree resident on device across token steps). ``kv`` switches
        attention nodes to the shared block-pool layout (serving/kv/)."""
        gc = self.conf.global_conf
        dt = _dtype_of(gc.compute_dtype or gc.dtype)
        out = {}
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "layer":
                if kv is not None:
                    out[name] = node.layer.init_paged_decode_state(
                        self.params.get(name, {}), batch, max_len,
                        kv["num_blocks"], kv["block_size"], dt)
                else:
                    out[name] = node.layer.init_decode_state(
                        self.params.get(name, {}), batch, max_len, dt)
        return out

    def decode_step(self, params, state, dstate, x_t, pos,
                    block_tables=None):
        """Pure one-token step along the topo order (single-input,
        single-path graphs; vertices like residual adds work on the
        (B, 1, F) slices unchanged). Bitwise contract and compute-dtype
        handling match MultiLayerNetwork.decode_step; ``block_tables``
        routes attention nodes through the paged-KV path."""
        if len(self.conf.network_inputs) != 1:
            raise ValueError(
                "incremental decode supports single-input graphs; got "
                f"inputs {self.conf.network_inputs}")
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x_t = x_t.astype(cdt)
            params = _cast_floats(params, cdt)
        acts = {self.conf.network_inputs[0]: x_t}
        new_d = dict(dstate)
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                continue
            ins = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                acts[name] = node.vertex.apply(ins)
                continue
            st = state.get(name) if state else None
            if block_tables is None:
                y, nd = node.layer.decode_step(
                    params.get(name, {}), dstate.get(name), ins[0], pos,
                    state=st)
            else:
                y, nd = node.layer.decode_step_paged(
                    params.get(name, {}), dstate.get(name), ins[0], pos,
                    block_tables, state=st)
            new_d[name] = nd
            acts[name] = y
        outs = [acts[n] for n in self.conf.network_outputs]
        return (outs[0] if len(outs) == 1 else outs), new_d

    def prefill_chunk(self, params, state, dstate, x, start, n,
                      block_tables=None, carry_stack=False):
        """Advance a prefill chunk along the topo order: ``x`` (B, K, F)
        chunk activations, ``n`` (B,) valid rows (Layer.prefill_chunk).
        Vertices apply to the (B, K, F) chunk slices unchanged.
        ``carry_stack=True`` additionally returns a name-keyed dict of
        carry snapshot stacks (None where the layer keeps no carry) for
        speculative rewind (serving/spec/)."""
        if len(self.conf.network_inputs) != 1:
            raise ValueError(
                "incremental decode supports single-input graphs; got "
                f"inputs {self.conf.network_inputs}")
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x = x.astype(cdt)
            params = _cast_floats(params, cdt)
        acts = {self.conf.network_inputs[0]: x}
        new_d = dict(dstate)
        stacks = {}
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                continue
            ins = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                acts[name] = node.vertex.apply(ins)
                continue
            st = state.get(name) if state else None
            if carry_stack:
                y, nd, stacks[name] = node.layer.prefill_chunk(
                    params.get(name, {}), dstate.get(name), ins[0], start,
                    n, state=st, block_tables=block_tables,
                    carry_stack=True)
            else:
                y, nd = node.layer.prefill_chunk(
                    params.get(name, {}), dstate.get(name), ins[0], start,
                    n, state=st, block_tables=block_tables)
            new_d[name] = nd
            acts[name] = y
        outs = [acts[n] for n in self.conf.network_outputs]
        out = outs[0] if len(outs) == 1 else outs
        return (out, new_d, stacks) if carry_stack else (out, new_d)

    def tree_chunk(self, params, state, dstate, x, pos0, tree, n,
                   block_tables=None):
        """Score a speculation token tree along the topo order (see
        MultiLayerNetwork.tree_chunk): ``x`` (B, N, F) node activations,
        vertices apply to the (B, N, F) slices unchanged. Returns
        ``(y, stacks, kv_windows)`` keyed by layer-node name; ``dstate``
        is NOT advanced — the verify program rewinds carries from the
        stacks and commits the accepted path via ``tree_commit``."""
        if len(self.conf.network_inputs) != 1:
            raise ValueError(
                "incremental decode supports single-input graphs; got "
                f"inputs {self.conf.network_inputs}")
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x = x.astype(cdt)
            params = _cast_floats(params, cdt)
        acts = {self.conf.network_inputs[0]: x}
        stacks, wins = {}, {}
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                continue
            ins = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                acts[name] = node.vertex.apply(ins)
                continue
            st = state.get(name) if state else None
            y, _, stacks[name], wins[name] = node.layer.tree_chunk(
                params.get(name, {}), dstate.get(name), ins[0], pos0,
                tree, n, state=st, block_tables=block_tables)
            acts[name] = y
        outs = [acts[n] for n in self.conf.network_outputs]
        return (outs[0] if len(outs) == 1 else outs), stacks, wins

    def tree_commit(self, dstate, kv_windows, path, pos0, commit_n,
                    block_tables=None):
        """Write the accepted root-path's positional KV into the decode
        state (Layer.tree_commit); nodes without a KV window pass
        through untouched."""
        new_d = dict(dstate)
        for name, win in kv_windows.items():
            if win is not None:
                new_d[name] = self.conf.nodes[name].layer.tree_commit(
                    None, dstate.get(name), win, path, pos0, commit_n,
                    block_tables=block_tables)
        return new_d

    def evaluate(self, data):
        """First-output classification eval, dispatched through the
        bucketed engine with the host read pipelined one batch behind the
        device (see MultiLayerNetwork._eval_stream). Features are staged
        on device ahead of the engine and a ``device_side`` pre-processor
        on the iterator chain runs on chip here too — train/eval parity."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.data.prefetcher import DevicePrefetcher
        from deeplearning4j_tpu.util.timing import PipelineTimer

        ev = Evaluation()
        dev_fn, host_pp = self._resolve_device_pp(data)
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        elif hasattr(data, "reset"):
            data.reset()
        eng = self.serving_engine()
        labels = []
        timer = PipelineTimer()

        def feats():
            for ds in data:
                if isinstance(ds, DataSet):
                    ds = ds.to_multi()
                if host_pp is not None:
                    ds = MultiDataSet(
                        features=[host_pp.transform_features(np.asarray(f))
                                  for f in ds.features],
                        labels=ds.labels, features_masks=ds.features_masks,
                        labels_masks=ds.labels_masks)
                labels.append(ds.labels[0])
                yield [jnp.asarray(f) for f in ds.features]

        dev_tx = (None if dev_fn is None
                  else (lambda fs: [dev_fn(f) for f in fs]))
        staged = DevicePrefetcher(feats(), depth=max(1, self.prefetch_depth),
                                  transform=dev_tx, timer=timer)
        timer.start()
        for i, out in enumerate(eng.predict_stream(staged)):
            if isinstance(out, list):
                out = out[0]
            ev.eval(np.asarray(labels[i]), out)
        timer.stop()
        self.last_pipeline_stats = timer.summary()
        timer.publish("eval")
        return ev

    # ------------------------------------------------------------- utilities
    def num_params(self):
        return sum(int(np.prod(a.shape)) for a in
                   jax.tree_util.tree_leaves(self.params))

    def summary(self):
        lines = ["=" * 78,
                 f"{'Vertex':<28}{'Type':<26}{'Inputs':<14}{'Params':>10}",
                 "=" * 78]
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                lines.append(f"{name:<28}{'(input)':<26}{'':<14}{0:>10}")
                continue
            tname = (type(node.layer).__name__ if node.kind == "layer"
                     else type(node.vertex).__name__)
            n = 0
            if node.kind == "layer" and self.params and name in self.params:
                n = sum(int(np.prod(a.shape)) for a in
                        jax.tree_util.tree_leaves(self.params[name]))
            ins = ",".join(node.inputs)[:13]
            lines.append(f"{name:<28}{tname:<26}{ins:<14}{n:>10,}")
        lines.append("=" * 78)
        lines.append(f"Total params: {self.num_params():,}")
        return "\n".join(lines)

    def save(self, path, save_updater=True):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(self, path, save_updater)

    @staticmethod
    def load(path, load_updater=True):
        from deeplearning4j_tpu.util.model_serializer import restore_computation_graph
        return restore_computation_graph(path, load_updater)
