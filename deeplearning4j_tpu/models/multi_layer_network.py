"""MultiLayerNetwork — the sequential network container.

Parity surface: reference nn/multilayer/MultiLayerNetwork.java (3,156 LoC):
``init`` (:541), ``fit`` (:1156), ``output`` (:1947), ``score``,
``computeGradientAndScore`` (:2206), truncated BPTT (:1219),
``rnnTimeStep`` (:2209 stored-state path), plus the Solver/updater loop
(optimize/Solver.java, BaseOptimizer.java:171).

TPU design: ONE jit-compiled pure train step per network — forward, loss,
``jax.grad`` backward, optax update, constraints — all fused by XLA into a
single device program (the reference runs a Java-side loop over layers with a
JNI call per op). Parameters/updater state are immutable pytrees; "mutation"
is rebinding, and buffers are donated so XLA updates in place.
"""

from __future__ import annotations

import itertools
import time
from typing import Optional, List, Any, Dict

import numpy as np
import jax
import jax.numpy as jnp
import optax

from deeplearning4j_tpu.monitor.tracing import trace
from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
from deeplearning4j_tpu.nn.updaters import make_gradient_transform
from deeplearning4j_tpu.nn.layers.special import FrozenLayer


def _dtype_of(name):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


from deeplearning4j_tpu.util.dtypes import (cast_floats as _cast_floats,
                                             restore_dtypes as _restore_dtypes)


class MultiLayerNetwork:
    _prog_ids = itertools.count()

    def __init__(self, conf: MultiLayerConfiguration):
        conf.finalize()
        self.conf = conf
        self.layers = conf.layers
        self.params: Optional[List[Dict]] = None
        self.state: Optional[List[Dict]] = None
        self.opt_state: Optional[List[Any]] = None
        self.listeners: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self._epoch_batch = 0         # batches consumed in the current epoch
                                      # (persisted in checkpoints → resume
                                      # restarts mid-epoch at the right batch)
        self._score = float("nan")
        self._last_input = None       # last fit batch (activation capture)
        self._rnn_carries = None      # stored state for rnn_time_step
        self._train_step = None
        self._train_step_seq = None
        self._scan_fit = None
        self._output_fn = None
        self._serving = None          # bucketed inference engine (lazy)
        self._transforms = None
        self._fused = None            # fused update plan (nn/fused_update.py)
        self._update_step = None      # standalone donated update program
        self._compile_count = 0       # train programs traced (see _note_compile)
        self._flight = None           # FlightRecorder (monitor/flight.py)
        self._train_mon = None        # lazy TrainMonitor (metric children)
        self._exec = None             # execution core (lazy; exec/executor.py)
        # per-instance caller id for the XLA program registry (/programs):
        # a rebuilt net gets fresh registry rows, never a stale hit
        self._prog_caller = f"mln{next(MultiLayerNetwork._prog_ids)}"

    @property
    def _executor(self):
        """The execution core all compile sites build programs through
        (mesh placement, in/out shardings, donation — docs/SHARDING.md)."""
        if self._exec is None:
            from deeplearning4j_tpu.exec import get_executor
            self._exec = get_executor()
        return self._exec

    # ------------------------------------------------------------------ init
    def init(self, rng=None):
        """Initialize parameters (parity: MultiLayerNetwork.init :541)."""
        gc = self.conf.global_conf
        dtype = _dtype_of(gc.dtype)
        if rng is None:
            rng = jax.random.PRNGKey(gc.seed)
        keys = jax.random.split(rng, max(len(self.layers), 1))
        self.params = [l.init(k, dtype) for l, k in zip(self.layers, keys)]
        self.state = [l.init_state(dtype) for l in self.layers]
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        import json
        from deeplearning4j_tpu.nn.fused_update import (build_fused_update,
                                                        fused_update_enabled)
        gc = self.conf.global_conf
        self._transforms = []
        group_keys = {}
        for i, (l, p) in enumerate(zip(self.layers, self.params)):
            upd = l.updater or gc.updater
            if isinstance(l, FrozenLayer) or not p:
                self._transforms.append(optax.set_to_zero())
                group_keys[i] = None
            else:
                self._transforms.append(make_gradient_transform(upd))
                group_keys[i] = json.dumps(upd.to_dict(), sort_keys=True)
        self.opt_state = [t.init(p) for t, p in zip(self._transforms, self.params)]
        self._fused = None
        if fused_update_enabled():
            self._fused = build_fused_update(
                dict(enumerate(self.params)),
                dict(enumerate(self._transforms)), group_keys,
                {i: l.apply_constraints
                 for i, l in enumerate(self.layers)})
        self._train_step = None  # force re-trace
        self._scan_fit = None
        self._output_fn = None
        self._serving = None
        self._update_step = None

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def attach_flight_recorder(self, recorder):
        """Attach (or detach, with None) a ``monitor.flight.FlightRecorder``.
        The train-step/fit_scan programs re-trace ONCE with the fused
        ``(L, 5)`` telemetry side-output (see monitor/flight.py); detached
        training stays byte-identical to today's path."""
        self._flight = recorder
        if recorder is not None:
            recorder.bind(self)
        self._train_step = None       # force re-trace with/without the
        self._scan_fit = None         # side-output
        return self

    # ----------------------------------------------------------- forward core
    def _compute_dtype(self, train):
        """The forward's compute dtype: the model's own ``compute_dtype``
        when configured, else the executor's train-precision policy (bf16
        compute, f32 accumulation — docs/TRAINING_PERF.md) on the fit path
        of f32 models. None means no cast. Read at trace time."""
        gc = self.conf.global_conf
        if gc.compute_dtype:
            return _dtype_of(gc.compute_dtype)
        if train:
            dt = self._executor.train_dtype
            if dt is not None and _dtype_of(gc.dtype) == jnp.float32:
                return dt
        return None

    def _forward(self, params, state, x, *, train, rng, mask=None, carries=None,
                 upto=None):
        """Pure forward through layers [0, upto). Returns (act, new_states,
        new_carries)."""
        gc = self.conf.global_conf
        cdt = self._compute_dtype(train)
        if cdt is not None:
            x = x.astype(cdt)
            params = _cast_floats(params, cdt)
        n = len(self.layers) if upto is None else upto
        new_states = list(state)
        new_carries = list(carries) if carries is not None else None
        i = 0
        while i < n:
            l = self.layers[i]
            lrng = None if rng is None else jax.random.fold_in(rng, i)
            # consecutive stacked LSTMs fuse into ONE wavefront kernel (the
            # cuDNN numLayers=2 schedule — see ops/lstm_pallas.py); the
            # stateful-carry path (rnn_time_step) stays per-layer
            if (new_carries is None and i + 1 < n and x.ndim == 3):
                from deeplearning4j_tpu.nn.layers.rnn import (
                    lstm_pair_fusable, apply_lstm_pair)
                if lstm_pair_fusable(l, self.layers[i + 1], params[i],
                                     params[i + 1], x, mask):
                    x = apply_lstm_pair(l, self.layers[i + 1],
                                        params[i], params[i + 1], x,
                                        train=train, rng=lrng)
                    i += 2
                    continue
            p_i = params[i]
            if train and l.weight_noise is not None and lrng is not None:
                p_i = l.weight_noise.apply(
                    p_i, jax.random.fold_in(lrng, 0x5eed))
            if new_carries is not None and hasattr(l, "apply_with_carry"):
                x, c = l.apply_with_carry(p_i, x, new_carries[i], mask=mask)
                new_carries[i] = c
            else:
                x, st = l.apply(p_i, x, state[i], train=train, rng=lrng,
                                mask=mask)
                new_states[i] = st if st is not None else state[i]
            if x.ndim == 2:
                mask = None  # sequence collapsed to per-example
            i += 1
        if cdt is not None:
            # keep persistent layer state (e.g. BN running stats) at its
            # storage dtype so dtypes are stable across steps
            new_states = _restore_dtypes(new_states, list(state))
        return x, new_states, new_carries

    def _loss(self, params, state, x, y, rng, mask_f, mask_l, carries=None):
        gc = self.conf.global_conf
        out_layer = self.layers[-1]
        act, new_states, new_carries = self._forward(
            params, state, x, train=True, rng=rng, mask=mask_f, carries=carries,
            upto=len(self.layers) - 1)
        lrng = None if rng is None else jax.random.fold_in(rng, len(self.layers) - 1)
        p_out = params[-1]
        if out_layer.weight_noise is not None and lrng is not None:
            p_out = out_layer.weight_noise.apply(
                p_out, jax.random.fold_in(lrng, 0x5eed))
        if hasattr(out_layer, "compute_score"):
            loss = out_layer.compute_score(p_out, act, y, mask_l,
                                           train=True, rng=lrng)
        else:
            raise ValueError(
                f"Last layer {type(out_layer).__name__} has no loss; use an "
                "OutputLayer/LossLayer variant")
        reg = 0.0
        for l, p in zip(self.layers, params):
            reg = reg + l.reg_loss(p)
        loss = loss + reg
        if self._compute_dtype(True) is not None:
            loss = loss.astype(jnp.float32)
        return loss, (new_states, new_carries)

    def _normalize_grads(self, grads):
        from deeplearning4j_tpu.nn.updaters import normalize_layer_grad
        gc = self.conf.global_conf
        kind = gc.gradient_normalization
        if not kind or kind == "None":
            return grads
        thr = gc.gradient_normalization_threshold
        return [normalize_layer_grad(g, kind, thr) for g in grads]

    # -------------------------------------------- data-parallel protocol
    # Uniform surface used by parallel.wrapper.ParallelWrapper so the wrapper
    # is model-agnostic (parity: reference ParallelWrapper.java:58 accepts any
    # Model). ComputationGraph implements the same three methods.
    def _dp_batch(self, ds):
        """DataSet → canonical (x, y, features_mask, labels_mask)."""
        return (np.asarray(ds.features), np.asarray(ds.labels),
                None if ds.features_mask is None else np.asarray(ds.features_mask),
                None if ds.labels_mask is None else np.asarray(ds.labels_mask))

    def _dp_loss(self, params, state, x, y, rng, pad_mask=None, mf=None,
                 ml=None):
        """Loss with optional per-example zero-weighting of padded rows,
        combined with the DataSet's own masks. pad_mask: (B,) float,
        1=real row / 0=pad. Returns (loss, new_state)."""
        if pad_mask is not None:
            pm = (jnp.broadcast_to(pad_mask[:, None], y.shape[:2])
                  if y.ndim == 3 else pad_mask)
            ml = pm if ml is None else ml * pm
        loss, (new_state, _) = self._loss(params, state, x, y, rng, mf, ml)
        return loss, new_state

    def _dp_apply_updates(self, params, opt_state, grads, fused=None):
        """Normalize grads, run updaters, apply constraints. Default path:
        the fused flat program (nn/fused_update.py — bitwise-equal to the
        per-layer loop below, which remains as the DL4JTPU_FUSED_UPDATE=0
        fallback and the parity oracle). Tensor-parallel callers pass
        ``fused=False``: raveling row- and column-sharded leaves into one
        vector would gather every shard (and trips a GSPMD mis-partition
        on mixed-axis concat) — the per-leaf loop keeps TP placement."""
        grads = self._normalize_grads(grads)
        if fused is None:
            fused = self._executor.model_size <= 1
        if fused and self._fused is not None:
            n = len(params)
            pd, od = self._fused.apply(dict(enumerate(params)),
                                       dict(enumerate(opt_state)),
                                       dict(enumerate(grads)))
            return [pd[i] for i in range(n)], [od[i] for i in range(n)]
        new_params, new_opt = [], []
        for i, (l, t) in enumerate(zip(self.layers, self._transforms)):
            if not params[i]:
                new_params.append(params[i])
                new_opt.append(opt_state[i])
                continue
            u, o = t.update(grads[i], opt_state[i], params[i])
            p = optax.apply_updates(params[i], u)
            new_params.append(l.apply_constraints(p))
            new_opt.append(o)
        return new_params, new_opt

    def _apply_updates_jitted(self):
        """The standalone grad→update→apply program: one compile per
        (model, updater), params + opt-state donated so XLA updates in
        place. External-gradient callers go through this instead of an
        eager per-leaf loop; it traces the same `_dp_apply_updates` math
        the train step embeds."""
        if self._update_step is None:
            def upd(params, opt_state, grads):
                self._note_compile()
                return self._dp_apply_updates(params, opt_state, grads)

            from deeplearning4j_tpu import exec as ex
            self._update_step = self._executor.jit(
                upd, in_specs=(ex.PARAMS, ex.OPT, ex.PARAMS),
                out_specs=(ex.PARAMS, ex.OPT), donate_argnums=(0, 1))
        return self._update_step

    def apply_external_updates(self, grads):
        """One updater step from externally-computed gradients via the
        donated fused-update program (registered as ``apply_updates`` in
        the /programs registry)."""
        step = self._apply_updates_jitted()
        c0, t0 = self._compile_count, time.perf_counter()
        self.params, self.opt_state = step(self.params, self.opt_state,
                                           grads)
        if self._compile_count > c0:
            self._executor.register_program(
                self._prog_caller, "apply_updates", step,
                (self.params, self.opt_state, grads),
                compile_seconds=time.perf_counter() - t0)
        return self

    def _note_compile(self):
        # called from inside jitted train-step bodies: runs only while jit
        # traces a NEW signature, i.e. exactly once per compiled program.
        # Program-registry introspection re-lowers the same body (exec/
        # programs.py) — that re-trace must not count as a fresh compile.
        from deeplearning4j_tpu.exec.programs import is_registering
        if is_registering():
            return
        self._compile_count += 1

    @property
    def _mon(self):
        if self._train_mon is None:
            from deeplearning4j_tpu.monitor.hooks import TrainMonitor
            self._train_mon = TrainMonitor(type(self).__name__)
        return self._train_mon

    # ----------------------------------------------------------- train step
    def _loss_for_grad(self):
        """The differentiated loss: jax.checkpoint-wrapped when remat is
        configured (recompute activations in the backward — faster AND
        smaller for HBM-bound conv models, see GlobalConf.remat)."""
        from deeplearning4j_tpu.util.remat import remat_loss
        return remat_loss(self._loss, self.conf.global_conf.remat)

    def _make_train_step(self, with_masks, with_carries):
        loss_fn = self._loss_for_grad()
        rec = self._flight           # captured at trace-build time: the
        # recorder-off program is byte-identical to the pre-flight path
        sample_k = rec.sample_every if rec is not None else 1

        def step(params, state, opt_state, x, y, it, mask_f, mask_l, carries):
            self._note_compile()
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self.conf.global_conf.seed), it)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, x, y, rng,
                                       mask_f, mask_l, carries)
            new_params, new_opt = self._dp_apply_updates(params, opt_state, grads)
            if rec is None:
                return new_params, new_state, new_opt, loss, new_carries
            from deeplearning4j_tpu.monitor import flight
            telem = flight.step_telemetry(
                flight.telemetry_triples(params, new_params, grads),
                it, sample_k)
            return new_params, new_state, new_opt, loss, new_carries, telem

        from deeplearning4j_tpu import exec as ex
        out_specs = (ex.PARAMS, ex.STATE, ex.OPT, ex.REPL, ex.BATCH)
        if rec is not None:
            out_specs = out_specs + (ex.AUX,)
        return self._executor.jit(
            step,
            in_specs=(ex.PARAMS, ex.STATE, ex.OPT, ex.BATCH, ex.BATCH,
                      ex.REPL, ex.BATCH, ex.BATCH, ex.BATCH),
            out_specs=out_specs,
            donate_argnums=(0, 1, 2))

    def _get_train_step(self, with_masks, with_carries):
        key = (with_masks, with_carries)
        if self._train_step is None:
            self._train_step = {}
        if key not in self._train_step:
            self._train_step[key] = self._make_train_step(*key)
        return self._train_step[key]

    # ------------------------------------------------------------------- fit
    def fit_scan(self, xs, ys):
        """Device-resident training: run ``xs.shape[0]`` train steps inside
        ONE compiled call (lax.scan over a leading step axis), eliminating
        per-step host dispatch — which dominates small-model training,
        especially on tunneled TPU attachments (~ms per dispatch).

        ``xs``: (n_steps, batch, ...) features, ``ys``: (n_steps, batch, ...)
        labels, both device-resident. The reference has no equivalent (its
        fit loop dispatches per minibatch, MultiLayerNetwork.java:1204); this
        is the XLA-idiomatic fast path with identical per-step math."""
        if self.conf.backprop_type == "tbptt":
            raise ValueError(
                "fit_scan runs full-sequence backprop; a net configured for "
                "truncated BPTT must use fit() (the tbptt chunking path)")
        xs, ys = jnp.asarray(xs), jnp.asarray(ys)
        if self._scan_fit is None:
            loss_fn = self._loss_for_grad()
            rec = self._flight       # trace-build capture (see attach)
            sample_k = rec.sample_every if rec is not None else 1

            def inner(params, state, opt_state, xs, ys, it0):
                self._note_compile()

                def body(carry, inp):
                    params, state, opt_state, it = carry
                    x, y = inp
                    rng = jax.random.fold_in(
                        jax.random.PRNGKey(self.conf.global_conf.seed), it)
                    (loss, (new_state, _)), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, state, x, y, rng,
                                               None, None, None)
                    new_params, opt_state = self._dp_apply_updates(
                        params, opt_state, grads)
                    if rec is None:
                        return (new_params, new_state, opt_state,
                                it + 1), loss
                    from deeplearning4j_tpu.monitor import flight
                    telem = flight.step_telemetry(
                        flight.telemetry_triples(params, new_params, grads),
                        it, sample_k)
                    return (new_params, new_state, opt_state, it + 1), \
                        (loss, telem)

                (p, s, o, _), out = jax.lax.scan(
                    body, (params, state, opt_state, it0), (xs, ys))
                if rec is None:
                    return p, s, o, out
                return p, s, o, out[0], out[1]

            from deeplearning4j_tpu import exec as ex
            out_specs = (ex.PARAMS, ex.STATE, ex.OPT, ex.REPL)
            if rec is not None:
                out_specs = out_specs + (ex.AUX,)
            self._scan_fit = self._executor.jit(
                inner,
                in_specs=(ex.PARAMS, ex.STATE, ex.OPT, ex.STEP_BATCH,
                          ex.STEP_BATCH, ex.REPL),
                out_specs=out_specs,
                donate_argnums=(0, 1, 2))
        c0, t0 = self._compile_count, time.perf_counter()
        if self._flight is not None:
            (self.params, self.state, self.opt_state, losses,
             telems) = self._scan_fit(
                self.params, self.state, self.opt_state, xs, ys,
                jnp.asarray(self.iteration, jnp.int32))
            self._flight.record_scan(self.iteration, telems)
        else:
            self.params, self.state, self.opt_state, losses = self._scan_fit(
                self.params, self.state, self.opt_state, xs, ys,
                jnp.asarray(self.iteration, jnp.int32))
        self._last_input = xs[-1]     # device ref for activation capture
        self.iteration += int(xs.shape[0])
        self._epoch_batch += int(xs.shape[0])
        self._score = losses[-1]
        self._mon.record(seconds=time.perf_counter() - t0,
                         steps=int(xs.shape[0]),
                         examples=int(xs.shape[0]) * int(xs.shape[1]),
                         score=self._score,
                         compiled=self._compile_count - c0, path="scan")
        if self._compile_count > c0:
            # fresh XLA program: record its cost/memory analysis so /programs
            # and the bench MFU column read measured numbers, not estimates.
            # Lowering args are the donated call's OUTPUTS (same shapes).
            self._executor.register_program(
                self._prog_caller,
                f"fit_scan_k{int(xs.shape[0])}_b{int(xs.shape[1])}",
                self._scan_fit,
                (self.params, self.state, self.opt_state, xs, ys,
                 jnp.asarray(self.iteration, jnp.int32)),
                compile_seconds=time.perf_counter() - t0)
        if self.listeners:
            with trace.span("callback"):
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration, self.epoch)
        return self

    def fit(self, data, labels=None, epochs=1, prefetch=None,
            checkpoint=None, resume_from=None):
        """fit(x, y) | fit(DataSet) | fit(iterator, epochs=N)
        (parity: MultiLayerNetwork.fit :1156).

        Iterator batches are auto-chunked onto the device-resident scan
        path: runs of mask-free, same-shape batches are stacked and trained
        as ONE compiled multi-step call (``fit_scan``), so plain
        ``fit(iterator)`` gets the same dispatch amortization as callers
        who stage their data manually — per-minibatch host dispatch
        (~ms, and tens of ms on tunneled attachments) otherwise dominates
        small-model training. The per-step math and RNG streams are
        identical (both fold the iteration index into the seed); score
        listeners fire once per chunk instead of once per iteration.
        Masked, tBPTT, or shape-changing batches fall back to single-step
        fits transparently.

        ``prefetch``: device-resident prefetch depth for the streamed path
        (see data/prefetcher.py) — staged work items are device_put ahead
        of consumption so the H2D transfer of chunk k+1 overlaps the step
        for chunk k. ``None`` uses the class default ``prefetch_depth``;
        ``0`` disables (naive path — same math, no overlap). Per-stage
        timing for the last epoch lands in ``self.last_pipeline_stats``.

        ``checkpoint``: crash-safe periodic saves for the duration of this
        call — a ``resilience.CheckpointListener``, or a directory path
        (defaults to save-every-epoch into it). ``resume_from``: a
        checkpoint zip or checkpoint directory (latest taken) — restores
        params/updater/iteration/epoch/epoch-position and continues the
        SAME run bitwise-identically: completed epochs are replayed
        through the iterator (reset + full consumption, so stateful
        shuffles land where the uninterrupted run left them) and the
        partial epoch skips the batches already trained. Requires
        resettable iterator data (docs/FAULT_TOLERANCE.md)."""
        from deeplearning4j_tpu.monitor.profiling import profile_scope

        # DL4JTPU_PROFILE=<dir> wraps the whole call in jax.profiler.trace
        # (docs/OBSERVABILITY.md); unset, this is a plain passthrough
        with profile_scope():
            return self._fit_impl(data, labels, epochs, prefetch,
                                  checkpoint, resume_from)

    def _fit_impl(self, data, labels, epochs, prefetch, checkpoint,
                  resume_from):
        from deeplearning4j_tpu.data.dataset import DataSet

        ckpt = None
        if checkpoint is not None:
            from deeplearning4j_tpu.resilience.checkpoint import (
                CheckpointListener)
            ckpt = (checkpoint if isinstance(checkpoint, CheckpointListener)
                    else CheckpointListener(checkpoint, every_n_epochs=1))
            self.listeners.append(ckpt)
        try:
            if labels is not None or isinstance(data, DataSet):
                if resume_from is not None:
                    raise ValueError(
                        "resume_from needs resettable iterator data; a bare "
                        "array/DataSet fit has no epoch stream to replay")
                return self._fit_batch(data if labels is None
                                       else DataSet(data, labels))
            n_epochs, skip = epochs, 0
            if resume_from is not None:
                if not hasattr(data, "reset"):
                    raise ValueError(
                        "resume_from needs a resettable iterator (reset()) "
                        "to replay the stream to the crash position")
                skip = self._resume_training(resume_from, data)
                n_epochs = max(0, epochs - self.epoch)
            for k in range(n_epochs):
                if hasattr(data, "reset"):
                    data.reset()
                self._fit_stream(data, prefetch=prefetch,
                                 skip_batches=skip if k == 0 else 0)
                self.epoch += 1
                self._epoch_batch = 0
                for lst in self.listeners:
                    if hasattr(lst, "on_epoch_end"):
                        lst.on_epoch_end(self)
            return self
        finally:
            if ckpt is not None:
                self.listeners.remove(ckpt)

    def _resume_training(self, resume_from, data):
        """Restore from a checkpoint and wind the iterator forward to where
        the crashed run stood. Returns the number of batches to skip in the
        first (partial) epoch."""
        import os as _os
        from deeplearning4j_tpu.resilience.checkpoint import latest_checkpoint
        from deeplearning4j_tpu.util.model_serializer import restore_into

        path = _os.fspath(resume_from)
        if _os.path.isdir(path):
            found = latest_checkpoint(path)
            if found is None:
                raise FileNotFoundError(
                    f"resume_from: no checkpoints in directory {path}")
            path = found
        restore_into(self, path)
        # replay completed epochs through the iterator: the uninterrupted
        # run did reset() (fit loop) + ONE iter() (_stream_chunks) + full
        # consumption per epoch — stateful iterators (advancing shuffle
        # RNGs, sampling) must see the identical call sequence to land in
        # the same state. NB `for _ in iter(data)` would call __iter__
        # twice (once explicitly, once by the for protocol) and de-sync a
        # reset-counting shuffle — drive next() by hand instead.
        for _ in range(self.epoch):
            data.reset()
            it = iter(data)
            while True:
                try:
                    next(it)
                except StopIteration:
                    break
        return self._epoch_batch

    # chunk cap: bounded host-side staging memory for the stacked block
    _CHUNK_MAX_STEPS = 64
    _CHUNK_MAX_BYTES = 256 << 20

    def _chunk_len(self, ds):
        per = ds.features.nbytes + ds.labels.nbytes
        return max(1, min(self._CHUNK_MAX_STEPS,
                          self._CHUNK_MAX_BYTES // max(1, per)))

    # device-resident prefetch depth for the streamed fit/eval path: work
    # items are device_put this many batches ahead of consumption so the
    # H2D copy of item k+1 overlaps the compiled step for item k
    # (data/prefetcher.py). 0 = naive path (same math, no overlap).
    prefetch_depth = 2
    # per-stage timing summary of the last streamed fit/eval epoch
    last_pipeline_stats = None

    def _resolve_device_pp(self, data):
        """Split a ``device_side`` pre-processor off the iterator chain:
        returns (dev_fn, host_pp). ``dev_fn`` is the jitted on-chip
        transform (raw — typically uint8 — batches travel host->device and
        the f32 cast/scale runs on chip, see data/normalizers.py);
        ``host_pp`` is the fallback when the transform is not expressible
        device-side (the iterator still emitted the batch raw)."""
        from deeplearning4j_tpu.data.iterators import resolve_pre_processor

        pp = resolve_pre_processor(data)
        dev_fn = host_pp = None
        if pp is not None and getattr(pp, "device_side", False):
            f = pp.as_device_transform()
            if f is not None:
                dev_fn = jax.jit(f)
            else:
                host_pp = pp      # device-side requested but not expressible
        return dev_fn, host_pp

    def _stream_chunks(self, data, host_pp, timer, skip_batches=0):
        """Host-side stage of the streamed fit pipeline: pull batches,
        stack runs of mask-free same-shape batches into scan chunks.
        Yields ``("chunk", (xs, ys))`` stacked host blocks (np arrays) or
        ``("batch", DataSet)`` fallbacks, in base-iterator order — the
        chunk boundaries do not depend on prefetch depth, so the training
        math is bitwise-identical with prefetch on or off."""
        from deeplearning4j_tpu.data.dataset import DataSet

        chunkable = self.conf.backprop_type != "tbptt"
        buf, shape = [], None

        def flush():
            nonlocal buf, shape
            out = None
            if len(buf) == 1:
                out = ("batch", buf[0])
            elif buf:
                with timer.stage("stack"):
                    out = ("chunk", (
                        np.stack([np.asarray(d.features) for d in buf]),
                        np.stack([np.asarray(d.labels) for d in buf])))
            buf, shape = [], None
            return out

        it = iter(data)
        for _ in range(skip_batches):
            # resume path: these batches were already trained before the
            # crash — pull and drop them so the stream (and any iterator
            # RNG) advances exactly as it did in the uninterrupted run
            try:
                next(it)
            except StopIteration:
                return
        while True:
            t0 = time.perf_counter()
            try:
                with trace.span("fetch"):
                    batch = next(it)
            except StopIteration:
                break
            timer.add("fetch", time.perf_counter() - t0)
            ds = batch if isinstance(batch, DataSet) else DataSet(*batch)
            if host_pp is not None:
                with timer.stage("decode"):
                    ds = host_pp.pre_process(ds)
            if (not chunkable or ds.features_mask is not None
                    or ds.labels_mask is not None):
                out = flush()
                if out is not None:
                    yield out
                yield ("batch", ds)
                continue
            key = (ds.features.shape, ds.labels.shape)
            if shape is not None and key != shape:
                out = flush()
                if out is not None:
                    yield out
            shape = key
            buf.append(ds)
            if len(buf) >= self._chunk_len(ds):
                yield flush()
        out = flush()
        if out is not None:
            yield out

    def _fit_stream(self, data, prefetch=None, skip_batches=0):
        """One epoch over an iterator: host chunk assembly → device-resident
        prefetch → compiled steps. While the device executes chunk k, the
        prefetcher has already dispatched the H2D copy of chunk k+1 and the
        host is stacking chunk k+2 — the three pipeline stages overlap
        (the AsyncDataSetIterator adds a fourth: parallel decode).

        Per-stage timing lands in ``self.last_pipeline_stats``; its
        ``host_stall_frac`` is the fraction of epoch wall time the consumer
        loop spent blocked waiting on data."""
        from deeplearning4j_tpu.data.prefetcher import DevicePrefetcher
        from deeplearning4j_tpu.util.timing import PipelineTimer

        dev_fn, host_pp = self._resolve_device_pp(data)
        depth = self.prefetch_depth if prefetch is None else int(prefetch)
        timer = PipelineTimer()
        stream = self._stream_chunks(data, host_pp, timer,
                                     skip_batches=skip_batches)
        if depth > 0:
            stream = DevicePrefetcher(stream, depth=depth, timer=timer)
        it = iter(stream)
        timer.start()
        while True:
            # one "train_step" span per consumer iteration: it nests the
            # wait (and any fetch/h2d work surfaced inside it) + the step
            with trace.span("train_step"):
                with timer.stage("wait"):
                    try:
                        kind, payload = next(it)
                    except StopIteration:
                        break
                with timer.stage("step"):
                    if kind == "chunk":
                        xs, ys = payload
                        xs = jnp.asarray(xs)
                        if dev_fn is not None:
                            xs = dev_fn(xs)
                        self.fit_scan(xs, ys)
                    else:
                        # the fallback path must normalize too — the
                        # iterator intentionally emitted this batch raw
                        # for a device_side pp
                        self._fit_batch(self._apply_dev_pp(payload, dev_fn))
        timer.stop()
        self.last_pipeline_stats = timer.summary()
        timer.publish("fit")

    @staticmethod
    def _apply_dev_pp(ds, dev_fn):
        if dev_fn is None:
            return ds
        from deeplearning4j_tpu.data.dataset import DataSet
        return DataSet(dev_fn(jnp.asarray(ds.features)),
                       ds.labels, ds.features_mask, ds.labels_mask)

    def _fit_batch(self, ds):
        gc = self.conf.global_conf
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        mf = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
        ml = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
        self._last_input = x          # device ref for activation-capture
        c0 = self._compile_count      # listeners (ConvolutionalIteration-
        t0 = time.perf_counter()      # Listener)
        if self.conf.backprop_type == "tbptt" and x.ndim == 3:
            self._fit_tbptt(x, y, mf, ml)
        else:
            step = self._get_train_step(mf is not None or ml is not None, False)
            out = step(
                self.params, self.state, self.opt_state, x, y,
                jnp.asarray(self.iteration, jnp.int32), mf, ml, None)
            self.params, self.state, self.opt_state, loss = out[:4]
            self._score = loss      # device scalar; host-read deferred to
                                    # get_score() (a sync costs ~100ms on
                                    # tunneled TPU attachments)
            if self._flight is not None:
                self._flight.record(self.iteration, out[5])
        self._last_fit_time = time.perf_counter() - t0
        self.iteration += 1
        self._epoch_batch += 1
        self._mon.record(seconds=self._last_fit_time, steps=1,
                         examples=int(x.shape[0]), score=self._score,
                         compiled=self._compile_count - c0, path="batch")
        if self.listeners:
            with trace.span("callback"):
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration, self.epoch)
        return self

    # -------------------------------------------------------------- pretrain
    def pretrain(self, data, epochs: int = 1, lr: float = 0.01):
        """Greedy unsupervised layerwise pretraining of RBM/AutoEncoder/VAE
        layers (parity: MultiLayerNetwork.pretrain :1172 — called before
        supervised fit). ``data``: iterator of DataSets (features used)."""
        from deeplearning4j_tpu.nn.layers.pretrain import get_pretrain_step
        from deeplearning4j_tpu.data.dataset import DataSet

        # a plain generator would be exhausted after the first (layer, epoch)
        # pass — materialize anything we can't reset()
        if not isinstance(data, DataSet) and not hasattr(data, "reset"):
            data = list(data)
        for i, layer in enumerate(self.layers):
            step = get_pretrain_step(layer)
            if step is None:
                continue
            jit_step = jax.jit(step)

            def featurize(x):
                act, _, _ = self._forward(self.params, self.state,
                                          jnp.asarray(x), train=False,
                                          rng=None, upto=i)
                return act

            feat_fn = jax.jit(featurize)
            for ep in range(epochs):
                if hasattr(data, "reset"):
                    data.reset()
                for j, ds in enumerate(data if not isinstance(data, DataSet)
                                       else [data]):
                    if not isinstance(ds, DataSet):
                        ds = DataSet(*ds)
                    x = feat_fn(ds.features)
                    if x.ndim > 2:
                        x = x.reshape(x.shape[0], -1)
                    rng = jax.random.fold_in(
                        jax.random.PRNGKey(self.conf.global_conf.seed),
                        i * 100003 + ep * 1009 + j)
                    self.params[i], loss = jit_step(self.params[i], x, rng,
                                                    jnp.asarray(lr))
                    self._score = loss
        return self

    def _fit_tbptt(self, x, y, mf, ml):
        """Truncated BPTT: slice time into tbptt_fwd_length chunks, carrying
        RNN state across chunks (parity: MultiLayerNetwork.doTruncatedBPTT
        :1219). Truncation is structural: each chunk's step differentiates
        only through its own forward — the carried state enters as a plain
        argument, so no stop_gradient is needed."""
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = [None] * len(self.layers)
        step = self._get_train_step(mf is not None or ml is not None, True)
        losses = []
        telem = None
        for start in range(0, T, L):
            xs = x[:, start:start + L]
            ys = y[:, start:start + L] if y.ndim == 3 else y
            mfs = None if mf is None else mf[:, start:start + L]
            mls = None if ml is None else ml[:, start:start + L]
            out = step(
                self.params, self.state, self.opt_state, xs, ys,
                jnp.asarray(self.iteration, jnp.int32), mfs, mls, carries)
            self.params, self.state, self.opt_state, loss, carries = out[:5]
            if self._flight is not None:
                telem = out[5]      # every chunk shares the iteration —
                                    # the LAST chunk's stats are the record
            losses.append(loss)
        self._score = jnp.mean(jnp.stack(losses))   # device-side mean
        if self._flight is not None and telem is not None:
            self._flight.record(self.iteration, telem)

    # ------------------------------------------------------------- inference
    def serving_engine(self, **kw):
        """The shape-bucketed inference engine for this net (lazy, shared by
        ``output``/``evaluate``; see serving/engine.py). Keyword args are
        honored on first construction only."""
        if self._serving is None:
            from deeplearning4j_tpu.serving.engine import InferenceEngine
            self._serving = InferenceEngine(self, **kw)
        return self._serving

    def output(self, x, train=False, mask=None, bucketed=True):
        """Forward pass to network output (parity: output :1947).

        Default fast path is shape-BUCKETED: the batch is zero-padded up to
        a power-of-two bucket so ⌈log2(max_batch)⌉+1 compiled programs cover
        every request size (each fresh compile is 20-120 s on tunneled TPU
        attachments), with pad rows sliced off after the device call —
        numerically identical because inference computes every output row
        from its own input row alone. ``bucketed=False`` forces the legacy
        exact-shape program (one compile per distinct batch size)."""
        x = jnp.asarray(x)
        if bucketed:
            return self.serving_engine().predict(
                x, None if mask is None else jnp.asarray(mask))
        if self._output_fn is None:
            def fwd(params, state, x, mask):
                act, _, _ = self._forward(params, state, x, train=False,
                                          rng=None, mask=mask)
                return act
            from deeplearning4j_tpu import exec as ex
            self._output_fn = self._executor.jit(
                fwd, in_specs=(ex.PARAMS, ex.STATE, ex.BATCH, ex.BATCH),
                out_specs=(ex.BATCH,))
        return self._output_fn(self.params, self.state, x,
                               None if mask is None else jnp.asarray(mask))

    def feed_forward(self, x, train=False):
        """All layer activations (parity: feedForward :852)."""
        x = jnp.asarray(x)
        acts = [x]
        state = self.state
        for i, l in enumerate(self.layers):
            x, st = l.apply(self.params[i], x, state[i], train=train, rng=None)
            acts.append(x)
        return acts

    def score(self, ds=None, x=None, y=None):
        """Loss on a dataset (parity: MultiLayerNetwork.score)."""
        if ds is not None:
            x, y = ds.features, ds.labels
            mf = ds.features_mask
            ml = ds.labels_mask
        else:
            mf = ml = None
        loss, _ = self._loss(self.params, self.state, jnp.asarray(x),
                             jnp.asarray(y), None,
                             None if mf is None else jnp.asarray(mf),
                             None if ml is None else jnp.asarray(ml))
        return float(loss)

    def get_score(self):
        self._score = float(self._score)   # cache: host read is ~100ms on
        return self._score                 # tunneled TPU attachments

    # ------------------------------------------------------------------ rnn
    def rnn_time_step(self, x):
        """Stateful single/multi-step inference (parity: rnnTimeStep :2362 in
        ComputationGraph / MultiLayerNetwork.java:2209)."""
        x = jnp.asarray(x)
        if x.ndim == 2:
            x = x[:, None, :]
        if self._rnn_carries is None:
            self._rnn_carries = [None] * len(self.layers)
        act, _, self._rnn_carries = self._forward(
            self.params, self.state, x, train=False, rng=None,
            carries=self._rnn_carries)
        return act

    def rnn_clear_previous_state(self):
        self._rnn_carries = None

    # --------------------------------------------------- incremental decode
    def init_decode_state(self, batch: int, max_len: int = 256, kv=None):
        """Per-layer decode state for ``batch`` concurrent streams of up to
        ``max_len`` tokens (serving/decode.py keeps this tree resident on
        device). Recurrent layers contribute their (h, c) carry; attention
        a fixed-capacity KV cache; stateless layers None. ``kv`` — a
        ``{"num_blocks": N, "block_size": bs}`` dict — switches attention
        to the shared block-pool layout (serving/kv/) instead of dense
        per-slot strips."""
        gc = self.conf.global_conf
        dt = _dtype_of(gc.compute_dtype or gc.dtype)
        if kv is not None:
            return [l.init_paged_decode_state(p, batch, max_len,
                                              kv["num_blocks"],
                                              kv["block_size"], dt)
                    for l, p in zip(self.layers, self.params)]
        return [l.init_decode_state(p, batch, max_len, dt)
                for l, p in zip(self.layers, self.params)]

    def decode_step(self, params, state, dstate, x_t, pos,
                    block_tables=None):
        """Pure one-token step through the stack: ``x_t`` (B, 1, F) input
        slice, ``pos`` (B,) int32 per-stream position. Returns
        ``(y, new_dstate)`` — bitwise-equal to position ``pos`` of a full
        teacher-forced ``_forward`` on the same prefix (the compute-dtype
        cast mirrors ``_forward`` exactly so bf16 nets stay bit-identical).
        ``block_tables`` (B, max_blocks) routes attention through the
        paged-KV path; the dense path is byte-identical without it."""
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x_t = x_t.astype(cdt)
            params = _cast_floats(params, cdt)
        x = x_t
        new_d = list(dstate)
        for i, l in enumerate(self.layers):
            st = state[i] if state else None
            if block_tables is None:
                x, new_d[i] = l.decode_step(params[i], dstate[i], x, pos,
                                            state=st)
            else:
                x, new_d[i] = l.decode_step_paged(params[i], dstate[i], x,
                                                  pos, block_tables,
                                                  state=st)
        return x, new_d

    def prefill_chunk(self, params, state, dstate, x, start, n,
                      block_tables=None, carry_stack=False):
        """Advance a prefill chunk through the stack: ``x`` (B, K, F)
        activations for positions ``start .. start+K-1`` per stream, ``n``
        (B,) valid rows (see Layer.prefill_chunk). Same compute-dtype
        handling as ``decode_step``. ``carry_stack=True`` additionally
        returns a per-layer list of carry snapshot stacks (None where the
        layer keeps no carry) for speculative rewind (serving/spec/)."""
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x = x.astype(cdt)
            params = _cast_floats(params, cdt)
        new_d = list(dstate)
        stacks = [None] * len(self.layers)
        for i, l in enumerate(self.layers):
            st = state[i] if state else None
            if carry_stack:
                x, new_d[i], stacks[i] = l.prefill_chunk(
                    params[i], dstate[i], x, start, n, state=st,
                    block_tables=block_tables, carry_stack=True)
            else:
                x, new_d[i] = l.prefill_chunk(params[i], dstate[i], x,
                                              start, n, state=st,
                                              block_tables=block_tables)
        return (x, new_d, stacks) if carry_stack else (x, new_d)

    def tree_chunk(self, params, state, dstate, x, pos0, tree, n,
                   block_tables=None):
        """Score a speculation token tree through the stack: ``x``
        (B, N, F) node activations in ``tree`` (TreeSpec) order, node n
        at stream position ``pos0 + tree.depth[n]`` attending only to
        its root-path (Layer.tree_chunk). Same compute-dtype handling as
        ``decode_step``. Returns ``(y, stacks, kv_windows)`` — per-layer
        node-indexed carry snapshot stacks and uncommitted attention K/V
        windows; ``dstate`` itself is NOT advanced (the verify program
        rewinds carries from the stacks and commits the accepted path
        via ``tree_commit``)."""
        gc = self.conf.global_conf
        if gc.compute_dtype:
            cdt = _dtype_of(gc.compute_dtype)
            x = x.astype(cdt)
            params = _cast_floats(params, cdt)
        stacks = [None] * len(self.layers)
        wins = [None] * len(self.layers)
        for i, l in enumerate(self.layers):
            st = state[i] if state else None
            x, _, stacks[i], wins[i] = l.tree_chunk(
                params[i], dstate[i], x, pos0, tree, n, state=st,
                block_tables=block_tables)
        return x, stacks, wins

    def tree_commit(self, dstate, kv_windows, path, pos0, commit_n,
                    block_tables=None):
        """Write the accepted root-path's positional KV into the decode
        state (Layer.tree_commit); layers without a KV window pass
        through untouched."""
        new_d = list(dstate)
        for i, l in enumerate(self.layers):
            if kv_windows[i] is not None:
                new_d[i] = l.tree_commit(None, dstate[i], kv_windows[i],
                                         path, pos0, commit_n,
                                         block_tables=block_tables)
        return new_d

    # ------------------------------------------------------------- evaluate
    def _eval_stream(self, data, eval_fn):
        """Shared bucketed+pipelined evaluation core: dispatch runs one
        batch ahead of the host read, so the device executes batch k+1
        while ``eval_fn`` consumes batch k (the serving engine's
        predict_stream does the in-flight bookkeeping). ``eval_fn`` gets
        (labels, host_output, labels_mask) per batch.

        Mirrors the fit path's input handling: features are staged onto
        the device ahead of the engine (H2D overlaps the previous batch's
        forward) and a ``device_side`` pre-processor on the iterator chain
        runs on chip here too — a net trained with an on-chip normalizer
        evaluates through the same transform (train/eval parity)."""
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.prefetcher import DevicePrefetcher
        from deeplearning4j_tpu.util.timing import PipelineTimer

        dev_fn, host_pp = self._resolve_device_pp(data)
        eng = self.serving_engine()
        metas = []
        timer = PipelineTimer()

        def feats():
            for ds in data:
                if not isinstance(ds, DataSet):
                    ds = DataSet(*ds)
                if host_pp is not None:
                    ds = host_pp.pre_process(ds)
                metas.append((ds.labels, ds.labels_mask))
                yield ds.features

        staged = DevicePrefetcher(feats(), depth=max(1, self.prefetch_depth),
                                  transform=dev_fn, timer=timer)
        # predict_stream lags ≥1 batch behind feats(), so metas[i] is
        # always populated before output i arrives
        timer.start()
        for i, out in enumerate(eng.predict_stream(staged)):
            labels, lm = metas[i]
            eval_fn(np.asarray(labels), out,
                    None if lm is None else np.asarray(lm))
        timer.stop()
        self.last_pipeline_stats = timer.summary()
        timer.publish("eval")

    def evaluate(self, data, labels=None):
        """Classification evaluation (parity: MultiLayerNetwork.evaluate),
        batches dispatched through the bucketed engine with the host read
        pipelined one batch behind the device."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        from deeplearning4j_tpu.data.dataset import DataSet
        ev = Evaluation()
        if labels is not None:
            data = [DataSet(data, labels)]
        elif isinstance(data, DataSet):
            data = [data]
        elif hasattr(data, "reset"):
            data.reset()
        self._eval_stream(data, ev.eval)
        return ev

    def evaluate_regression(self, data):
        from deeplearning4j_tpu.eval.evaluation import RegressionEvaluation
        from deeplearning4j_tpu.data.dataset import DataSet
        ev = RegressionEvaluation()
        if isinstance(data, DataSet):
            data = [data]
        elif hasattr(data, "reset"):
            data.reset()
        self._eval_stream(data,
                          lambda y, out, _lm: ev.eval(y, out))
        return ev

    # ------------------------------------------------------------- utilities
    def num_params(self):
        return sum(int(np.prod(a.shape)) for a in
                   jax.tree_util.tree_leaves(self.params))

    def summary(self):
        lines = ["=" * 70,
                 f"{'Layer':<30}{'Type':<25}{'Params':>12}", "=" * 70]
        for i, (l, p) in enumerate(zip(self.layers, self.params)):
            n = sum(int(np.prod(a.shape)) for a in jax.tree_util.tree_leaves(p))
            name = l.name or f"layer_{i}"
            lines.append(f"{name:<30}{type(l).__name__:<25}{n:>12,}")
        lines.append("=" * 70)
        lines.append(f"Total params: {self.num_params():,}")
        return "\n".join(lines)

    def clone(self):
        import copy as _copy
        net = MultiLayerNetwork(MultiLayerConfiguration.from_json(self.conf.to_json()))
        if self.params is not None:
            net.params = jax.tree_util.tree_map(lambda a: a, self.params)
            net.state = jax.tree_util.tree_map(lambda a: a, self.state)
            net._build_optimizer()
        return net

    # persistence shortcuts (full impl in util/model_serializer.py)
    def save(self, path, save_updater=True):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(self, path, save_updater)

    @staticmethod
    def load(path, load_updater=True):
        from deeplearning4j_tpu.util.model_serializer import restore_multi_layer_network
        return restore_multi_layer_network(path, load_updater)
