"""Weight-only low-precision serving: per-channel symmetric quantization.

The serve-side hot paths (bucketed forward, continuous-batching decode
step) are memory-bound: every device call streams the full weight tree
from HBM. Weight-only quantization (the LLM.int8() observation, Dettmers
et al. 2022) cuts that traffic ~4x by storing weights as int8 (or
fp8-e4m3) codes plus one f32 scale per OUTPUT channel, and dequantizing
on the fly INSIDE the compiled program — XLA fuses the
``codes.astype(f32) * scale`` expansion into the consuming matmul, so
the f32 activation math is unchanged and only the weight bytes shrink.

Two precisions, one mechanism:

- ``int8``: codes in [-127, 127], ``scale = amax / 127`` per channel.
  ~0.25x weight bytes; typical per-layer max-abs-err ~amax/254.
- ``fp8``: ``jnp.float8_e4m3fn`` codes (max finite 448), ``scale =
  amax / 448``. Same bytes as int8 but a floating mantissa: relative
  error is roughly uniform across magnitudes instead of absolute.
- ``f32``: the identity policy. ``quantize_tree`` returns the tree
  UNTOUCHED (same array objects), so the f32 serving path stays
  bitwise-identical and compiles the exact same programs.

Per-channel means per OUTPUT channel — the LAST axis of a kernel
(``(n_in, n_out)`` dense, ``(kh, kw, cin, cout)`` conv, the gate-stacked
``(n_in, 4*n_out)`` LSTM input kernel). A per-last-axis scale commutes
with the matmul's contraction (every contracted element of a column
shares one scale), which is what keeps dequant-on-the-fly exact up to
the rounding already paid at quantize time.

Policy (what quantizes): float leaves with ``ndim >= 2`` whose path
matches no entry of the exclusion list. Biases, norm gains/shifts and
other 1-D leaves stay f32 — they are a rounding error of the byte
budget and quantizing them buys nothing. The default exclusion list is
empty; pass ``exclude=("P",)`` etc. to keep e.g. positional embeddings
full-precision (docs/QUANTIZATION.md).

``QTensor`` is a registered pytree, so quantized trees flow through
``Executor.jit`` unchanged: the codes and scales become ordinary device
arrays of the program, jit signatures key on their dtypes, and swapping
a same-shape quantized tree hits the compiled-program cache exactly
like an f32 swap (the zero-new-compiles invariant the serving tests
pin).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

PRECISIONS = ("f32", "int8", "fp8")

# fp8-e4m3 (fn variant): max finite magnitude
_FP8_MAX = 448.0


def resolve_precision(precision: Optional[str]) -> str:
    """Normalize/validate a precision name (None → 'f32')."""
    p = (precision or "f32").strip().lower()
    aliases = {"float32": "f32", "fp32": "f32", "none": "f32",
               "i8": "int8", "e4m3": "fp8", "fp8_e4m3": "fp8",
               "float8": "fp8"}
    p = aliases.get(p, p)
    if p not in PRECISIONS:
        raise ValueError(
            f"unknown precision {precision!r} (want one of {PRECISIONS})")
    return p


@jax.tree_util.register_pytree_node_class
@dataclass(frozen=True)
class QTensor:
    """One quantized weight: ``codes`` (int8 / fp8 array, original shape)
    and ``scale`` (f32, shape broadcastable as one scale per last-axis
    channel). ``dequantize(qt)`` reconstructs f32."""

    codes: jnp.ndarray
    scale: jnp.ndarray

    # pytree protocol: codes+scale are children, so quantized trees pass
    # through jit/device_put/tree_map like any other weight tree
    def tree_flatten(self):
        return (self.codes, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.codes.shape

    @property
    def dtype(self):
        return self.codes.dtype

    @property
    def nbytes(self) -> int:
        return (self.codes.size * self.codes.dtype.itemsize
                + self.scale.size * self.scale.dtype.itemsize)


def _is_q(x) -> bool:
    return isinstance(x, QTensor)


def _channel_amax(w):
    """max|w| per last-axis channel, keepdims — one scale per output
    channel, broadcastable against ``w``."""
    axes = tuple(range(w.ndim - 1))
    return jnp.max(jnp.abs(w), axis=axes, keepdims=True)


def quantize(w, precision: str) -> QTensor:
    """Per-channel symmetric quantization of one ``ndim>=2`` float array."""
    w = jnp.asarray(w, jnp.float32)
    amax = _channel_amax(w)
    if precision == "int8":
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        codes = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    elif precision == "fp8":
        scale = jnp.where(amax > 0, amax / _FP8_MAX, 1.0)
        codes = (w / scale).astype(jnp.float8_e4m3fn)
    else:
        raise ValueError(f"quantize() wants int8/fp8, got {precision!r}")
    return QTensor(codes, scale.astype(jnp.float32))


def dequantize(qt: QTensor):
    """f32 reconstruction. Inside a jitted forward this is the
    dequant-on-the-fly expansion XLA fuses into the consuming matmul."""
    return qt.codes.astype(jnp.float32) * qt.scale


def _eligible(path: str, leaf, exclude: Sequence[str]) -> bool:
    if _is_q(leaf) or getattr(leaf, "ndim", 0) < 2:
        return False
    if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
        return False
    return not any(tok in path for tok in exclude)


def quantize_tree(tree, precision: str, exclude: Sequence[str] = ()):
    """Quantize every eligible leaf of a weight pytree; 'f32' returns the
    tree unchanged (same objects — the bitwise-identity policy)."""
    precision = resolve_precision(precision)
    if precision == "f32":
        return tree

    def q(path, leaf):
        key = jax.tree_util.keystr(path)
        return quantize(leaf, precision) if _eligible(key, leaf, exclude) \
            else leaf
    return jax.tree_util.tree_map_with_path(q, tree)


def dequantize_tree(tree):
    """Reconstruct f32 leaves from any QTensor nodes; plain leaves pass
    through untouched, so on an f32 tree this is the identity (zero ops
    traced — the f32 path compiles the exact same program)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x) if _is_q(x) else x, tree,
        is_leaf=_is_q)


def tree_bytes(tree) -> int:
    """Total weight bytes of a (possibly quantized) tree — codes + scales
    for QTensor leaves, raw array bytes otherwise."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_q):
        if _is_q(leaf):
            total += leaf.nbytes
        else:
            a = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
            total += int(np.prod(a.shape, dtype=np.int64)
                         * jnp.dtype(a.dtype).itemsize) if a.ndim else \
                jnp.dtype(a.dtype).itemsize
    return int(total)


def quant_error_report(tree, qtree) -> dict:
    """Per-leaf max-abs-err of a quantized tree vs its f32 source — the
    quality check docs/QUANTIZATION.md's accuracy bars are stated over.
    Returns {path: err} for quantized leaves plus ``"max"`` (worst leaf)
    and ``"rel_max"`` (worst err / amax)."""
    report, worst, worst_rel = {}, 0.0, 0.0
    flat = {jax.tree_util.keystr(p): l for p, l
            in jax.tree_util.tree_flatten_with_path(tree)[0]}
    qflat = {jax.tree_util.keystr(p): l for p, l
             in jax.tree_util.tree_flatten_with_path(
                 qtree, is_leaf=_is_q)[0]}
    for key, ql in qflat.items():
        if not _is_q(ql):
            continue
        w = np.asarray(flat[key], np.float32)
        err = float(np.max(np.abs(w - np.asarray(dequantize(ql)))))
        amax = float(np.max(np.abs(w)))
        report[key] = err
        worst = max(worst, err)
        if amax > 0:
            worst_rel = max(worst_rel, err / amax)
    report["max"] = worst
    report["rel_max"] = worst_rel
    return report


# ------------------------------------------------------------------ metrics
def record_weight_bytes(engine: str, precision: str, nbytes: int) -> None:
    """Publish ``dl4jtpu_weight_bytes{engine, precision}`` (the serving
    tier's resident weight footprint; OBSERVABILITY.md catalog)."""
    from deeplearning4j_tpu.monitor import get_registry
    get_registry().gauge(
        "dl4jtpu_weight_bytes",
        "Device-resident serving weight bytes per engine and precision "
        "(codes + scales for quantized trees).",
        ("engine", "precision")).labels(
            engine=engine, precision=precision).set(float(nbytes))


def record_accuracy_delta(engine: str, delta: float) -> None:
    """Publish ``dl4jtpu_quant_accuracy_delta{engine}`` — (quantized −
    f32) end-to-end eval accuracy, set by the quality checks / bench."""
    from deeplearning4j_tpu.monitor import get_registry
    get_registry().gauge(
        "dl4jtpu_quant_accuracy_delta",
        "End-to-end eval accuracy delta of the quantized serving path vs "
        "f32 (0 when serving f32).", ("engine",)).labels(
            engine=engine).set(float(delta))
