"""Low-precision serving: weight-only int8/fp8 quantization.

One module owns the mechanism (``qtensor.py``); the policy surface is
``exec.Executor(precision=...)`` / ``DL4JTPU_PRECISION`` — the engines
(serving/engine.py, serving/decode.py) quantize at load/swap time and
dequantize on the fly inside their compiled programs. See
docs/QUANTIZATION.md.
"""

from deeplearning4j_tpu.quant.qtensor import (  # noqa: F401
    PRECISIONS, QTensor, dequantize, dequantize_tree, quant_error_report,
    quantize, quantize_tree, record_accuracy_delta, record_weight_bytes,
    resolve_precision, tree_bytes)

__all__ = [
    "PRECISIONS", "QTensor", "quantize", "dequantize",
    "quantize_tree", "dequantize_tree", "tree_bytes",
    "quant_error_report", "resolve_precision",
    "record_weight_bytes", "record_accuracy_delta",
]
