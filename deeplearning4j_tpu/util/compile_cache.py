"""Persistent XLA compilation cache setup.

On tunneled TPU attachments every compile is a remote RPC (~20-120 s per
program, occasionally failing transiently); the persistent cache is
verified to hit across processes in this environment, so a pre-warmed
cache directory makes later runs (benchmarks, artifact training, the
driver's recorded bench) pay ~0 compile time.
"""

from __future__ import annotations

import os
from pathlib import Path


def setup_compile_cache(cache_dir=None) -> str:
    """Point JAX at a persistent compilation cache directory (idempotent).

    Resolution: explicit arg > ``DL4JTPU_JAX_CACHE`` env > ``.jax_cache``
    at the repo root. Returns the directory used."""
    d = (cache_dir or os.environ.get("DL4JTPU_JAX_CACHE")
         or str(Path(__file__).resolve().parents[2] / ".jax_cache"))
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    try:
        # scrapeable cache size: live callback gauges, evaluated only when
        # /metrics is actually pulled (a directory walk per scrape)
        from deeplearning4j_tpu.monitor.metrics import get_registry
        reg = get_registry()
        reg.gauge("dl4jtpu_compile_cache_entries",
                  "Files in the persistent XLA compilation cache."
                  ).set_function(lambda: cache_stats(d)["entries"])
        reg.gauge("dl4jtpu_compile_cache_bytes",
                  "Total bytes of the persistent XLA compilation cache."
                  ).set_function(lambda: cache_stats(d)["bytes"])
    except Exception:
        pass
    return str(d)


def cache_stats(cache_dir=None) -> dict:
    """Entry count + total bytes of the persistent cache directory (the
    serving /stats surface: lets an operator confirm a warmed process will
    really serve its first request compile-free). Safe before setup — an
    absent directory reports zero entries."""
    d = Path(cache_dir or os.environ.get("DL4JTPU_JAX_CACHE")
             or Path(__file__).resolve().parents[2] / ".jax_cache")
    entries = bytes_ = 0
    if d.is_dir():
        for p in d.rglob("*"):
            if p.is_file():
                entries += 1
                bytes_ += p.stat().st_size
    return {"dir": str(d), "entries": entries, "bytes": bytes_}
