"""Persistent XLA compilation cache setup.

On tunneled TPU attachments every compile is a remote RPC (~20-120 s per
program, occasionally failing transiently); the persistent cache is
verified to hit across processes in this environment, so a pre-warmed
cache directory makes later runs (benchmarks, artifact training, the
driver's recorded bench) pay ~0 compile time.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

# per-directory (walk_time, stats) — a warmed cache holds thousands of
# files, and /metrics scrapes two gauges off it; full rglob per scrape
# would put a directory walk on the monitoring hot path
_stats_cache: dict = {}


def setup_compile_cache(cache_dir=None) -> str:
    """Point JAX at a persistent compilation cache directory (idempotent).

    Resolution: explicit arg > ``DL4JTPU_JAX_CACHE`` env > ``.jax_cache``
    at the repo root. Returns the directory used."""
    d = (cache_dir or os.environ.get("DL4JTPU_JAX_CACHE")
         or str(Path(__file__).resolve().parents[2] / ".jax_cache"))
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", str(d))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    try:
        # scrapeable cache size: live callback gauges, evaluated only when
        # /metrics is actually pulled (a directory walk per scrape)
        from deeplearning4j_tpu.monitor.metrics import get_registry
        reg = get_registry()
        reg.gauge("dl4jtpu_compile_cache_entries",
                  "Files in the persistent XLA compilation cache."
                  ).set_function(lambda: cache_stats(d)["entries"])
        reg.gauge("dl4jtpu_compile_cache_bytes",
                  "Total bytes of the persistent XLA compilation cache."
                  ).set_function(lambda: cache_stats(d)["bytes"])
    except Exception:
        pass
    return str(d)


def cache_stats(cache_dir=None, ttl: float = 5.0) -> dict:
    """Entry count + total bytes of the persistent cache directory (the
    serving /stats surface: lets an operator confirm a warmed process will
    really serve its first request compile-free). Safe before setup — an
    absent directory reports zero entries.

    The walk is memoized for ``ttl`` seconds per directory so back-to-back
    /metrics scrapes of a large warmed cache don't each pay a full
    ``rglob``; ``ttl=0`` forces a fresh walk."""
    d = Path(cache_dir or os.environ.get("DL4JTPU_JAX_CACHE")
             or Path(__file__).resolve().parents[2] / ".jax_cache")
    key = str(d)
    now = time.monotonic()
    hit = _stats_cache.get(key)
    if hit is not None and ttl > 0 and now - hit[0] < ttl:
        return dict(hit[1])
    entries = bytes_ = 0
    if d.is_dir():
        for p in d.rglob("*"):
            if p.is_file():
                entries += 1
                bytes_ += p.stat().st_size
    stats = {"dir": key, "entries": entries, "bytes": bytes_}
    _stats_cache[key] = (now, stats)
    return dict(stats)
