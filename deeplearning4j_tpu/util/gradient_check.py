"""Numeric gradient checking.

Parity surface: reference gradientcheck/GradientCheckUtil.java:57 — the
correctness backbone of the test suite (13 gradient-check suites,
SURVEY.md §4). Compares ``jax.grad`` analytic gradients against central
finite differences parameter-by-parameter.

Checks run in float64 on CPU (jax.enable_x64 inside) because finite
differences at eps=1e-6 drown in float32 rounding — same reason the
reference forces DOUBLE data type in its gradient-check tests.
"""

from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp


@contextlib.contextmanager
def _x64():
    """Scope float64 to the check (central differences at eps~1e-6 cancel
    catastrophically in float32; the reference similarly forces
    DataBuffer.Type.DOUBLE in its gradient-check suites). A process-global
    ``jax.config.update`` would leak x64 defaults into every test imported
    after this module — the context manager keeps it local. (Lives under
    jax.experimental since jax 0.4.31; the top-level alias is gone.)"""
    from jax.experimental import enable_x64
    with enable_x64():
        yield


def gradient_check_fn(loss_fn, params, eps=1e-6, max_rel_error=1e-3,
                      min_abs_error=1e-8, max_checks_per_array=25, seed=0,
                      verbose=False):
    """Check d loss_fn / d params via central differences (in scoped x64).

    loss_fn: params_pytree -> scalar. Must be pure.
    Returns (n_failures, n_checked, max_rel_err_seen).
    """
    with _x64():
        return _gradient_check_fn_x64(loss_fn, params, eps, max_rel_error,
                                      min_abs_error, max_checks_per_array,
                                      seed, verbose)


def _gradient_check_fn_x64(loss_fn, params, eps, max_rel_error,
                           min_abs_error, max_checks_per_array, seed,
                           verbose):
    # upcast float params HERE, inside the x64 scope — callers can pass f32
    # pytrees without caring about the x64 state of their own context
    params = jax.tree_util.tree_map(
        lambda a: (jnp.asarray(a, jnp.float64)
                   if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating)
                   else jnp.asarray(a)), params)
    for leaf in jax.tree_util.tree_leaves(params):
        if jnp.issubdtype(leaf.dtype, jnp.floating) and \
                leaf.dtype != jnp.float64:
            # x64 must actually be enabled here or the whole check silently
            # runs at f32 against its own design (parity:
            # GradientCheckUtil.java:57 forces DOUBLE)
            raise RuntimeError(
                f"gradient check requires f64 but got {leaf.dtype}; "
                "is jax.enable_x64 active?")
    loss_fn = jax.jit(loss_fn)  # compile once; FD loop then runs fast
    grads = jax.jit(jax.grad(loss_fn))(params)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    gleaves = jax.tree_util.tree_flatten(grads)[0]
    rng = np.random.RandomState(seed)

    failures = 0
    checked = 0
    worst = 0.0
    for li, (leaf, gleaf) in enumerate(zip(leaves, gleaves)):
        arr = np.array(leaf, np.float64)  # copy: jax buffers are read-only
        ganalytic = np.asarray(gleaf, np.float64)
        n = arr.size
        idxs = (np.arange(n) if n <= max_checks_per_array
                else rng.choice(n, max_checks_per_array, replace=False))
        for i in idxs:
            orig = arr.flat[i]
            arr.flat[i] = orig + eps
            leaves2 = list(leaves)
            leaves2[li] = jnp.asarray(arr, leaf.dtype)
            plus = float(loss_fn(jax.tree_util.tree_unflatten(treedef, leaves2)))
            arr.flat[i] = orig - eps
            leaves2[li] = jnp.asarray(arr, leaf.dtype)
            minus = float(loss_fn(jax.tree_util.tree_unflatten(treedef, leaves2)))
            arr.flat[i] = orig
            numeric = (plus - minus) / (2 * eps)
            analytic = ganalytic.flat[i]
            denom = abs(numeric) + abs(analytic)
            abs_err = abs(numeric - analytic)
            rel = abs_err / denom if denom > 0 else 0.0
            checked += 1
            if rel > max_rel_error and abs_err > min_abs_error:
                failures += 1
                if verbose:
                    print(f"  leaf {li} idx {i}: analytic={analytic:.3e} "
                          f"numeric={numeric:.3e} rel={rel:.3e}")
            worst = max(worst, rel if abs_err > min_abs_error else 0.0)
    return failures, checked, worst


def gradient_check_network(net, x, y, eps=1e-5, max_rel_error=1e-3,
                           min_abs_error=1e-7, max_checks_per_array=20,
                           verbose=False):
    """Gradient-check a MultiLayerNetwork's full loss (incl. l1/l2) wrt all
    params (parity: GradientCheckUtil.checkGradients)."""
    with _x64():
        x = jnp.asarray(x, jnp.float64) if x.dtype != np.int32 \
            else jnp.asarray(x)
        y = jnp.asarray(y, jnp.float64)
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, jnp.float64), net.params)

        def loss_fn(params):
            loss, _ = net._loss(params, net.state, x, y, None, None, None)
            return loss

        return _gradient_check_fn_x64(loss_fn, params64, eps, max_rel_error,
                                      min_abs_error, max_checks_per_array,
                                      0, verbose)
