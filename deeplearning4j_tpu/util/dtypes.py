"""Mixed-precision pytree helpers shared by both network containers.

The containers' ``compute_dtype`` contract: master params and persistent
layer state (e.g. batchnorm running stats) are stored in the configured
storage dtype (f32 by default); forward/backward run in the compute dtype
(params cast at forward entry — grads come back in the storage dtype through
the autodiff of the cast); state written back keeps its storage dtype so
shapes/dtypes are stable across steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cast_floats(tree, dt):
    """Cast floating leaves of a pytree to ``dt``."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dt)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a, tree)


def restore_dtypes(new_tree, old_tree):
    """Leaf-wise: cast ``new_tree`` back to ``old_tree``'s dtypes (persistent
    state keeps its storage dtype under mixed-precision compute)."""
    return jax.tree_util.tree_map(
        lambda new, old: new.astype(old.dtype)
        if hasattr(new, "dtype") and hasattr(old, "dtype") else new,
        new_tree, old_tree)
