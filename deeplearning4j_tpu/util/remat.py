"""Shared backward-rematerialization dispatch for the network containers.

See GlobalConf.remat (nn/conf/configuration.py) for the modes and
docs/PERF_R05.md for the measurements behind them.
"""

from __future__ import annotations

import jax


def remat_loss(loss_fn, mode):
    """``loss_fn`` wrapped per the configured remat ``mode``:
    False → unchanged; True/'full' → jax.checkpoint;
    'save_convs'/'selective' → checkpoint saving only named conv outputs
    (ConvolutionLayer tags them "conv_out")."""
    if not mode:
        return loss_fn
    if mode in (True, "full"):
        return jax.checkpoint(loss_fn)
    if mode in ("save_convs", "selective"):
        return jax.checkpoint(
            loss_fn,
            policy=jax.checkpoint_policies.save_only_these_names("conv_out"))
    raise ValueError(f"unknown remat mode {mode!r} "
                     "(False | True | 'full' | 'save_convs')")
