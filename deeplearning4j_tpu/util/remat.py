"""Shared backward-rematerialization dispatch for the network containers.

See GlobalConf.remat (nn/conf/configuration.py) for the modes and
docs/PERF_R05.md for the measurements behind them.
"""

from __future__ import annotations

import jax

_MODES = (False, True, "full", "save_convs", "selective")


def check_remat_mode(mode):
    """Fail fast on an invalid mode (builder/zoo entry points call this so
    a typo surfaces at configuration time, not at the first train step)."""
    if mode not in _MODES:
        raise ValueError(
            f"unknown remat mode {mode!r} "
            "(False | True | 'full' | 'save_convs' | 'selective')")
    return mode


def remat_loss(loss_fn, mode):
    """``loss_fn`` wrapped per the configured remat ``mode``:
    False → unchanged; True/'full' → jax.checkpoint;
    'save_convs'/'selective' → checkpoint saving only named conv outputs
    (ConvolutionLayer tags them "conv_out")."""
    if not mode:
        return loss_fn
    if mode in (True, "full"):
        return jax.checkpoint(loss_fn)
    if mode in ("save_convs", "selective"):
        return jax.checkpoint(
            loss_fn,
            policy=jax.checkpoint_policies.save_only_these_names("conv_out"))
    check_remat_mode(mode)                     # raises; not a known mode
    raise AssertionError("unreachable")
