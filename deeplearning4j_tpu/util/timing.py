"""Honest device timing under high-latency dispatch tunnels.

Some TPU attachment paths (e.g. the axon tunnel used in this environment)
have two properties that break naive benchmarking:

- ``jax.block_until_ready`` returns immediately (async dispatch is not
  awaited), so ``time(dispatch loop) + block_until_ready`` measures only
  Python enqueue time;
- a device→host read is a fixed-latency RPC (~100 ms here), so timing a
  single op by reading its result measures the tunnel, not the op.

``time_op`` solves both: the op runs N times inside ONE jitted
``lax.fori_loop`` (iterations chained with a negligible 1e-30-scaled data
dependency so XLA cannot hoist the body), completion is forced by a scalar
host read, and the fixed RPC cost is removed by differencing against an
N=1 run. N is chosen adaptively so the measured delta dominates RPC jitter.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import numpy as np

from deeplearning4j_tpu.monitor.tracing import trace


class PipelineTimer:
    """Per-stage input-pipeline accounting (fetch / decode / h2d / step).

    The containers' streamed fit path records how long the consumer loop
    spends in each stage; ``host_stall_frac()`` is the fraction of the
    epoch's wall time the host spent WAITING ON DATA instead of dispatching
    device work — the number that caps accelerator utilization once the
    compiled step is fast (un-pipelined input feeding, not FLOPs).

    Stage conventions used by ``_fit_stream``:

    - ``wait``  — consumer blocked in ``next()`` on the input stream. With
      the prefetch pipeline on, this is the ONLY stall the host sees (the
      fetch/decode/h2d work happens inside it or ahead of it).
    - ``fetch`` / ``decode`` / ``h2d`` — informative sub-stage costs
      recorded by the stream/prefetcher; they may be nested inside ``wait``
      so they are NOT summed into the stall when ``wait`` was recorded.
    - ``step`` — train-step dispatch (async on TPU: enqueue time, not
      device time; honest device step timing is ``time_op`` below).

    ``host_stall_frac`` = wait/wall when ``wait`` was recorded, else
    (fetch+decode+h2d)/wall (the naive un-pipelined path executes those
    stages inline on the consumer thread)."""

    _STALL_FALLBACK = ("fetch", "decode", "h2d")

    def __init__(self):
        self.seconds = {}
        self.counts = {}
        self._t0 = None
        self.wall = 0.0

    def add(self, stage: str, sec: float):
        self.seconds[stage] = self.seconds.get(stage, 0.0) + sec
        self.counts[stage] = self.counts.get(stage, 0) + 1

    @contextmanager
    def stage(self, name: str):
        # every timed stage is also a trace span (no-op while tracing is
        # off), so the Perfetto timeline and the stage totals agree
        with trace.span(name):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                self.add(name, time.perf_counter() - t0)

    def start(self):
        self._t0 = time.perf_counter()
        return self

    def stop(self):
        if self._t0 is not None:
            self.wall += time.perf_counter() - self._t0
            self._t0 = None
        return self

    def host_stall_frac(self):
        if not self.wall:
            return None
        if "wait" in self.seconds:
            stall = self.seconds["wait"]
        else:
            stall = sum(self.seconds.get(s, 0.0)
                        for s in self._STALL_FALLBACK)
        return min(1.0, stall / self.wall)

    def summary(self) -> dict:
        out = {"wall_sec": round(self.wall, 4),
               "host_stall_frac": self.host_stall_frac()}
        if out["host_stall_frac"] is not None:
            out["host_stall_frac"] = round(out["host_stall_frac"], 4)
        for k in sorted(self.seconds):
            out[f"{k}_sec"] = round(self.seconds[k], 4)
        return out

    def publish(self, path: str):
        """Flow this timer's stage totals into the process-wide
        MetricsRegistry so ``host_stall_frac`` and per-stage seconds are
        scrapeable at ``/metrics``. ``path`` labels the pipeline ("fit" /
        "eval"). Stage counters accumulate across epochs; the stall
        fraction gauge holds the LAST epoch's value."""
        from deeplearning4j_tpu.monitor.metrics import get_registry
        reg = get_registry()
        fam = reg.counter(
            "dl4jtpu_pipeline_stage_seconds_total",
            "Cumulative input-pipeline stage seconds (see PipelineTimer "
            "stage conventions).", ("path", "stage"))
        for stage, sec in self.seconds.items():
            fam.labels(path=path, stage=stage).inc(sec)
        reg.counter(
            "dl4jtpu_pipeline_wall_seconds_total",
            "Cumulative wall seconds of streamed fit/eval epochs.",
            ("path",)).labels(path=path).inc(self.wall)
        frac = self.host_stall_frac()
        if frac is not None:
            reg.gauge(
                "dl4jtpu_pipeline_host_stall_frac",
                "Fraction of the last epoch's wall time the host spent "
                "blocked waiting on data.",
                ("path",)).labels(path=path).set(frac)
        return self


def host_sync(x) -> float:
    """Force completion of ``x`` by reading one scalar to the host."""
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(np.asarray(jax.device_get(leaf)).ravel()[0])


def _chained_loop(fn, iters):
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def loop(*args):
        def body(_, carry):
            s, = carry
            out = fn(args[0] + s, *args[1:])
            leaf = jax.tree_util.tree_leaves(out)[0]
            return (jnp.asarray(leaf, jnp.float32).ravel()[0] * 1e-30,)
        return lax.fori_loop(0, iters, body, (jnp.float32(0),))[0]

    return loop


def _run(loop, args, repeats=3):
    best = float("inf")
    host_sync(loop(*args))                    # compile + warm
    for _ in range(repeats):
        t0 = time.perf_counter()
        host_sync(loop(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def time_op(fn, *args, target_s: float = 0.15, pilot_iters: int = 128,
            max_iters: int = 8192, repeats: int = 3) -> float:
    """Seconds per execution of ``fn(*args)`` on device.

    ``fn``'s first argument must be an array (it carries the chaining
    perturbation); its output may be any pytree of arrays.
    """
    t1 = _run(_chained_loop(fn, 1), args, repeats)
    n = pilot_iters
    tn = _run(_chained_loop(fn, n), args, repeats)
    delta = tn - t1
    if delta < target_s / 2:
        n2 = min(max_iters, max(n * 2, int(n * target_s / max(delta, 1e-3))))
        if n2 > n:
            n = n2
            tn = _run(_chained_loop(fn, n), args, repeats)
            delta = tn - t1
    return max(delta, 1e-9) / (n - 1)


def time_python_loop(step, n_steps: int, sync) -> float:
    """Seconds per step of a Python-level training loop with RPC-latency
    differencing: run ``step`` once + sync, then ``n_steps`` times + sync,
    return the per-step delta. ``step(i)`` must chain state internally;
    ``sync()`` must host-read something produced by the last step."""
    step(0)
    sync()                                     # warm / ensure compiled
    t0 = time.perf_counter()
    step(0)
    sync()
    t_one = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(n_steps):
        step(i)
    sync()
    t_n = time.perf_counter() - t0
    return max(t_n - t_one, 1e-9) / (n_steps - 1)
