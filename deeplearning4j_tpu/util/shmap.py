"""shard_map import shim.

jax >= 0.7 exposes ``jax.shard_map`` (keyword ``check_vma``); older releases
only have ``jax.experimental.shard_map.shard_map`` whose equivalent keyword
is ``check_rep`` — a bare re-import would make every ``check_vma=`` call
site TypeError on exactly the versions the fallback exists for, so the
legacy path adapts the kwarg.
"""

from __future__ import annotations

try:
    from jax import shard_map  # noqa: F401
except ImportError:  # pragma: no cover — legacy jax
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f=None, **kwargs):  # type: ignore[misc]
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda fn: _legacy_shard_map(fn, **kwargs)
        return _legacy_shard_map(f, **kwargs)
