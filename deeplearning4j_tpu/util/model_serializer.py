"""Model persistence — zip checkpoints.

Parity surface: reference util/ModelSerializer.java:40-137 — a zip containing
``configuration.json`` + ``coefficients.bin`` + ``updaterState.bin`` +
optional normalizer. Here: configuration.json (our JSON DSL) +
``coefficients.npz`` (params pytree flattened to named numpy arrays) +
``updaterState.npz`` + ``modelState.npz`` (batchnorm running stats etc.) +
``normalizer.json``. Updater-state round-tripping is part of the contract
(reference ModelSerializerTest) — training resumes bit-exact.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any

import numpy as np
import jax

CONFIG_NAME = "configuration.json"
COEFF_NAME = "coefficients.npz"
UPDATER_NAME = "updaterState.npz"
STATE_NAME = "modelState.npz"
NORMALIZER_NAME = "normalizer.json"
META_NAME = "meta.json"


def _flatten_pytree(tree) -> dict:
    """Pytree → {path: ndarray} with json-encodable key paths."""
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_elem(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_elem(p):
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_into(template, flat: dict):
    """Rebuild arrays into the same structure as template."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_elem(p) for p in path)
        if key not in flat:
            raise KeyError(f"Checkpoint missing array '{key}'")
        arr = flat[key]
        new_leaves.append(arr.astype(np.asarray(leaf).dtype)
                          if hasattr(leaf, "dtype") else arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _savez(z: zipfile.ZipFile, name: str, arrays: dict):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    z.writestr(name, buf.getvalue())


def _loadz(z: zipfile.ZipFile, name: str) -> dict:
    with z.open(name) as f:
        data = np.load(io.BytesIO(f.read()), allow_pickle=False)
        return {k: data[k] for k in data.files}


def write_model(model, path, save_updater=True, normalizer=None):
    """Parity: ModelSerializer.writeModel :52."""
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    kind = "MultiLayerNetwork" if isinstance(model, MultiLayerNetwork) \
        else "ComputationGraph"
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(META_NAME, json.dumps({
            "format": "deeplearning4j_tpu/model/v1", "kind": kind,
            "iteration": model.iteration, "epoch": model.epoch}))
        z.writestr(CONFIG_NAME, model.conf.to_json())
        _savez(z, COEFF_NAME, _flatten_pytree(model.params))
        _savez(z, STATE_NAME, _flatten_pytree(model.state))
        if save_updater and model.opt_state is not None:
            _savez(z, UPDATER_NAME, _flatten_pytree(model.opt_state))
        if normalizer is not None:
            z.writestr(NORMALIZER_NAME, json.dumps(normalizer.to_dict()))


def _restore(path, load_updater, kind_expected):
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration

    with zipfile.ZipFile(path, "r") as z:
        meta = json.loads(z.read(META_NAME))
        conf_json = z.read(CONFIG_NAME).decode()
        if meta["kind"] == "MultiLayerNetwork":
            conf = MultiLayerConfiguration.from_json(conf_json)
            model = MultiLayerNetwork(conf)
        else:
            conf = ComputationGraphConfiguration.from_json(conf_json)
            model = ComputationGraph(conf)
        if kind_expected and meta["kind"] != kind_expected:
            raise ValueError(f"Expected {kind_expected}, zip holds {meta['kind']}")
        model.init()
        model.params = _unflatten_into(model.params, _loadz(z, COEFF_NAME))
        model.state = _unflatten_into(model.state, _loadz(z, STATE_NAME))
        if load_updater and UPDATER_NAME in z.namelist():
            model.opt_state = _unflatten_into(model.opt_state,
                                              _loadz(z, UPDATER_NAME))
        model.iteration = meta.get("iteration", 0)
        model.epoch = meta.get("epoch", 0)
        return model


def restore_multi_layer_network(path, load_updater=True):
    """Parity: ModelSerializer.restoreMultiLayerNetwork :137."""
    return _restore(path, load_updater, "MultiLayerNetwork")


def restore_computation_graph(path, load_updater=True):
    return _restore(path, load_updater, "ComputationGraph")


def restore_normalizer(path):
    from deeplearning4j_tpu.data.normalizers import Normalizer
    with zipfile.ZipFile(path, "r") as z:
        if NORMALIZER_NAME not in z.namelist():
            return None
        return Normalizer.from_dict(json.loads(z.read(NORMALIZER_NAME)))


def guess_model(path):
    """Sniff + load a model file (parity: core util/ModelGuesser.java):
    our zip checkpoint (MLN or CG), or a Keras HDF5 file. ``path`` may be a
    filesystem path or a seekable file-like object (e.g. the BytesIO held by
    InMemoryModelSaver)."""
    if hasattr(path, "read") and hasattr(path, "seek"):
        path.seek(0)
        magic = path.read(8)
        path.seek(0)
    else:
        with open(path, "rb") as fh:
            magic = fh.read(8)
    if magic[:4] == b"PK\x03\x04":          # our zip checkpoint
        with zipfile.ZipFile(path, "r") as z:
            if META_NAME not in z.namelist():
                raise ValueError(
                    f"{path} is a zip but not a deeplearning4j_tpu "
                    f"checkpoint (missing {META_NAME})")
        return _restore(path, True, None)
    if magic == b"\x89HDF\r\n\x1a\n":       # Keras HDF5
        from deeplearning4j_tpu.modelimport.keras_import import (
            import_keras_model_and_weights)
        return import_keras_model_and_weights(path)
    raise ValueError(f"cannot identify model format of {path} "
                     f"(magic {magic!r}); expected checkpoint zip or "
                     f"Keras HDF5")
