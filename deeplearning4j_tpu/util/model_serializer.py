"""Model persistence — zip checkpoints.

Parity surface: reference util/ModelSerializer.java:40-137 — a zip containing
``configuration.json`` + ``coefficients.bin`` + ``updaterState.bin`` +
optional normalizer. Here: configuration.json (our JSON DSL) +
``coefficients.npz`` (params pytree flattened to named numpy arrays) +
``updaterState.npz`` + ``modelState.npz`` (batchnorm running stats etc.) +
``normalizer.json``. Updater-state round-tripping is part of the contract
(reference ModelSerializerTest) — training resumes bit-exact.

Durability contract (docs/FAULT_TOLERANCE.md): ``write_model`` to a path is
ATOMIC — the zip is staged to a temp file in the target directory, fsynced,
then ``os.replace``d over the destination, so a reader (or a process killed
mid-save) only ever sees the old complete checkpoint or the new complete
checkpoint, never a torn one. A checkpoint that IS damaged (truncated copy,
bad disk) surfaces as one clear ``CorruptCheckpointError`` naming the
missing/unreadable member instead of a bare ``KeyError``/``BadZipFile``.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
import zlib
from typing import Any

import numpy as np
import jax

from deeplearning4j_tpu.resilience.errors import CorruptCheckpointError

CONFIG_NAME = "configuration.json"
COEFF_NAME = "coefficients.npz"
UPDATER_NAME = "updaterState.npz"
STATE_NAME = "modelState.npz"
NORMALIZER_NAME = "normalizer.json"
META_NAME = "meta.json"

__all__ = [
    "CorruptCheckpointError", "write_model", "restore_multi_layer_network",
    "restore_computation_graph", "restore_into", "restore_normalizer",
    "load_weights", "read_meta", "guess_model", "META_NAME", "CONFIG_NAME",
    "COEFF_NAME", "UPDATER_NAME", "STATE_NAME", "NORMALIZER_NAME",
]


def _flatten_pytree(tree) -> dict:
    """Pytree → {path: ndarray} with json-encodable key paths."""
    flat = {}
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_elem(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_elem(p):
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_into(template, flat: dict):
    """Rebuild arrays into the same structure as template."""
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = "/".join(_path_elem(p) for p in path)
        if key not in flat:
            raise KeyError(f"Checkpoint missing array '{key}'")
        arr = flat[key]
        new_leaves.append(arr.astype(np.asarray(leaf).dtype)
                          if hasattr(leaf, "dtype") else arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _savez(z: zipfile.ZipFile, name: str, arrays: dict):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    z.writestr(name, buf.getvalue())


def _open_zip(path) -> zipfile.ZipFile:
    """Open a checkpoint zip, mapping a damaged archive to
    CorruptCheckpointError (FileNotFoundError passes through untouched)."""
    try:
        return zipfile.ZipFile(path, "r")
    except zipfile.BadZipFile as e:
        raise CorruptCheckpointError(path, detail=str(e)) from e


def _read_member(z: zipfile.ZipFile, path, name: str) -> bytes:
    """Read one member, naming it in the error if missing or unreadable
    (truncated central directory, CRC mismatch, bad deflate stream)."""
    try:
        return z.read(name)
    except KeyError as e:
        raise CorruptCheckpointError(path, member=name,
                                     detail="member missing") from e
    except (zipfile.BadZipFile, zlib.error, EOFError, OSError) as e:
        raise CorruptCheckpointError(path, member=name, detail=str(e)) from e


def _loadz(z: zipfile.ZipFile, path, name: str) -> dict:
    raw = _read_member(z, path, name)
    try:
        data = np.load(io.BytesIO(raw), allow_pickle=False)
        return {k: data[k] for k in data.files}
    except (zipfile.BadZipFile, ValueError, zlib.error, EOFError, OSError) as e:
        raise CorruptCheckpointError(path, member=name, detail=str(e)) from e


def write_model(model, path, save_updater=True, normalizer=None):
    """Parity: ModelSerializer.writeModel :52.

    Filesystem paths are written ATOMICALLY: the zip is staged to a unique
    temp file in the destination directory, fsynced, then ``os.replace``d
    into place — a crash mid-save leaves the previous checkpoint intact and
    never exposes a torn zip. File-like targets (e.g. the BytesIO held by
    InMemoryModelSaver) are written directly.
    """
    if hasattr(path, "write"):
        _write_model_to(model, path, save_updater, normalizer)
        return
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            _write_model_to(model, fh, save_updater, normalizer)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:        # make the rename itself durable; best-effort on odd FSes
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def _write_model_to(model, fileobj, save_updater, normalizer):
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    kind = "MultiLayerNetwork" if isinstance(model, MultiLayerNetwork) \
        else "ComputationGraph"
    with zipfile.ZipFile(fileobj, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr(META_NAME, json.dumps({
            "format": "deeplearning4j_tpu/model/v1", "kind": kind,
            "iteration": model.iteration, "epoch": model.epoch,
            "epoch_batch": int(getattr(model, "_epoch_batch", 0))}))
        z.writestr(CONFIG_NAME, model.conf.to_json())
        _savez(z, COEFF_NAME, _flatten_pytree(model.params))
        _savez(z, STATE_NAME, _flatten_pytree(model.state))
        if save_updater and model.opt_state is not None:
            _savez(z, UPDATER_NAME, _flatten_pytree(model.opt_state))
        if normalizer is not None:
            z.writestr(NORMALIZER_NAME, json.dumps(normalizer.to_dict()))


def _load_meta(z: zipfile.ZipFile, path) -> dict:
    try:
        return json.loads(_read_member(z, path, META_NAME))
    except json.JSONDecodeError as e:
        raise CorruptCheckpointError(path, member=META_NAME,
                                     detail=str(e)) from e


def _restore(path, load_updater, kind_expected):
    from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
    from deeplearning4j_tpu.models.computation_graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration

    with _open_zip(path) as z:
        meta = _load_meta(z, path)
        conf_json = _read_member(z, path, CONFIG_NAME).decode()
        if meta["kind"] == "MultiLayerNetwork":
            conf = MultiLayerConfiguration.from_json(conf_json)
            model = MultiLayerNetwork(conf)
        else:
            conf = ComputationGraphConfiguration.from_json(conf_json)
            model = ComputationGraph(conf)
        if kind_expected and meta["kind"] != kind_expected:
            raise ValueError(f"Expected {kind_expected}, zip holds {meta['kind']}")
        model.init()
        _load_state_into(model, z, path, meta, load_updater)
        return model


def _load_state_into(model, z, path, meta, load_updater):
    model.params = _unflatten_into(model.params, _loadz(z, path, COEFF_NAME))
    model.state = _unflatten_into(model.state, _loadz(z, path, STATE_NAME))
    if load_updater and UPDATER_NAME in z.namelist():
        model.opt_state = _unflatten_into(model.opt_state,
                                          _loadz(z, path, UPDATER_NAME))
    model.iteration = meta.get("iteration", 0)
    model.epoch = meta.get("epoch", 0)
    model._epoch_batch = meta.get("epoch_batch", 0)


def restore_multi_layer_network(path, load_updater=True):
    """Parity: ModelSerializer.restoreMultiLayerNetwork :137."""
    return _restore(path, load_updater, "MultiLayerNetwork")


def restore_computation_graph(path, load_updater=True):
    return _restore(path, load_updater, "ComputationGraph")


def restore_into(model, path, load_updater=True):
    """Load a checkpoint's tensors + counters into an EXISTING initialized
    model in place (the container's ``resume_from=`` path — keeps the
    caller's listeners, prefetch config and compiled-step caches). The
    checkpoint kind must match the model's class. Returns ``model``."""
    kind = type(model).__name__
    with _open_zip(path) as z:
        meta = _load_meta(z, path)
        if meta["kind"] != kind:
            raise ValueError(f"Expected {kind}, zip holds {meta['kind']}")
        if model.params is None:
            model.init()
        _load_state_into(model, z, path, meta, load_updater)
    return model


def load_weights(model, path):
    """Read just the ``(params, state)`` tensors from a checkpoint zip,
    unflattened against ``model``'s own pytree structure — the hot-swap
    loader. The configuration inside the zip is deliberately IGNORED: only
    the flattened array paths matter, so a transfer-learning head-only
    checkpoint (whose FrozenLayer wrappers preserve the inner layers' param
    paths) loads cleanly into the plain serving net. Counters, updater state
    and the model object itself are untouched. A checkpoint whose arrays do
    not cover the model's structure raises ``WeightSwapError`` (the serving
    engines additionally verify shapes/dtypes before swapping)."""
    from deeplearning4j_tpu.resilience.errors import WeightSwapError
    with _open_zip(path) as z:
        try:
            params = _unflatten_into(model.params,
                                     _loadz(z, path, COEFF_NAME))
            state = _unflatten_into(model.state, _loadz(z, path, STATE_NAME))
        except KeyError as e:
            raise WeightSwapError(
                f"checkpoint {os.fspath(path)} is not swap-compatible with "
                f"the serving model", [str(e.args[0])]) from e
    return params, state


def read_meta(path) -> dict:
    """Checkpoint metadata (kind/iteration/epoch/epoch_batch) without
    loading any tensors — what CheckpointManager's manifest records."""
    with _open_zip(path) as z:
        return _load_meta(z, path)


def restore_normalizer(path):
    from deeplearning4j_tpu.data.normalizers import Normalizer
    with _open_zip(path) as z:
        if NORMALIZER_NAME not in z.namelist():
            return None
        return Normalizer.from_dict(
            json.loads(_read_member(z, path, NORMALIZER_NAME)))


def guess_model(path):
    """Sniff + load a model file (parity: core util/ModelGuesser.java):
    our zip checkpoint (MLN or CG), or a Keras HDF5 file. ``path`` may be a
    filesystem path or a seekable file-like object (e.g. the BytesIO held by
    InMemoryModelSaver)."""
    if hasattr(path, "read") and hasattr(path, "seek"):
        path.seek(0)
        magic = path.read(8)
        path.seek(0)
    else:
        with open(path, "rb") as fh:
            magic = fh.read(8)
    if magic[:4] == b"PK\x03\x04":          # our zip checkpoint
        with _open_zip(path) as z:
            if META_NAME not in z.namelist():
                raise ValueError(
                    f"{path} is a zip but not a deeplearning4j_tpu "
                    f"checkpoint (missing {META_NAME})")
        return _restore(path, True, None)
    if magic == b"\x89HDF\r\n\x1a\n":       # Keras HDF5
        from deeplearning4j_tpu.modelimport.keras_import import (
            import_keras_model_and_weights)
        return import_keras_model_and_weights(path)
    raise ValueError(f"cannot identify model format of {path} "
                     f"(magic {magic!r}); expected checkpoint zip or "
                     f"Keras HDF5")
