"""Native (C++) runtime components.

The reference's runtime leans on external native code — libnd4j for ops,
DataVec/JavaCPP for ETL, Aeron's C media driver for transport (SURVEY.md §2
'Native / non-JVM components'). The TPU build's op path is XLA (C++ via
jit); this package holds the framework's OWN native pieces: the ETL record
readers + async batcher (recordreader.cpp).

Compilation happens lazily on first use with g++ (cached .so next to the
source, keyed on source mtime); every caller has a pure-Python fallback, so
a host without a toolchain still works (set DL4J_TPU_NO_NATIVE=1 to force
the fallback)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

_DIR = Path(__file__).parent
_SRC = _DIR / "recordreader.cpp"
_SO = _DIR / "_librecordreader.so"

_lib = None
_tried = False


def _disabled() -> bool:
    return os.environ.get("DL4J_TPU_NO_NATIVE", "").lower() in (
        "1", "true", "yes", "on")


def _build() -> Optional[Path]:
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           str(_SRC), "-o", str(_SO)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return _SO
    except Exception:
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (build failure / disabled)."""
    global _lib, _tried
    if _disabled():
        return None
    if _lib is None and not _tried:
        _tried = True
        so = _build()
        if so is not None:
            lib = ctypes.CDLL(str(so))
            c = ctypes
            lib.idx_load.argtypes = [
                c.c_char_p, c.c_char_p, c.c_int,
                c.POINTER(c.c_int64), c.POINTER(c.c_int64),
                c.POINTER(c.c_float), c.POINTER(c.c_float)]
            lib.idx_load.restype = c.c_int
            lib.csv_dims.argtypes = [c.c_char_p, c.c_int, c.c_char,
                                     c.POINTER(c.c_int64),
                                     c.POINTER(c.c_int64)]
            lib.csv_dims.restype = c.c_int
            lib.csv_load.argtypes = [c.c_char_p, c.c_int, c.c_char,
                                     c.c_int64, c.c_int, c.c_int,
                                     c.POINTER(c.c_float),
                                     c.POINTER(c.c_float)]
            lib.csv_load.restype = c.c_int
            lib.batcher_create.argtypes = [
                c.POINTER(c.c_float), c.POINTER(c.c_float),
                c.c_int64, c.c_int64, c.c_int64, c.c_int64,
                c.c_int, c.c_uint64, c.c_int]
            lib.batcher_create.restype = c.c_void_p
            lib.batcher_next.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                         c.POINTER(c.c_float)]
            lib.batcher_next.restype = c.c_int64
            lib.batcher_reset.argtypes = [c.c_void_p]
            lib.batcher_destroy.argtypes = [c.c_void_p]
            _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None
