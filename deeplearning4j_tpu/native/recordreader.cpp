// Native ETL: record readers + async batch prefetcher.
//
// Parity role: the reference's ETL runs in native/background threads —
// DataVec record readers (external dep of deeplearning4j-core
// datasets/datavec/) and AsyncDataSetIterator's prefetch thread
// (nn/.../datasets/iterator/AsyncDataSetIterator.java, used at
// MultiLayerNetwork.java:1161 — SURVEY.md §3.1 'thread boundary (ETL)').
// Python threads can't overlap CPU-bound parsing/assembly with the train
// loop (GIL); these C++ worker threads can.
//
// C API (ctypes-friendly): IDX (MNIST/EMNIST) and CSV readers materialize
// f32 feature/label arrays; the batcher owns a bounded queue filled by a
// worker thread doing shuffled gather+copy of minibatches.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ------------------------------------------------------------------ IDX
// Returns 0 on success. Query mode: pass null buffers, receive dims.
// Labels are one-hot encoded to n_classes (0 → raw label values, ydim=1).
int idx_load(const char* img_path, const char* lab_path, int n_classes,
             int64_t* out_n, int64_t* out_feat,
             float* x_out, float* y_out) {
  FILE* fi = fopen(img_path, "rb");
  if (!fi) return 1;
  unsigned char hdr[16];
  if (fread(hdr, 1, 16, fi) != 16 || hdr[0] != 0 || hdr[1] != 0 ||
      hdr[2] != 0x08 || hdr[3] != 0x03) {
    fclose(fi);
    return 2;  // not an idx3-ubyte file
  }
  auto be32 = [](unsigned char* p) {
    return (int64_t)((p[0] << 24) | (p[1] << 16) | (p[2] << 8) | p[3]);
  };
  int64_t n = be32(hdr + 4), rows = be32(hdr + 8), cols = be32(hdr + 12);
  int64_t feat = rows * cols;
  *out_n = n;
  *out_feat = feat;
  if (!x_out) {  // query mode
    fclose(fi);
    return 0;
  }
  std::vector<unsigned char> buf(feat);
  for (int64_t i = 0; i < n; i++) {
    if (fread(buf.data(), 1, feat, fi) != (size_t)feat) {
      fclose(fi);
      return 3;
    }
    float* dst = x_out + i * feat;
    for (int64_t j = 0; j < feat; j++) dst[j] = buf[j] * (1.0f / 255.0f);
  }
  fclose(fi);

  FILE* fl = fopen(lab_path, "rb");
  if (!fl) return 4;
  unsigned char lh[8];
  if (fread(lh, 1, 8, fl) != 8 || lh[2] != 0x08 || lh[3] != 0x01) {
    fclose(fl);
    return 5;
  }
  int64_t nl = be32(lh + 4);
  if (nl != n) {
    fclose(fl);
    return 6;
  }
  std::vector<unsigned char> labs(n);
  if (fread(labs.data(), 1, n, fl) != (size_t)n) {
    fclose(fl);
    return 7;
  }
  fclose(fl);
  if (n_classes > 0) {
    memset(y_out, 0, sizeof(float) * n * n_classes);
    for (int64_t i = 0; i < n; i++) {
      int lab = labs[i];
      if (lab >= 0 && lab < n_classes) y_out[i * n_classes + lab] = 1.0f;
    }
  } else {
    for (int64_t i = 0; i < n; i++) y_out[i] = (float)labs[i];
  }
  return 0;
}

// ------------------------------------------------------------------ CSV
// Two-phase: csv_dims counts rows/cols; csv_load fills x (all non-label
// columns) and y (label column one-hot to n_classes, or raw if 0).
// Lines longer than the 64 KiB buffer are an error (rc=8), not a silent
// row split; quoted fields / embedded delimiters are unsupported (the
// Python binding documents this).
static int line_truncated(const char* line, size_t cap, FILE* f) {
  size_t len = strlen(line);
  if (len != cap - 1 || line[len - 1] == '\n') return 0;
  // buffer full without newline: truncated unless this is the final line of
  // a file with no trailing newline
  int c = fgetc(f);
  if (c == EOF) return 0;
  ungetc(c, f);
  return 1;
}

int csv_dims(const char* path, int skip_lines, char delim,
             int64_t* out_rows, int64_t* out_cols) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  char line[1 << 16];
  int64_t rows = 0, cols = 0;
  int skipped = 0;
  while (fgets(line, sizeof(line), f)) {
    if (line_truncated(line, sizeof(line), f)) {
      fclose(f);
      return 8;
    }
    if (skipped < skip_lines) {
      skipped++;
      continue;
    }
    if (line[0] == '\n' || line[0] == '\r' || line[0] == 0) continue;
    if (cols == 0) {
      cols = 1;
      for (char* p = line; *p; p++)
        if (*p == delim) cols++;
    }
    rows++;
  }
  fclose(f);
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

int csv_load(const char* path, int skip_lines, char delim, int64_t n_cols,
             int label_col, int n_classes, float* x_out, float* y_out) {
  FILE* f = fopen(path, "rb");
  if (!f) return 1;
  char line[1 << 16];
  int skipped = 0;
  int64_t row = 0;
  int64_t n_feat = (label_col >= 0) ? n_cols - 1 : n_cols;
  while (fgets(line, sizeof(line), f)) {
    if (line_truncated(line, sizeof(line), f)) {
      fclose(f);
      return 8;
    }
    if (skipped < skip_lines) {
      skipped++;
      continue;
    }
    if (line[0] == '\n' || line[0] == '\r' || line[0] == 0) continue;
    int64_t col = 0, xcol = 0;
    char* p = line;
    while (*p && col < n_cols) {
      char* end;
      double v = strtod(p, &end);
      if (col == label_col) {
        if (n_classes > 0) {
          int lab = (int)v;
          for (int c = 0; c < n_classes; c++)
            y_out[row * n_classes + c] = (c == lab) ? 1.0f : 0.0f;
        } else {
          y_out[row] = (float)v;
        }
      } else {
        x_out[row * n_feat + xcol] = (float)v;
        xcol++;
      }
      col++;
      p = (end == p) ? p + 1 : end;
      while (*p && *p != delim) p++;
      if (*p == delim) p++;
    }
    row++;
  }
  fclose(f);
  return 0;
}

// --------------------------------------------------------------- batcher
struct Batch {
  std::vector<float> x, y;
  int64_t count;
};

struct Batcher {
  const float* x;
  const float* y;
  int64_t n, xdim, ydim;
  int64_t batch;
  bool shuffle;
  uint64_t seed;
  int64_t epoch;
  size_t capacity;

  std::vector<int64_t> order;
  std::queue<Batch*> q;
  std::mutex m;
  std::condition_variable cv_put, cv_get;
  std::thread worker;
  std::atomic<bool> stop{false};
  std::atomic<bool> epoch_done{false};

  void fill_order() {
    order.resize(n);
    for (int64_t i = 0; i < n; i++) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + (uint64_t)epoch);
      for (int64_t i = n - 1; i > 0; i--) {
        int64_t j = (int64_t)(rng() % (uint64_t)(i + 1));
        std::swap(order[i], order[j]);
      }
    }
  }

  void run() {
    fill_order();
    for (int64_t start = 0; start < n && !stop; start += batch) {
      int64_t cnt = std::min(batch, n - start);
      Batch* b = new Batch();
      b->count = cnt;
      b->x.resize(cnt * xdim);
      b->y.resize(cnt * ydim);
      for (int64_t i = 0; i < cnt; i++) {
        int64_t src = order[start + i];
        memcpy(b->x.data() + i * xdim, x + src * xdim,
               sizeof(float) * xdim);
        memcpy(b->y.data() + i * ydim, y + src * ydim,
               sizeof(float) * ydim);
      }
      std::unique_lock<std::mutex> lk(m);
      cv_put.wait(lk, [&] { return q.size() < capacity || stop; });
      if (stop) {
        delete b;
        return;
      }
      q.push(b);
      cv_get.notify_one();
    }
    epoch_done = true;
    cv_get.notify_all();
  }
};

void* batcher_create(const float* x, const float* y, int64_t n,
                     int64_t xdim, int64_t ydim, int64_t batch,
                     int shuffle, uint64_t seed, int capacity) {
  Batcher* b = new Batcher();
  b->x = x;
  b->y = y;
  b->n = n;
  b->xdim = xdim;
  b->ydim = ydim;
  b->batch = batch;
  b->shuffle = shuffle != 0;
  b->seed = seed;
  b->epoch = 0;
  b->capacity = capacity > 0 ? capacity : 4;
  b->worker = std::thread([b] { b->run(); });
  return b;
}

// Returns examples in this batch, 0 when the epoch is exhausted.
int64_t batcher_next(void* h, float* x_out, float* y_out) {
  Batcher* b = (Batcher*)h;
  std::unique_lock<std::mutex> lk(b->m);
  b->cv_get.wait(lk, [&] { return !b->q.empty() || b->epoch_done || b->stop; });
  if (b->q.empty()) return 0;
  Batch* batch = b->q.front();
  b->q.pop();
  b->cv_put.notify_one();
  lk.unlock();
  memcpy(x_out, batch->x.data(), sizeof(float) * batch->count * b->xdim);
  memcpy(y_out, batch->y.data(), sizeof(float) * batch->count * b->ydim);
  int64_t cnt = batch->count;
  delete batch;
  return cnt;
}

// New epoch: re-shuffles with seed+epoch and restarts the worker.
void batcher_reset(void* h) {
  Batcher* b = (Batcher*)h;
  {
    std::unique_lock<std::mutex> lk(b->m);
    b->stop = true;
    b->cv_put.notify_all();
    b->cv_get.notify_all();
  }
  if (b->worker.joinable()) b->worker.join();
  std::queue<Batch*> empty;
  while (!b->q.empty()) {
    delete b->q.front();
    b->q.pop();
  }
  b->stop = false;
  b->epoch_done = false;
  b->epoch++;
  b->worker = std::thread([b] { b->run(); });
}

void batcher_destroy(void* h) {
  Batcher* b = (Batcher*)h;
  {
    std::unique_lock<std::mutex> lk(b->m);
    b->stop = true;
    b->cv_put.notify_all();
    b->cv_get.notify_all();
  }
  if (b->worker.joinable()) b->worker.join();
  while (!b->q.empty()) {
    delete b->q.front();
    b->q.pop();
  }
  delete b;
}

}  // extern "C"
