"""Multi-host distributed initialization.

Parity surface: the reference's cluster story — Spark driver/executor setup
(SparkDl4jMultiLayer) and the Aeron VoidParameterServer transport
(SURVEY.md §5 'distributed communication backend'). TPU-native equivalent:
``jax.distributed.initialize`` forms the multi-host runtime; after it, the
SAME ParallelWrapper/pjit code runs unchanged — ``jax.devices()`` spans all
hosts, the mesh covers the pod, and XLA routes collectives over ICI within a
pod slice and DCN across slices. No parameter server, no gradient
quantization, no custom transport.

There is deliberately no Spark-equivalent job scheduler here: launching one
process per host (GKE/JobSet, mpirun, etc.) replaces Spark executors, and
fault tolerance is checkpoint/restart (util/model_serializer +
orbax-compatible arrays) rather than task retry.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["initialize", "pod_mesh", "local_batch_slice",
           "set_topology", "topology", "set_generation", "generation",
           "fence", "StaleGenerationError"]


class StaleGenerationError(RuntimeError):
    """A contribution stamped with a membership generation that has since
    been superseded (a worker evicted / a replacement joined). The elastic
    coordinator fences these at the RPC layer; this is the worker-side
    guard for anything that slipped past it."""


# -- elastic topology ------------------------------------------------------
# The TCP-fallback cluster (exec/cluster.py) never initializes
# jax.distributed — the jaxlib CPU backend ships no cross-process
# collectives — so rank/world live here instead of in jax.process_*().
# The elastic worker re-stamps these at every committed generation.
_rank: Optional[int] = None
_world: Optional[int] = None
_generation: int = 0


def set_topology(rank: Optional[int], world: Optional[int]) -> None:
    """Pin this process's (rank, world) for ``local_batch_slice`` when the
    cluster membership is coordinator-managed rather than jax-managed.
    ``(None, None)`` reverts to ``jax.process_index/count``."""
    global _rank, _world
    _rank, _world = rank, world


def topology() -> Tuple[int, int]:
    """Effective (rank, world): the elastic override when set, else the
    jax.distributed view (single-process: (0, 1))."""
    if _rank is not None and _world is not None:
        return _rank, _world
    return jax.process_index(), jax.process_count()


def set_generation(gen: int) -> None:
    """Record the committed membership generation this process trains in."""
    global _generation
    _generation = int(gen)


def generation() -> int:
    return _generation


def fence(gen: int) -> None:
    """Raise unless ``gen`` is the current generation — the guard every
    gradient contribution passes before leaving this process, so a
    straggler from a dead epoch can never publish into a live one."""
    if int(gen) != _generation:
        raise StaleGenerationError(
            f"contribution carries generation {gen}, membership is at "
            f"{_generation}")


def _is_initialized() -> bool:
    """``jax.distributed.is_initialized`` without requiring it: older jax
    releases (0.4.3x) don't expose the predicate, but the global
    distributed state object it reads exists on every release — checking
    its client slot is the same test and still never touches the XLA
    backend."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:       # noqa: BLE001 — private layout moved: assume no
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               generation: Optional[int] = None):
    """Initialize the multi-host JAX runtime (idempotent, env-var driven like
    jax itself: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID if args omitted,
    with DL4JTPU_RANK/DL4JTPU_WORLD as the elastic cluster's rank wiring).
    Call once per host process before building meshes — and before ANYTHING
    that touches the XLA backend (jax.devices/process_count included), which
    is why the already-initialized check must not query the backend.

    ``generation`` stamps the committed membership generation (see
    ``fence``); the elastic worker re-initializes it on every reform."""
    if generation is not None:
        set_generation(generation)
    if process_id is None and os.environ.get("DL4JTPU_RANK"):
        process_id = int(os.environ["DL4JTPU_RANK"])
    if num_processes is None and os.environ.get("DL4JTPU_WORLD"):
        num_processes = int(os.environ["DL4JTPU_WORLD"])
    if _is_initialized():
        return
    if coordinator_address or os.environ.get("COORDINATOR_ADDRESS"):
        kwargs = {"coordinator_address": (coordinator_address or
                                          os.environ["COORDINATOR_ADDRESS"])}
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        jax.distributed.initialize(**kwargs)
    elif process_id is not None and num_processes is not None:
        # no jax-level cluster (the loopback-TCP fallback): record the
        # coordinator-assigned topology so local_batch_slice still shards
        set_topology(process_id, num_processes)


def pod_mesh(axes=("data",), shape=None) -> Mesh:
    """Mesh over every device on every host. shape: optional tuple matching
    axes, e.g. axes=('data','model') shape=(4, 2)."""
    devs = np.array(jax.devices())
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, axes)


def local_batch_slice(global_batch: int, rank: Optional[int] = None,
                      world: Optional[int] = None) -> slice:
    """This process's slice of a globally-sharded batch (data axis split
    across processes, parity with each Spark executor reading its
    partition). ``rank``/``world`` override the ambient topology — the
    elastic cluster passes its committed-generation membership so a
    degraded N-1 world re-shards without touching jax.distributed. Ragged
    worlds are handled: the first ``global_batch % world`` ranks take one
    extra row, so every row is owned exactly once."""
    if rank is None or world is None:
        rank, world = topology()
    base, rem = divmod(int(global_batch), int(world))
    start = rank * base + min(rank, rem)
    return slice(start, start + base + (1 if rank < rem else 0))
