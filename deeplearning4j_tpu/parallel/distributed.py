"""Multi-host distributed initialization.

Parity surface: the reference's cluster story — Spark driver/executor setup
(SparkDl4jMultiLayer) and the Aeron VoidParameterServer transport
(SURVEY.md §5 'distributed communication backend'). TPU-native equivalent:
``jax.distributed.initialize`` forms the multi-host runtime; after it, the
SAME ParallelWrapper/pjit code runs unchanged — ``jax.devices()`` spans all
hosts, the mesh covers the pod, and XLA routes collectives over ICI within a
pod slice and DCN across slices. No parameter server, no gradient
quantization, no custom transport.

There is deliberately no Spark-equivalent job scheduler here: launching one
process per host (GKE/JobSet, mpirun, etc.) replaces Spark executors, and
fault tolerance is checkpoint/restart (util/model_serializer +
orbax-compatible arrays) rather than task retry.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh


def _is_initialized() -> bool:
    """``jax.distributed.is_initialized`` without requiring it: older jax
    releases (0.4.3x) don't expose the predicate, but the global
    distributed state object it reads exists on every release — checking
    its client slot is the same test and still never touches the XLA
    backend."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:       # noqa: BLE001 — private layout moved: assume no
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Initialize the multi-host JAX runtime (idempotent, env-var driven like
    jax itself: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID if args omitted).
    Call once per host process before building meshes — and before ANYTHING
    that touches the XLA backend (jax.devices/process_count included), which
    is why the already-initialized check must not query the backend."""
    if _is_initialized():
        return
    kwargs = {}
    if coordinator_address or os.environ.get("COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (coordinator_address or
                                         os.environ["COORDINATOR_ADDRESS"])
        if num_processes is not None:
            kwargs["num_processes"] = num_processes
        if process_id is not None:
            kwargs["process_id"] = process_id
        jax.distributed.initialize(**kwargs)


def pod_mesh(axes=("data",), shape=None) -> Mesh:
    """Mesh over every device on every host. shape: optional tuple matching
    axes, e.g. axes=('data','model') shape=(4, 2)."""
    devs = np.array(jax.devices())
    if shape is not None:
        devs = devs.reshape(shape)
    return Mesh(devs, axes)


def local_batch_slice(global_batch: int) -> slice:
    """This host's slice of a globally-sharded batch (data axis split across
    processes, parity with each Spark executor reading its partition)."""
    per = global_batch // jax.process_count()
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)
