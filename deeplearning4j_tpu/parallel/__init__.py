from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference

__all__ = ["ParallelWrapper", "ParallelInference"]
