"""Batched multi-device inference.

Parity surface: reference ParallelInference (parallelism/ParallelInference.java,
401 LoC) + BatchedInferenceObservable — a request queue whose observables are
merged into device-sized batches, dispatched round-robin to per-device model
replicas, and demuxed back to callers.

TPU-native design: replicas/round-robin are replaced by ONE sharded jit call —
the merged batch is sharded over the mesh 'data' axis, params replicated; XLA
splits the work across devices. The host-side piece kept from the reference is
the dynamic batcher: a background thread that merges concurrent requests up to
``max_batch_size`` / ``nano_timeout`` before dispatch.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.wrapper import default_mesh


class ParallelInference:
    def __init__(self, model, mesh: Optional[Mesh] = None,
                 max_batch_size: int = 256, batch_timeout_ms: float = 2.0):
        self.model = model
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n_devices = self.mesh.devices.size
        self.max_batch_size = max_batch_size
        self.batch_timeout_ms = batch_timeout_ms
        self._fwd = None
        self._q: "queue.Queue" = queue.Queue()
        self._thread = None
        self._stop = threading.Event()

    def _build(self):
        model = self.model
        repl = NamedSharding(self.mesh, P())
        data_sh = NamedSharding(self.mesh, P("data"))

        def fwd(params, state, x):
            act, _, _ = model._forward(params, state, x, train=False, rng=None)
            return act

        self._fwd = jax.jit(fwd, in_shardings=(repl, repl, data_sh),
                            out_shardings=data_sh)
        self._params = jax.device_put(model.params, repl)
        self._state = jax.device_put(model.state, repl)

    # ---------------------------------------------------------- sync output
    def output(self, x):
        """Direct sharded batch inference (pads batch to a device multiple)."""
        if self._fwd is None:
            self._build()
        x = np.asarray(x)
        b = x.shape[0]
        pad = (-b) % self.n_devices
        if pad:
            x = np.concatenate([x, np.repeat(x[-1:], pad, axis=0)])
        out = self._fwd(self._params, self._state, jnp.asarray(x))
        return np.asarray(out)[:b]

    # ------------------------------------------------------ async (batched)
    def start(self):
        """Start the dynamic-batching worker (parity: the observable queue)."""
        if self._thread is not None:
            return self
        if self._fwd is None:
            self._build()
        self._stop.clear()

        def worker():
            while not self._stop.is_set():
                try:
                    first = self._q.get(timeout=0.1)
                except queue.Empty:
                    continue
                batch = [first]
                total = first[0].shape[0]
                deadline = self.batch_timeout_ms / 1000.0
                t0 = _now()
                while total < self.max_batch_size and (_now() - t0) < deadline:
                    try:
                        item = self._q.get_nowait()
                        batch.append(item)
                        total += item[0].shape[0]
                    except queue.Empty:
                        break
                xs = np.concatenate([b[0] for b in batch])
                try:
                    out = self.output(xs)
                    ofs = 0
                    for x, fut in batch:
                        fut.set_result(out[ofs:ofs + x.shape[0]])
                        ofs += x.shape[0]
                except Exception as e:
                    for _, fut in batch:
                        fut.set_exception(e)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()
        return self

    def submit(self, x) -> Future:
        """Submit a request; merged with concurrent requests into one batch."""
        if self._thread is None:
            self.start()
        fut: Future = Future()
        self._q.put((np.asarray(x), fut))
        return fut

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def _now():
    import time
    return time.perf_counter()
