"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2 parallelism inventory —
its capability bar is DP only); this is a TPU-idiomatic extension completing
the dp/tp/sp/pp set. Design (the scaling-book recipe):

- the model is S *uniform* stages (same pytree structure per stage); stage
  parameters are stacked on a leading axis and sharded over the mesh's
  'pipe' axis, so each device holds exactly one stage;
- a batch is split into M microbatches; the schedule runs M + S - 1 ticks
  inside ONE compiled ``lax.scan``. Each tick, every device applies its
  stage to its current activation and hands the result to the next device
  with ``lax.ppermute`` (compute overlaps the ICI transfer);
- the whole schedule is differentiable — shard_map/ppermute have transpose
  rules — so ``jax.grad`` of a loss over ``pipeline_forward`` yields the
  stacked per-stage parameter gradients and one optimizer step updates all
  stages in place (the GPipe synchronous update, no weight staleness).

Uniform stages are the standard PP regime (transformer blocks); arbitrary
heterogeneous stacks should use DP/TP instead.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.shmap import shard_map


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def shard_stages(stacked, mesh: Mesh, axis: str = "pipe"):
    """Place the stacked stage params with the stage axis over ``axis``."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(*( [axis] + [None] * (a.ndim - 1))))),
        stacked)


def pipeline_forward(stage_fn: Callable, stacked_params, x_microbatches,
                     mesh: Mesh, axis: str = "pipe"):
    """Run the pipelined forward.

    stage_fn(params, x) -> y with y.shape == x.shape (uniform stages).
    stacked_params: pytree, leaves (S, ...), stage axis sharded over ``axis``.
    x_microbatches: (M, mb, F) — microbatch axis leading, replicated.
    Returns (M, mb, F): the last stage's output per microbatch.
    """
    S = mesh.shape[axis]
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages != S:
        raise ValueError(
            f"{n_stages} stages but the '{axis}' mesh axis has {S} devices "
            "— each device holds exactly one stage")
    M = x_microbatches.shape[0]
    T = M + S - 1

    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(),
             check_vma=False)
    def run(params, xs):
        my_params = jax.tree_util.tree_map(lambda a: a[0], params)
        s = lax.axis_index(axis)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 ingests microbatch t; later stages take the handoff
            mb_idx = jnp.clip(t, 0, M - 1)
            x_in = jnp.where(s == 0, xs[mb_idx], buf)
            y = stage_fn(my_params, x_in)
            # the last stage's tick t result is microbatch t - (S-1)
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            take = (s == S - 1) & (t >= S - 1)
            outs = outs.at[out_idx].set(
                jnp.where(take, y, outs[out_idx]))
            buf = lax.ppermute(y, axis, perm)
            return (buf, outs), jnp.float32(0)

        buf0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        (buf, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(T))
        # only the last device holds real outputs; broadcast to all
        outs = lax.psum(jnp.where(s == S - 1, outs, jnp.zeros_like(outs)),
                        axis)
        return outs

    return run(stacked_params, x_microbatches)


def split_microbatches(x, num_microbatches: int):
    """(B, ...) → (M, B/M, ...)."""
    B = x.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into "
                         f"{num_microbatches} microbatches")
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])


class PipelineParallel:
    """Minimal GPipe trainer over uniform stages.

    stage_fn(stage_params, x) -> y (same shape); loss_fn(y, targets) ->
    scalar mean loss. One jitted train step runs schedule + backward +
    SGD update for all stages.
    """

    def __init__(self, stage_fn, loss_fn, per_stage_params, mesh: Mesh,
                 axis: str = "pipe", learning_rate: float = 1e-2,
                 num_microbatches: int = None):
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.axis = axis
        self.lr = learning_rate
        self.num_microbatches = num_microbatches or mesh.shape[axis]
        if len(per_stage_params) != mesh.shape[axis]:
            raise ValueError(
                f"{len(per_stage_params)} stages but the '{axis}' mesh axis "
                f"has {mesh.shape[axis]} devices")
        self.params = shard_stages(stack_stage_params(per_stage_params),
                                   mesh, axis)
        self._step = None

    def _build(self):
        stage_fn, loss_fn = self.stage_fn, self.loss_fn
        mesh, axis, lr = self.mesh, self.axis, self.lr

        def loss(params, xs, ys):
            outs = pipeline_forward(stage_fn, params, xs, mesh, axis)
            return loss_fn(outs.reshape((-1,) + outs.shape[2:]),
                           ys.reshape((-1,) + ys.shape[2:]))

        def step(params, xs, ys):
            l, g = jax.value_and_grad(loss)(params, xs, ys)
            params = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                            params, g)
            return params, l

        return jax.jit(step, donate_argnums=(0,))

    def fit_batch(self, x, y):
        xs = split_microbatches(jnp.asarray(x), self.num_microbatches)
        ys = split_microbatches(jnp.asarray(y), self.num_microbatches)
        if self._step is None:
            self._step = self._build()
        self.params, loss = self._step(self.params, xs, ys)
        return loss

    def forward(self, x):
        xs = split_microbatches(jnp.asarray(x), self.num_microbatches)
        outs = pipeline_forward(self.stage_fn, self.params, xs, self.mesh,
                                self.axis)
        return outs.reshape((-1,) + outs.shape[2:])
