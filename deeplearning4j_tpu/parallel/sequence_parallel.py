"""Sequence/context parallelism: ring attention over a mesh axis.

The reference handles long sequences only via truncated BPTT (SURVEY.md §5);
this module provides the TPU-native long-context capability the build plan
requires: the sequence axis is sharded over the mesh, each device holds a
(B, T/n, H, Dh) block of Q/K/V, and K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates its attention output with the
streaming-softmax (flash) recurrence — max/denominator carried in log-space,
so the result is EXACT full attention, never materializing the (T, T) score
matrix and overlapping compute with ICI transfers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.shmap import shard_map


def _ring_attention_local(q, k, v, axis_name, causal):
    """Runs INSIDE shard_map. q/k/v: (B, Tl, H, Dh) local blocks."""
    # psum of 1 = the axis size (lax.axis_size is gone in this jax line)
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, Tl, H, Dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(r, carry):
        k_blk, v_blk, m, l, o = carry
        src = (my - r) % n                      # global block id of k_blk
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        if causal:
            qpos = my * Tl + jnp.arange(Tl)
            kpos = src * Tl + jnp.arange(Tl)
            s = jnp.where(qpos[None, None, :, None] >= kpos[None, None, None, :],
                          s, -jnp.inf)
        m_blk = s.max(-1)                       # (B,H,Tq)
        m_new = jnp.maximum(m, m_blk)
        # guard -inf - -inf = nan for fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new)

    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)
    o0 = jnp.zeros((B, H, Tl, Dh), q.dtype)
    _, _, m, l, o = lax.fori_loop(0, n, step, (k, v, m0, l0, o0))
    out = o / jnp.maximum(l, 1e-30)[..., None]     # (B,H,Tq,Dh)
    return out.transpose(0, 2, 1, 3)               # (B,Tq,H,Dh)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "seq", causal: bool = False):
    """Exact attention with the sequence axis sharded over ``mesh[axis]``.

    q/k/v: (B, T, H, Dh) global arrays (T divisible by mesh axis size).
    Returns (B, T, H, Dh) with the same sharding.
    """
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
    return fn(q, k, v)


class SequenceParallelAttention:
    """Module-level wrapper: applies a MultiHeadAttention layer's projections
    locally (sequence-sharded GEMMs) and its attention via the ring —
    the drop-in long-context execution path for the attention layer."""

    def __init__(self, layer, mesh: Mesh, axis: str = "seq"):
        self.layer = layer
        self.mesh = mesh
        self.axis = axis

    def __call__(self, params, x):
        B, T, C = x.shape
        q, k, v = self.layer._project(params, x)
        o = ring_attention(q, k, v, self.mesh, self.axis,
                           causal=self.layer.causal)
        o = o.reshape(B, T, self.layer.n_out) @ params["Wo"]
        if self.layer.has_bias:
            o = o + params["bo"]
        return o
