"""Data-parallel training over a device mesh.

Parity surface: reference ParallelWrapper (deeplearning4j-scaleout-
parallelwrapper/.../ParallelWrapper.java:58 — N replicas, synchronous param
averaging every ``averagingFrequency`` iterations :251-371, or async
threshold-encoded gradient sharing via EncodedGradientsAccumulator) and the
Spark ParameterAveragingTrainingMaster / SharedTrainingMaster stacks
(SURVEY.md §2 #19/#22/#23). Like the reference (which takes any ``Model``),
this wrapper accepts either container — MultiLayerNetwork or
ComputationGraph — through the uniform ``_dp_batch`` / ``_dp_loss`` /
``_dp_apply_updates`` protocol both implement.

TPU-native design: there are no worker threads, no parameter server, no
gradient quantization — one jit'd SPMD train step over a
``jax.sharding.Mesh``:

- params/opt-state: replicated (NamedSharding(P()))
- batch: sharded along the mesh 'data' axis (P('data'))
- XLA inserts the gradient all-reduce over ICI automatically from the
  sharding annotations (the scaling-book recipe). This is mathematically the
  reference's averaging with frequency=1 and supersedes its Aeron gradient-
  sharing path (SURVEY.md §5 maps all three mechanisms to psum).

``averaging_frequency > 1`` reproduces the reference's divergent-replica
semantics: each device takes k independent local steps on its own params
(shard_map + lax.scan over microbatches), then params AND updater state are
pmean-averaged (parity: averageUpdatersState ParallelWrapper.java:339).

Uneven batches are padded to a device multiple by duplicating rows, but the
pad rows carry a zero loss-weight (a per-example mask through the model's
mask-aware losses), so gradients equal the unpadded batch exactly — no
double-counting.

Multi-host: the same code scales over DCN by initializing
``jax.distributed`` (see deeplearning4j_tpu.parallel.distributed) — the mesh
then spans all hosts' devices and the collectives ride ICI within a pod and
DCN across pods. No NCCL/Aeron equivalent is needed.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, List

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.util.shmap import shard_map

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet


def default_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


class ParallelWrapper:
    """Data-parallel trainer wrapping a MultiLayerNetwork or ComputationGraph.

    Usage (parity: ParallelWrapper.Builder):
        pw = ParallelWrapper(net, workers=8, averaging_frequency=1)
        pw.fit(iterator)

    workers = number of mesh devices (defaults to all).
    averaging_frequency=1 → per-step gradient allreduce (recommended on TPU);
    >1 → reference-style local steps + periodic param/updater averaging.
    """

    def __init__(self, model, workers: Optional[int] = None,
                 averaging_frequency: int = 1, prefetch_buffer: int = 2,
                 mesh: Optional[Mesh] = None, model_axis: str = "model"):
        """``mesh`` may be 1-D ``('data',)`` (pure DP, the reference's
        capability bar) or 2-D ``('data', 'model')`` — a TPU-idiomatic
        extension: parameter output dims are sharded over the model axis
        (tensor parallelism) while the batch shards over data; XLA/GSPMD
        inserts the TP collectives. The reference has no TP (SURVEY §2
        parallelism inventory)."""
        self.model = model
        self.mesh = mesh if mesh is not None else default_mesh(workers)
        if "data" not in self.mesh.axis_names:
            raise ValueError(
                f"ParallelWrapper mesh needs a 'data' axis, got "
                f"{self.mesh.axis_names}")
        self.n_devices = self.mesh.shape["data"]   # batch shards over data
        if len(self.mesh.axis_names) > 1 and model_axis not in self.mesh.axis_names:
            # a multi-axis mesh whose extra axis doesn't match would silently
            # run pure DP with duplicate compute on the second axis
            raise ValueError(
                f"mesh has axes {self.mesh.axis_names} but model_axis="
                f"{model_axis!r} matches none of them")
        self.model_axis = model_axis if model_axis in self.mesh.axis_names \
            else None
        if self.model_axis is not None and averaging_frequency != 1:
            raise ValueError(
                "tensor parallelism (2-D mesh) requires "
                "averaging_frequency=1 (per-step sync)")
        self.averaging_frequency = max(1, int(averaging_frequency))
        self.prefetch_buffer = prefetch_buffer
        self._step_fn = None
        self._scan_fn = None

    # ------------------------------------------------------------------ build
    def _param_sharding(self, leaf, path=""):
        """TP placement for one weight leaf. The Megatron pairing rule
        (column-parallel Q/K/V & up-projections, row-parallel Wo/ff2/down,
        replicated 1-D vectors) lives in ``exec.param_spec`` — the same
        rule the execution core applies when its mesh has a model axis, so
        the wrapper and the default path can never disagree on placement."""
        if self.model_axis is None:
            return NamedSharding(self.mesh, P())
        from deeplearning4j_tpu.exec import param_spec
        return NamedSharding(self.mesh, param_spec(
            path, leaf, self.mesh.shape[self.model_axis],
            axis=self.model_axis))

    def _replicated(self, tree):
        """Place params: replicated (pure DP) or TP-sharded (2-D mesh)."""
        if self.model_axis is None:
            return jax.device_put(tree, NamedSharding(self.mesh, P()))

        def place(path, a):
            return jax.device_put(
                a, self._param_sharding(a, jax.tree_util.keystr(path)))
        return jax.tree_util.tree_map_with_path(place, tree)

    def _grad_update(self, params, state, opt_state, x, y, rng,
                     pad_mask=None, mf=None, ml=None):
        """The single train-step math shared by every DP path (per-step and
        scan, sync and averaging): grad of ``_dp_loss`` → ``_dp_apply_updates``.
        RNG derivation stays with each caller (the sync paths fold the
        iteration; the averaging paths additionally fold the device index so
        divergent replicas draw independent dropout masks)."""
        (loss, new_state), grads = jax.value_and_grad(
            self.model._dp_loss, has_aux=True)(params, state, x, y, rng,
                                               pad_mask, mf, ml)
        # TP meshes take the per-leaf path (see _dp_apply_updates: the
        # fused flat program would gather every TP shard)
        new_params, new_opt = self.model._dp_apply_updates(
            params, opt_state, grads,
            fused=None if self.model_axis is None else False)
        return new_params, new_state, new_opt, loss

    def _fold_iteration(self, it):
        return jax.random.fold_in(
            jax.random.PRNGKey(self.model.conf.global_conf.seed), it)

    def _build_sync_step(self):
        """averaging_frequency == 1: jit with sharding annotations; XLA emits
        the ICI all-reduce in backward."""
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        data_sh = NamedSharding(mesh, P("data"))

        def step(params, state, opt_state, x, y, it, pad_mask, mf, ml):
            return self._grad_update(params, state, opt_state, x, y,
                                     self._fold_iteration(it), pad_mask, mf, ml)

        if self.model_axis is not None:
            # TP x DP: params/opt were committed TP-sharded by _replicated
            # and the batch is committed data-sharded in fit(); jit follows
            # the committed input shardings and GSPMD inserts both the DP
            # gradient all-reduce and the TP collectives.
            return jax.jit(step, donate_argnums=(0, 1, 2))
        return jax.jit(
            step,
            in_shardings=(repl, repl, repl, data_sh, data_sh, None, data_sh,
                          data_sh, data_sh),
            out_shardings=(repl, repl, repl, repl),
            donate_argnums=(0, 1, 2))

    def _build_averaging_step(self):
        """averaging_frequency == k > 1: each device scans k local updates on
        its own divergent params, then params+opt state are pmean'd
        (parity: ParallelWrapper averaging + averageUpdatersState)."""
        mesh = self.mesh

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P(), P(None, "data"), P(None, "data"),
                           P(None, "data"), P()),
                 out_specs=(P(), P(), P(), P()),
                 check_vma=False)
        def step(params, state, opt_state, xs, ys, pad_masks, it):
            # xs leaves: (k, local_batch, ...) — microbatch axis leading,
            # batch axis sharded over 'data'
            def body(carry, inp):
                params, state, opt_state, j = carry
                x, y, pm = inp
                rng = jax.random.fold_in(self._fold_iteration(it + j),
                                         jax.lax.axis_index("data"))
                p, s, o, loss = self._grad_update(params, state, opt_state,
                                                  x, y, rng, pm)
                return (p, s, o, j + 1), loss

            (params, state, opt_state, _), losses = jax.lax.scan(
                body, (params, state, opt_state, 0), (xs, ys, pad_masks))
            # average divergent replicas (params + updater state + bn stats)
            params = jax.lax.pmean(params, "data")
            state = jax.lax.pmean(state, "data")
            opt_state = jax.lax.pmean(opt_state, "data")
            return params, state, opt_state, jax.lax.pmean(losses.mean(), "data")

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _build_sync_scan(self):
        """Device-resident multi-step sync DP: lax.scan over a leading step
        axis INSIDE the sharded jit. One dispatch trains ``n_steps``
        minibatches; XLA still inserts the per-step ICI gradient all-reduce
        from the sharding annotations. This is the DP analogue of the
        containers' ``fit_scan`` — per-step host dispatch (~ms on tunneled
        attachments) is paid once per call instead of once per minibatch."""
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        step_data = NamedSharding(mesh, P(None, "data"))

        def inner(params, state, opt_state, xs, ys, it0):
            def body(carry, inp):
                params, state, opt_state, it = carry
                x, y = inp
                p, s, o, loss = self._grad_update(
                    params, state, opt_state, x, y, self._fold_iteration(it))
                return (p, s, o, it + 1), loss

            (p, s, o, _), losses = jax.lax.scan(
                body, (params, state, opt_state, it0), (xs, ys))
            return p, s, o, losses

        if self.model_axis is not None:
            # TP x DP: follow the committed input shardings (params TP-sharded
            # by _replicated, batches data-sharded by fit_scan).
            return jax.jit(inner, donate_argnums=(0, 1, 2))
        return jax.jit(
            inner,
            in_shardings=(repl, repl, repl, step_data, step_data, None),
            out_shardings=(repl, repl, repl, repl),
            donate_argnums=(0, 1, 2))

    def _build_averaging_scan(self):
        """Device-resident averaging-frequency DP: outer scan over rounds,
        inner scan over the k local (divergent-replica) steps of each round,
        params+updater state pmean'd at every round boundary — the
        reference's averaging semantics (ParallelWrapper.java:251-371) with
        all rounds in one compiled call."""
        mesh = self.mesh

        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P(), P(None, None, "data"),
                           P(None, None, "data"), P()),
                 out_specs=(P(), P(), P(), P()),
                 check_vma=False)
        def step(params, state, opt_state, xs, ys, it0):
            # xs leaves: (rounds, k, local_batch, ...)
            def round_body(carry, inp):
                params, state, opt_state, it = carry
                xs_k, ys_k = inp

                def body(carry2, inp2):
                    params, state, opt_state, it = carry2
                    x, y = inp2
                    rng = jax.random.fold_in(self._fold_iteration(it),
                                             jax.lax.axis_index("data"))
                    p, s, o, loss = self._grad_update(params, state,
                                                      opt_state, x, y, rng)
                    return (p, s, o, it + 1), loss

                (params, state, opt_state, it), losses = jax.lax.scan(
                    body, (params, state, opt_state, it), (xs_k, ys_k))
                params = jax.lax.pmean(params, "data")
                state = jax.lax.pmean(state, "data")
                opt_state = jax.lax.pmean(opt_state, "data")
                return (params, state, opt_state, it), losses.mean()

            (params, state, opt_state, _), losses = jax.lax.scan(
                round_body, (params, state, opt_state, it0), (xs, ys))
            return params, state, opt_state, jax.lax.pmean(losses, "data")

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def fit_scan(self, xs, ys):
        """Train ``xs.shape[0]`` minibatches in ONE compiled sharded call.

        ``xs``: (n_steps, batch, ...) features, ``ys``: (n_steps, batch, ...)
        labels; ``batch`` must divide evenly over the mesh's data axis.
        averaging_frequency=1 runs per-step gradient all-reduce;
        k>1 requires n_steps % k == 0 and averages params/updater state every
        k local steps (reference averaging semantics). Masked datasets go
        through ``fit`` (the per-step path handles masks exactly)."""
        model = self.model
        if getattr(model.conf, "backprop_type", "standard") == "tbptt":
            raise ValueError(
                "fit_scan runs full-sequence backprop; a net configured for "
                "truncated BPTT must use fit() (the tbptt chunking path)")
        if model.params is None:
            model.init()
        xs = jax.tree_util.tree_map(jnp.asarray, xs)
        ys = jax.tree_util.tree_map(jnp.asarray, ys)
        lead = jax.tree_util.tree_leaves(xs)[0]
        n_steps, batch = lead.shape[0], lead.shape[1]
        for leaf in jax.tree_util.tree_leaves((xs, ys)):
            if leaf.shape[:2] != (n_steps, batch):
                raise ValueError(
                    f"fit_scan leaves must share (n_steps, batch)="
                    f"{(n_steps, batch)}; got {leaf.shape[:2]}")
        if batch % self.n_devices != 0:
            raise ValueError(
                f"fit_scan batch {batch} must divide over {self.n_devices} "
                "devices; pad the batch or use fit() (which pads exactly)")
        model.params = self._replicated(model.params)
        model.state = self._replicated(model.state)
        model.opt_state = self._replicated(model.opt_state)
        if self.averaging_frequency == 1:
            if self._scan_fn is None:
                self._scan_fn = self._build_sync_scan()
            if self.model_axis is not None:
                sh = NamedSharding(self.mesh, P(None, "data"))
                xs = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, sh), xs)
                ys = jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, sh), ys)
        else:
            k = self.averaging_frequency
            if n_steps % k != 0:
                raise ValueError(
                    f"n_steps={n_steps} must be a multiple of "
                    f"averaging_frequency={k} on the fit_scan path")
            reshape = lambda a: a.reshape((n_steps // k, k) + a.shape[1:])
            xs = jax.tree_util.tree_map(reshape, xs)
            ys = jax.tree_util.tree_map(reshape, ys)
            if self._scan_fn is None:
                self._scan_fn = self._build_averaging_scan()
        model.params, model.state, model.opt_state, losses = self._scan_fn(
            model.params, model.state, model.opt_state, xs, ys,
            jnp.asarray(model.iteration, jnp.int32))
        model.iteration += n_steps
        model._score = losses[-1]
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)
        return model

    # -------------------------------------------------------------------- fit
    def fit(self, data, epochs=1):
        """Train over the mesh. ``data``: iterator of DataSets (or list)."""
        model = self.model
        if model.params is None:
            model.init()
        model.params = self._replicated(model.params)
        model.state = self._replicated(model.state)
        model.opt_state = self._replicated(model.opt_state)

        if self.averaging_frequency == 1:
            if self._step_fn is None:
                self._step_fn = self._build_sync_step()
            data_sh = NamedSharding(self.mesh, P("data"))

            # device-side normalizer: raw (e.g. uint8) batches over the
            # host->device link, transform on chip (data/normalizers.py)
            from deeplearning4j_tpu.data.iterators import \
                resolve_pre_processor
            pp = resolve_pre_processor(data)
            dev_fn = host_pp = None
            if pp is not None and getattr(pp, "device_side", False):
                f = pp.as_device_transform()
                if f is not None:
                    dev_fn = jax.jit(f)
                else:
                    host_pp = pp   # device-side requested, not expressible

            def fit_one(ds):
                x, y, pad_mask, mf, ml = self._prepare(ds)
                if dev_fn is not None:
                    x = jax.tree_util.tree_map(
                        lambda a: dev_fn(jnp.asarray(a)), x)
                if self.model_axis is not None:
                    x, y, pad_mask, mf, ml = jax.tree_util.tree_map(
                        lambda a: jax.device_put(jnp.asarray(a), data_sh),
                        (x, y, pad_mask, mf, ml))
                model.params, model.state, model.opt_state, loss = \
                    self._step_fn(model.params, model.state, model.opt_state,
                                  x, y, jnp.asarray(model.iteration, jnp.int32),
                                  pad_mask, mf, ml)
                model._score = loss
                model.iteration += 1
                for lst in model.listeners:
                    lst.iteration_done(model, model.iteration, model.epoch)

            # auto-chunk runs of scan-able batches onto the device-resident
            # sharded multi-step path (same design as
            # MultiLayerNetwork._fit_stream: one compiled call per chunk
            # instead of one host dispatch per minibatch)
            chunkable = (getattr(model.conf, "backprop_type", "standard")
                         != "tbptt")
            for _ in range(epochs):
                if hasattr(data, "reset"):
                    data.reset()
                buf, shape = [], None

                def flush():
                    nonlocal buf, shape
                    if not buf:
                        return
                    if len(buf) == 1:
                        fit_one(buf[0])
                    else:
                        # _dp_batch returns numpy VIEWS of the DataSet
                        # arrays — re-deriving them here costs nothing and
                        # keeps the buffer to just the DataSets
                        views = [model._dp_batch(d)[:2] for d in buf]
                        xs = jax.tree_util.tree_map(
                            lambda *a: np.stack(a), *[v[0] for v in views])
                        ys = jax.tree_util.tree_map(
                            lambda *a: np.stack(a), *[v[1] for v in views])
                        if dev_fn is not None:
                            xs = jax.tree_util.tree_map(
                                lambda a: dev_fn(jnp.asarray(a)), xs)
                        self.fit_scan(xs, ys)
                    buf, shape = [], None

                for ds in data:
                    dsn = ds if isinstance(ds, (DataSet, MultiDataSet)) \
                        else DataSet(*ds)
                    if host_pp is not None:
                        dsn = host_pp.pre_process(dsn)
                    x, y, mf, ml = model._dp_batch(dsn)
                    b = jax.tree_util.tree_leaves(x)[0].shape[0]
                    if (not chunkable or mf is not None or ml is not None
                            or b % self.n_devices != 0):
                        flush()
                        fit_one(dsn)
                        continue
                    key = tuple(a.shape for a in
                                jax.tree_util.tree_leaves((x, y)))
                    if shape is not None and key != shape:
                        flush()
                    shape = key
                    buf.append(dsn)
                    per = sum(a.nbytes for a in
                              jax.tree_util.tree_leaves((x, y)))
                    if len(buf) >= max(1, min(64, (256 << 20) //
                                              max(1, per))):
                        flush()
                flush()
                model.epoch += 1
        else:
            if self._step_fn is None:
                self._step_fn = self._build_averaging_step()
            k = self.averaging_frequency
            for _ in range(epochs):
                if hasattr(data, "reset"):
                    data.reset()
                micro = []
                for ds in data:
                    micro.append(ds)
                    if len(micro) == k:
                        self._fit_avg_chunk(micro)
                        micro = []
                if micro:
                    self._fit_avg_chunk(micro)
                model.epoch += 1
        return model

    def _prepare(self, ds):
        """DataSet → numpy (x, y, pad_mask, mf, ml) padded to a device
        multiple; pad rows get zero loss-weight. The DataSet's own masks are
        carried through (combined with the pad mask inside ``_dp_loss``)."""
        if not isinstance(ds, (DataSet, MultiDataSet)):
            ds = DataSet(*ds)
        x, y, mf, ml = self.model._dp_batch(ds)
        b = jax.tree_util.tree_leaves(x)[0].shape[0]
        pad_mask = np.ones((b,), np.float32)
        if b % self.n_devices != 0:
            pad = self.n_devices - (b % self.n_devices)
            x = jax.tree_util.tree_map(self._pad_rows, x)
            y = jax.tree_util.tree_map(self._pad_rows, y)
            mf = jax.tree_util.tree_map(self._pad_rows, mf)
            ml = jax.tree_util.tree_map(self._pad_rows, ml)
            pad_mask = np.concatenate([pad_mask, np.zeros((pad,), np.float32)])
        return x, y, pad_mask, mf, ml

    def _fit_avg_chunk(self, micro: List):
        model = self.model
        # microbatches may differ in size (last batch of an epoch): pad each
        # to the chunk max by wrapping (zero loss-weight), then to a device
        # multiple
        prepared = [self._prepare(ds) for ds in micro]
        if any(p[3] is not None or p[4] is not None for p in prepared):
            raise NotImplementedError(
                "averaging_frequency > 1 does not support per-example masks; "
                "use averaging_frequency=1 (sync gradient allreduce), which "
                "handles masked data exactly")
        max_b = max(jax.tree_util.tree_leaves(p[0])[0].shape[0]
                    for p in prepared)

        def widen(arr, m):
            arr = np.asarray(arr)
            b = arr.shape[0]
            if b >= m:
                return arr
            idx = np.arange(m - b) % b  # wrap rows; mask zero-weights them
            return np.concatenate([arr, arr[idx]])

        xs, ys, pms = [], [], []
        for x, y, pm, _, _ in prepared:
            b = pm.shape[0]
            if b < max_b:
                x = jax.tree_util.tree_map(lambda a: widen(a, max_b), x)
                y = jax.tree_util.tree_map(lambda a: widen(a, max_b), y)
                pm = np.concatenate([pm, np.zeros((max_b - b,), np.float32)])
            xs.append(x)
            ys.append(y)
            pms.append(pm)
        xs = jax.tree_util.tree_map(lambda *a: np.stack(a), *xs)
        ys = jax.tree_util.tree_map(lambda *a: np.stack(a), *ys)
        pms = np.stack(pms)
        model.params, model.state, model.opt_state, loss = self._step_fn(
            model.params, model.state, model.opt_state, xs, ys, pms,
            jnp.asarray(model.iteration, jnp.int32))
        model._score = loss
        model.iteration += len(micro)
        for lst in model.listeners:
            lst.iteration_done(model, model.iteration, model.epoch)

    def _pad_rows(self, arr):
        n = self.n_devices
        arr = np.asarray(arr)
        b = arr.shape[0]
        if b % n == 0:
            return arr
        pad = n - (b % n)
        idx = np.arange(pad) % b  # wrap rows; pad_mask zero-weights them
        return np.concatenate([arr, arr[idx]])
