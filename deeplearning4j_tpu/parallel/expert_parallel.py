"""Expert parallelism: capacity-based mixture-of-experts over a mesh axis.

The reference has no MoE/expert parallelism (SURVEY §2 inventory); this is
the TPU-idiomatic extension completing dp/tp/sp/pp/ep. The classic dense
formulation (Shazeer et al.): top-1 gating builds static-shaped dispatch /
combine tensors (tokens × experts × capacity) so the whole layer is three
einsums plus the expert FFNs — no ragged shapes, XLA inserts the all-to-alls
when the expert axis of the parameters and intermediate (E, C, D) tensors is
sharded over the mesh's 'expert' axis.

Tokens routed to a full expert (beyond ``capacity``) are dropped (output 0
for that token — the standard GShard/Switch behavior); an auxiliary
load-balancing loss keeps the router from collapsing onto one expert.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(rng, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32):
    kg, k1, k2 = jax.random.split(rng, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_h = 1.0 / np.sqrt(d_hidden)
    return {
        "Wg": jax.random.normal(kg, (d_model, n_experts), dtype) * scale_in,
        "W1": jax.random.normal(k1, (n_experts, d_model, d_hidden), dtype)
        * scale_in,
        "b1": jnp.zeros((n_experts, d_hidden), dtype),
        "W2": jax.random.normal(k2, (n_experts, d_hidden, d_model), dtype)
        * scale_h,
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


def shard_moe_params(params, mesh: Mesh, axis: str = "expert"):
    """Expert-major leaves shard their leading (expert) dim over ``axis``;
    the router is replicated."""
    def place(name, a):
        if name == "Wg":
            return jax.device_put(a, NamedSharding(mesh, P()))
        return jax.device_put(
            a, NamedSharding(mesh, P(*([axis] + [None] * (a.ndim - 1)))))
    return {k: place(k, v) for k, v in params.items()}


def moe_ffw(params, x, capacity_factor: float = 1.25):
    """Top-1 routed expert feed-forward.

    x: (T, D) tokens. Returns (y, aux_loss) where y: (T, D) and aux_loss is
    the Switch-style load-balancing penalty (mean fraction × mean prob per
    expert, scaled by E).
    """
    T, D = x.shape
    E = params["Wg"].shape[-1]
    C = max(1, int(capacity_factor * T / E))

    logits = x @ params["Wg"]                     # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)           # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert, E, dtype=x.dtype)          # (T, E)
    # position of each token within its expert's queue
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot          # (T, E)
    keep = onehot * (pos < C)                                  # capacity drop
    pos_c = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=x.dtype)  # (T,E,C)
    dispatch = keep[..., None] * pos_c                         # (T, E, C)
    combine = dispatch * gate[:, None, None]                   # (T, E, C)

    xe = jnp.einsum("tec,td->ecd", dispatch, x)                # (E, C, D)
    h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", xe, params["W1"])
                    + params["b1"][:, None, :])
    ye = jnp.einsum("ech,ehd->ecd", h, params["W2"]) \
        + params["b2"][:, None, :]
    y = jnp.einsum("tec,ecd->td", combine, ye)                 # (T, D)

    # Switch load-balancing aux loss
    frac_tokens = onehot.mean(axis=0)                          # (E,)
    frac_probs = probs.mean(axis=0)
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return y, aux


def moe_ffw_dense_reference(params, x):
    """Every token through its argmax expert with NO capacity limit — the
    unsharded oracle for tests (equals moe_ffw when capacity is ample)."""
    probs = jax.nn.softmax(x @ params["Wg"], axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    W1 = params["W1"][expert]                     # (T, D, H)
    b1 = params["b1"][expert]
    W2 = params["W2"][expert]
    b2 = params["b2"][expert]
    h = jax.nn.gelu(jnp.einsum("td,tdh->th", x, W1) + b1)
    y = jnp.einsum("th,thd->td", h, W2) + b2
    return y * gate[:, None]
