"""Threshold-encoded sparse gradient compression.

Parity: reference optimize/solvers/accumulation/EncodingHandler.java:114
(encodeUpdates), EncodedGradientsAccumulator.java:33 and nd4j
``ThresholdCompression`` (SURVEY.md §2 #7) — the Strom-2015-style scheme the
reference uses for async gradient sharing over threads and Aeron UDP.

On-chip (ICI) gradient exchange needs none of this — XLA's psum moves dense
bf16 gradients at full ICI bandwidth (parallel/wrapper.py). This module is
for the one place compression still pays: DCN-spanning pods / multi-host
WANs (SURVEY.md §5 'keep it only for DCN-spanning pods'), and for parity
with the reference's ParallelWrapper SHARED mode semantics.

TPU design: the reference emits a variable-length int array (dynamic shape —
hostile to XLA). Here encode is a FIXED-CAPACITY jit-able kernel: top-K of
|g| above threshold → (indices, signed values, count), so the message shape
is static and the whole encode→decode→residual pipeline stays on device.
The un-sent remainder is carried as a residual and re-applied next step
(exactly the accumulator's deferred-updates semantics)."""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


def adapt_threshold(threshold, count, capacity, *, step, min_threshold):
    """The EncodingHandler threshold-adaptation policy, shared by the
    in-process handler below, the compiled collective exchange
    (scaleout/training_master.py) and the cluster wire codec
    (exec/comms.ThresholdCodec): saturated (count >= capacity) raises the
    threshold one step; sparse (count < capacity // 4) decays it one step
    toward the floor; otherwise unchanged."""
    if count >= capacity:
        return threshold + step
    if count < capacity // 4:
        return max(min_threshold, threshold - step)
    return threshold


def adapt_threshold_jnp(threshold, count, capacity, *, step, min_threshold):
    """Traced twin of ``adapt_threshold`` (``capacity`` static, the rest
    traced) for use inside jit/shard_map programs."""
    return jnp.where(
        count >= capacity, threshold + step,
        jnp.where(count < capacity // 4,
                  jnp.maximum(min_threshold, threshold - step), threshold))


@partial(jax.jit, static_argnums=(2,))
def threshold_encode(grad, threshold, capacity):
    """Encode |g| >= threshold entries, at most ``capacity`` of them (largest
    first). Returns (indices int32[capacity], values f32[capacity], count).
    Unused slots have index -1 / value 0."""
    flat = grad.reshape(-1)
    mag = jnp.abs(flat)
    v, idx = jax.lax.top_k(mag, capacity)
    keep = v >= threshold
    count = keep.sum(dtype=jnp.int32)
    # the reference transmits sign * threshold, not the raw value
    # (ThresholdCompression 1-bit style); residual keeps the difference.
    vals = jnp.where(keep, jnp.sign(flat[idx]) * threshold, 0.0)
    idx = jnp.where(keep, idx, -1)
    return idx.astype(jnp.int32), vals.astype(jnp.float32), count


@partial(jax.jit, static_argnums=(2,))
def threshold_decode(indices, values, n):
    """Dense f32[n] vector from an encoded message."""
    safe = jnp.where(indices < 0, 0, indices)
    dense = jnp.zeros((n,), jnp.float32).at[safe].add(
        jnp.where(indices < 0, 0.0, values))
    return dense


class EncodingHandler:
    """Stateful encoder with residual carry + adaptive threshold (parity:
    EncodingHandler.java threshold decay/"shake" and
    SharedTrainingMaster.java:70-99 thresholdStep/minThreshold/shakeFrequency).

    encode() returns the message AND retains (grad - decoded) as residual,
    which is added to the next gradient before encoding — the reference's
    deferred-updates semantics."""

    def __init__(self, threshold: float = 1e-3, min_threshold: float = 1e-5,
                 threshold_step: float = 1e-5, shake_frequency: int = 0,
                 capacity_fraction: float = 0.1):
        self.threshold = float(threshold)
        self.min_threshold = float(min_threshold)
        self.threshold_step = float(threshold_step)
        self.shake_frequency = int(shake_frequency)
        self.capacity_fraction = float(capacity_fraction)
        self.residual: Optional[jax.Array] = None
        self.iteration = 0

    def _capacity(self, n):
        return max(1, min(n, int(n * self.capacity_fraction)))

    def encode(self, grad):
        """grad: any pytree/array; flattened internally. Returns
        (indices, values, count) with static shapes."""
        flat = jnp.concatenate([a.reshape(-1) for a in
                                jax.tree_util.tree_leaves(grad)]) \
            if not isinstance(grad, jax.Array) else grad.reshape(-1)
        if self.residual is not None:
            flat = flat + self.residual
        cap = self._capacity(flat.shape[0])
        idx, vals, count = threshold_encode(flat, self.threshold, cap)
        sent = threshold_decode(idx, vals, flat.shape[0])
        self.residual = flat - sent
        self._adapt(int(count), cap)
        self.iteration += 1
        return idx, vals, count

    def _adapt(self, count, cap):
        """Threshold decay when too little is sent; periodic 'shake' lowers
        it to flush stale residuals (EncodingHandler semantics)."""
        self.threshold = adapt_threshold(
            self.threshold, count, cap, step=self.threshold_step,
            min_threshold=self.min_threshold)
        if (self.shake_frequency and self.iteration > 0
                and self.iteration % self.shake_frequency == 0):
            self.threshold = max(self.min_threshold, self.threshold * 0.5)

    def reset(self):
        self.residual = None
        self.iteration = 0


class EncodedGradientsAccumulator:
    """In-process multi-worker exchange of encoded updates (parity:
    optimize/solvers/accumulation/EncodedGradientsAccumulator.java:33 +
    FancyBlockingQueue). Each worker stores its encoded message; every
    worker then applies everyone's updates locally. Synchronous two-phase
    use (store all → apply all) replaces the reference's lock-free queues —
    device-side math is identical."""

    def __init__(self, n_workers: int, n_params: int, **handler_kwargs):
        self.n_workers = n_workers
        self.n_params = n_params
        self.handlers = [EncodingHandler(**handler_kwargs)
                         for _ in range(n_workers)]
        self._pending = [[] for _ in range(n_workers)]

    def store_update(self, worker: int, grad):
        """Encode worker's gradient and broadcast to all others' queues
        (EncodingHandler.broadcastUpdates :210)."""
        msg = self.handlers[worker].encode(grad)
        for w in range(self.n_workers):
            self._pending[w].append(msg)
        return msg

    def apply_update(self, worker: int):
        """Sum of all pending decoded updates for this worker; clears its
        queue. Returns a dense f32[n_params] update vector."""
        dense = jnp.zeros((self.n_params,), jnp.float32)
        for idx, vals, _ in self._pending[worker]:
            dense = dense + threshold_decode(idx, vals, self.n_params)
        self._pending[worker] = []
        return dense
